"""gRPC services: the process boundary between modules.

Role-equivalent to the reference's tempo.proto services (SURVEY.md §2.6):
  - Pusher (distributor → ingester, PushBytes)
  - Querier (querier → ingester / frontend jobs → query workers:
    FindTraceByID, SearchRecent, SearchBlock, SearchTags, SearchTagValues)
  - OTLP TraceService/Export receiver: our Trace message is wire-compatible
    with ExportTraceServiceRequest (batches == resource_spans field 1), so
    standard OTLP gRPC exporters can push directly.

Stubs are hand-rolled over grpc generic handlers (no grpc_tools in this
image); client classes present the same duck-typed interface the
in-process wiring uses, so a multi-process deployment swaps transparently.
"""

from __future__ import annotations

import grpc

from tempo_tpu import tempopb
from tempo_tpu.api.params import InvalidArgument
from tempo_tpu.modules.distributor import RateLimited

SERVICE_PUSHER = "tempopb.Pusher"
SERVICE_QUERIER = "tempopb.Querier"
SERVICE_INGESTER_QUERIER = "tempopb.IngesterQuerier"
SERVICE_GENERATOR = "tempopb.MetricsGenerator"
OTLP_SERVICE = "opentelemetry.proto.collector.trace.v1.TraceService"
OTLP_EXPORT_METHOD = f"/{OTLP_SERVICE}/Export"


# ---------------------------------------------------------------------------
# server


def make_module_grpc_server(address: str, *, pusher=None, ingester=None,
                            querier=None, otlp_push=None,
                            frontend_dispatcher=None, generator=None,
                            max_workers: int = 16) -> grpc.Server:
    """gRPC server exposing only the services this process's modules back:

      pusher    — Ingester (Pusher service: distributor → ingester)
      ingester  — Ingester (IngesterQuerier service: querier replica reads)
      querier   — Querier (Querier service: frontend job dispatch)
      otlp_push — fn(tenant, batches) (OTLP receiver, distributor role)
      frontend_dispatcher — PullDispatcher (Frontend service: querier
                  workers pull jobs over the Process duplex stream)
      generator — MetricsGenerator (PushSpans: distributor span forward)
    """
    from concurrent import futures

    # each Frontend/Process pull stream PARKS one executor thread for its
    # whole lifetime (the servicer loop blocks on the job queue), so the
    # dispatch server needs headroom for queriers × parallelism streams
    # on top of ordinary unary traffic — threads are cheap, starved
    # worker streams are silent. The floor covers small deployments;
    # size AppConfig.frontend_grpc_max_workers above your fleet's
    # stream count for large ones.
    if frontend_dispatcher is not None:
        max_workers = max(max_workers, 128)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    handlers = []

    if frontend_dispatcher is not None:
        from tempo_tpu.modules.worker import make_frontend_pull_handler

        handlers.append(make_frontend_pull_handler(frontend_dispatcher))

    if pusher is not None:
        def push_bytes(request, context):
            pusher.push_bytes(_tenant_from(context), request)
            return tempopb.PushResponse()

        handlers.append(grpc.method_handlers_generic_handler(SERVICE_PUSHER, {
            "PushBytes": _unary(push_bytes, tempopb.PushBytesRequest,
                                tempopb.PushResponse),
            # the reference distributor calls PushBytesV2 for
            # current-encoding segments (distributor.go:390); both names
            # accept the same request here — this framework has no v1
            # segment history to migrate from
            "PushBytesV2": _unary(push_bytes, tempopb.PushBytesRequest,
                                  tempopb.PushResponse),
        }))

    if ingester is not None:
        def find_partials(request, context):
            resp = tempopb.PartialsResponse()
            resp.objects.extend(
                ingester.find_trace_by_id(_tenant_from(context),
                                          request.trace_id))
            return resp

        def ing_search(request, context):
            from tempo_tpu.search import SearchResults
            results = SearchResults.for_request(request)
            ingester.search(_tenant_from(context), request, results)
            return results.response()

        def ing_tags(request, context):
            resp = tempopb.SearchTagsResponse()
            resp.tag_names.extend(sorted(
                ingester.search_tags(_tenant_from(context))))
            return resp

        def ing_tag_values(request, context):
            tenant = _tenant_from(context)
            # per-tenant byte cap from this ingester's overrides (the
            # client stub drops its max_bytes arg on purpose)
            lim = ingester.overrides.limits(tenant).max_bytes_per_tag_values
            resp = tempopb.SearchTagValuesResponse()
            resp.tag_values.extend(sorted(
                ingester.search_tag_values(tenant, request.tag_name, lim)))
            return resp

        handlers.append(grpc.method_handlers_generic_handler(
            SERVICE_INGESTER_QUERIER, {
                "FindPartials": _unary(find_partials, tempopb.TraceByIDRequest,
                                       tempopb.PartialsResponse),
                "Search": _unary(ing_search, tempopb.SearchRequest,
                                 tempopb.SearchResponse),
                "SearchTags": _unary(ing_tags, tempopb.SearchTagsRequest,
                                     tempopb.SearchTagsResponse),
                "SearchTagValues": _unary(ing_tag_values,
                                          tempopb.SearchTagValuesRequest,
                                          tempopb.SearchTagValuesResponse),
            }))

    if querier is not None:
        def find_trace(request, context):
            return querier.find_trace_by_id(
                _tenant_from(context), request.trace_id,
                block_start=request.block_start, block_end=request.block_end,
                mode=request.query_mode or "all",
            )

        def search_recent(request, context):
            return querier.search_recent(_tenant_from(context), request)

        def search_block(request, context):
            return querier.search_block(request)

        def search_blocks(request, context):
            return querier.search_blocks(request)

        def search_tags(request, context):
            return querier.search_tags(_tenant_from(context))

        def search_tag_values(request, context):
            return querier.search_tag_values(_tenant_from(context),
                                             request.tag_name)

        handlers.append(grpc.method_handlers_generic_handler(SERVICE_QUERIER, {
            "FindTraceByID": _unary(find_trace, tempopb.TraceByIDRequest,
                                    tempopb.TraceByIDResponse),
            "SearchRecent": _unary(search_recent, tempopb.SearchRequest,
                                   tempopb.SearchResponse),
            "SearchBlock": _unary(search_block, tempopb.SearchBlockRequest,
                                  tempopb.SearchResponse),
            "SearchBlocks": _unary(search_blocks, tempopb.SearchBlocksRequest,
                                   tempopb.SearchResponse),
            "SearchTags": _unary(search_tags, tempopb.SearchTagsRequest,
                                 tempopb.SearchTagsResponse),
            "SearchTagValues": _unary(search_tag_values,
                                      tempopb.SearchTagValuesRequest,
                                      tempopb.SearchTagValuesResponse),
        }))

    if generator is not None:
        def push_spans(request, context):
            generator.push_spans(_tenant_from(context),
                                 list(request.batches))
            return tempopb.PushResponse()

        handlers.append(grpc.method_handlers_generic_handler(
            SERVICE_GENERATOR, {
                "PushSpans": _unary(push_spans, tempopb.PushSpansRequest,
                                    tempopb.PushResponse),
            }))

    if otlp_push is not None:
        def otlp_export(request, context):
            # request is wire-compatible ExportTraceServiceRequest; the empty
            # response reuses Trace (wire-compatible: zero fields set)
            otlp_push(_tenant_from(context), list(request.batches))
            return tempopb.Trace()

        handlers.append(grpc.method_handlers_generic_handler(OTLP_SERVICE, {
            "Export": _unary(otlp_export, tempopb.Trace, tempopb.Trace),
        }))

        # OpenCensus agent TraceService rides the same receiver port
        from .opencensus import make_oc_handler

        handlers.append(make_oc_handler(otlp_push, tenant_from=_tenant_from))

    server.add_generic_rpc_handlers(tuple(handlers))
    # keep the ACTUAL bound port on the server: an ephemeral bind
    # (":0") only knows its port here, and callers (ModuleProcess)
    # advertise it over gossip — the race-free alternative to probing
    # for a free port and hoping it is still free at bind time
    server.bound_port = server.add_insecure_port(address)
    return server


def make_grpc_server(app, address: str = "0.0.0.0:9095",
                     max_workers: int = 16) -> grpc.Server:
    """Single-binary server: all services, backed by the in-process App."""
    first_ingester = next(iter(app.ingesters.values()))
    return make_module_grpc_server(
        address,
        pusher=first_ingester,        # the server IS one ingester process
        ingester=first_ingester,
        querier=app.queriers[0],
        otlp_push=app.push,
        max_workers=max_workers,
    )


def _unary(fn, req_cls, resp_cls):
    from tempo_tpu.observability import tracing

    def traced(request, context):
        md = {k.lower(): v for k, v in (context.invocation_metadata() or ())}
        parent = tracing.extract_traceparent(md)
        with tracing.start_span(f"grpc {fn.__name__}",
                                kind=tracing.KIND_SERVER, parent=parent):
            try:
                return fn(request, context)
            except InvalidArgument as e:
                # client-data errors (invalid tenant id, bad arguments)
                # must be INVALID_ARGUMENT — UNKNOWN reads as retryable
                # to standard exporters, which would re-send the same
                # bad request forever
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except ValueError as e:
                # every OTHER ValueError here is server-side (corrupt
                # WAL entry, object framing): INTERNAL, never a verdict
                # on the request itself (ADVICE r4)
                context.abort(grpc.StatusCode.INTERNAL, str(e))
            except RateLimited as e:
                # tenant ingest pushback → RESOURCE_EXHAUSTED (retryable
                # to standard OTLP exporters, reference
                # distributor.go:305)
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))

    return grpc.unary_unary_rpc_method_handler(
        traced,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def _tenant_from(context) -> str:
    from .params import DEFAULT_TENANT, validate_tenant

    for k, v in context.invocation_metadata() or ():
        if k.lower() == "x-scope-orgid":
            return validate_tenant(v)  # ValueError → call fails, not a
            # path traversal into the block store
    return DEFAULT_TENANT


# ---------------------------------------------------------------------------
# clients (duck-typed like the in-process modules)


class _Base:
    def __init__(self, address: str, tenant: str | None = None):
        self.channel = grpc.insecure_channel(address)
        self.tenant = tenant

    def _md(self, tenant: str | None):
        from tempo_tpu.observability import tracing

        t = tenant or self.tenant
        md = tracing.inject_traceparent({})
        if t:
            md["x-scope-orgid"] = t
        return tuple(md.items())

    def _call(self, service, method, req, resp_cls, tenant=None):
        rpc = self.channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        return rpc(req, metadata=self._md(tenant))


class PusherClient(_Base):
    """Distributor-side stub: same interface as modules.Ingester."""

    def push_bytes(self, tenant: str, req: tempopb.PushBytesRequest) -> None:
        self._call(SERVICE_PUSHER, "PushBytes", req, tempopb.PushResponse,
                   tenant=tenant)


class MetricsGeneratorClient(_Base):
    """Distributor-side stub, duck-typed like MetricsGenerator (the
    in-process forwarder target): push_spans(tenant, batches)."""

    def push_spans(self, tenant: str, batches) -> None:
        req = tempopb.PushSpansRequest()
        req.batches.extend(batches)
        self._call(SERVICE_GENERATOR, "PushSpans", req,
                   tempopb.PushResponse, tenant=tenant)


class IngesterClient(_Base):
    """Querier-side replica-read stub, duck-typed like modules.Ingester:
    find returns raw partial objects, search merges into the caller's
    SearchResults funnel — so Querier's combine/merge logic is identical
    for in-process and remote replicas."""

    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> list[bytes]:
        req = tempopb.TraceByIDRequest(trace_id=trace_id)
        resp = self._call(SERVICE_INGESTER_QUERIER, "FindPartials", req,
                          tempopb.PartialsResponse, tenant=tenant)
        return list(resp.objects)

    def search(self, tenant: str, req, results) -> None:
        resp = self._call(SERVICE_INGESTER_QUERIER, "Search", req,
                          tempopb.SearchResponse, tenant=tenant)
        for t in resp.traces:
            results.add(t)
        m = results.metrics
        m.inspected_traces += resp.metrics.inspected_traces
        m.inspected_bytes += resp.metrics.inspected_bytes
        m.inspected_blocks += resp.metrics.inspected_blocks
        m.skipped_blocks += resp.metrics.skipped_blocks
        m.truncated_entries += resp.metrics.truncated_entries
        m.failed_blocks += resp.metrics.failed_blocks

    def search_tags(self, tenant: str) -> set:
        resp = self._call(SERVICE_INGESTER_QUERIER, "SearchTags",
                          tempopb.SearchTagsRequest(),
                          tempopb.SearchTagsResponse, tenant=tenant)
        return set(resp.tag_names)

    def search_tag_values(self, tenant: str, tag: str,
                          max_bytes: int = 1 << 20) -> set:
        # byte cap is enforced server-side from the ingester's overrides
        resp = self._call(SERVICE_INGESTER_QUERIER, "SearchTagValues",
                          tempopb.SearchTagValuesRequest(tag_name=tag),
                          tempopb.SearchTagValuesResponse, tenant=tenant)
        return set(resp.tag_values)


class QuerierClient(_Base):
    def find_trace_by_id(self, tenant, trace_id, block_start="", block_end="",
                         mode="all") -> tempopb.TraceByIDResponse:
        req = tempopb.TraceByIDRequest(
            trace_id=trace_id, block_start=block_start,
            block_end=block_end, query_mode=mode,
        )
        return self._call(SERVICE_QUERIER, "FindTraceByID", req,
                          tempopb.TraceByIDResponse, tenant=tenant)

    def search_recent(self, tenant, req) -> tempopb.SearchResponse:
        return self._call(SERVICE_QUERIER, "SearchRecent", req,
                          tempopb.SearchResponse, tenant=tenant)

    def search_block(self, req) -> tempopb.SearchResponse:
        return self._call(SERVICE_QUERIER, "SearchBlock", req,
                          tempopb.SearchResponse)

    def search_blocks(self, req) -> tempopb.SearchResponse:
        return self._call(SERVICE_QUERIER, "SearchBlocks", req,
                          tempopb.SearchResponse)

    def search_tags(self, tenant) -> tempopb.SearchTagsResponse:
        return self._call(SERVICE_QUERIER, "SearchTags",
                          tempopb.SearchTagsRequest(),
                          tempopb.SearchTagsResponse, tenant=tenant)

    def search_tag_values(self, tenant, tag) -> tempopb.SearchTagValuesResponse:
        return self._call(SERVICE_QUERIER, "SearchTagValues",
                          tempopb.SearchTagValuesRequest(tag_name=tag),
                          tempopb.SearchTagValuesResponse, tenant=tenant)
