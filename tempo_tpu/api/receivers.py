"""Additional ingest receivers.

Role-equivalent to the reference's modules/distributor/receiver shim
(embedding otel-collector receiver factories for otlp/jaeger/zipkin/
opencensus/kafka/pubsub-lite — shim.go:75-138). Implemented natively:

  - OTLP gRPC: api/grpc_service.py (wire-compatible Trace, zero shim)
  - OTLP HTTP: POST /v1/traces, protobuf body (this module)
  - Zipkin v2 JSON: POST /api/v2/spans (this module)
  - Jaeger: thrift UDP agent + collector endpoint (api/jaeger.py)
  - Kafka: from-scratch wire-protocol consumer (api/kafka.py)
  - pubsub-lite [Shopify fork extra]: the Kafka consumer pointed at
    Pub/Sub Lite's Kafka-compatible endpoint (api/kafka.py; TLS —
    gated in this zero-egress environment)
  - OpenCensus: agent TraceService bidi stream with OC→OTLP
    translation (api/opencensus.py), on the same gRPC port as OTLP
"""

from __future__ import annotations

import json

from tempo_tpu import tempopb
from tempo_tpu.utils.ids import pad_trace_id

_ZIPKIN_KIND = {
    "CLIENT": tempopb.Span.SPAN_KIND_CLIENT,
    "SERVER": tempopb.Span.SPAN_KIND_SERVER,
    "PRODUCER": tempopb.Span.SPAN_KIND_PRODUCER,
    "CONSUMER": tempopb.Span.SPAN_KIND_CONSUMER,
}


def zipkin_json_to_batches(body: bytes) -> list:
    """Zipkin v2 JSON span array → list[ResourceSpans], one batch per
    local service name."""
    spans = json.loads(body)
    if not isinstance(spans, list):
        raise ValueError("zipkin v2 body must be a JSON array of spans")
    by_service: dict[str, tempopb.ResourceSpans] = {}
    for z in spans:
        svc = ((z.get("localEndpoint") or {}).get("serviceName")) or "unknown"
        rs = by_service.get(svc)
        if rs is None:
            rs = by_service[svc] = tempopb.ResourceSpans()
            kv = rs.resource.attributes.add()
            kv.key = "service.name"
            kv.value.string_value = svc
            rs.scope_spans.add().scope.name = "zipkin-receiver"
        s = rs.scope_spans[0].spans.add()
        s.trace_id = pad_trace_id(bytes.fromhex(z["traceId"]))
        s.span_id = bytes.fromhex(z["id"])[:8].rjust(8, b"\x00")
        if z.get("parentId"):
            s.parent_span_id = bytes.fromhex(z["parentId"])[:8].rjust(8, b"\x00")
        s.name = z.get("name", "")
        s.kind = _ZIPKIN_KIND.get(z.get("kind", ""), tempopb.Span.SPAN_KIND_UNSPECIFIED)
        ts_us = int(z.get("timestamp", 0))
        dur_us = int(z.get("duration", 0))
        s.start_time_unix_nano = ts_us * 1000
        s.end_time_unix_nano = (ts_us + dur_us) * 1000
        for k, v in (z.get("tags") or {}).items():
            kv = s.attributes.add()
            kv.key = k
            kv.value.string_value = str(v)
        if (z.get("tags") or {}).get("error"):
            s.status.code = tempopb.Status.STATUS_CODE_ERROR
    return list(by_service.values())


def otlp_http_to_batches(body: bytes) -> list:
    """OTLP/HTTP protobuf ExportTraceServiceRequest → batches (our Trace
    is wire-compatible)."""
    t = tempopb.Trace()
    t.ParseFromString(body)
    return list(t.batches)
