"""Kafka ingest receiver — a from-scratch wire-protocol client.

Role-equivalent to the reference's embedded otel-collector kafka
receiver (modules/distributor/receiver/shim.go:75-138 lists `kafka`
among the receiver factories): consume trace payloads from a Kafka
topic and push them into the distributor. The reference links the
Sarama-based collector receiver; here the protocol is implemented
directly on the stdlib socket layer — no client library — covering the
subset a consumer/producer needs:

  ApiVersions(v0), Metadata(v1), ListOffsets(v1), Fetch(v4),
  Produce(v3), FindCoordinator(v0), OffsetCommit(v2), OffsetFetch(v1)

with RecordBatch v2 (magic=2) encode/decode including CRC32C
(Castagnoli) integrity checks and zigzag-varint record fields.

Group membership is static-with-liveness: each receiver instance is
configured with (member_index, members) and owns partitions by
deterministic split — but members heartbeat THROUGH the group
coordinator (OffsetCommit on a reserved synthetic partition per member,
``_HEARTBEAT_PART_BASE + index``; the offsets log is a keyed KV store,
so committing to a partition the topic doesn't have is valid on any
Kafka), and the split is computed over the members whose heartbeat is
fresh: ``owner(p) = live[p % len(live)]``. With every member alive this
is exactly the static ``partition % members`` split; when one dies, the
survivors adopt its partitions within ``liveness_timeout_s``, resuming
from its committed offsets — the collector's consumer-group rebalance
(shim.go:75-138 role) without the join/sync-group protocol. A revived
member reclaims its share on its next heartbeat; the handover window is
at-least-once (both ends may briefly fetch the same partition), which
trace combining downstream already dedupes.

Google Cloud Pub/Sub Lite (the Shopify fork's extra receiver,
shim.go:10,97) exposes a Kafka-compatible endpoint
(kafka.pubsublite.googleapis.com:443, TLS + SASL); the `pubsub-lite`
receiver here is this same consumer pointed at that endpoint with
``tls: true`` — gated in this zero-egress environment.

Message encodings: ``otlp_proto`` (default — ExportTraceServiceRequest
bytes, the collector's default for topic ``otlp_spans``) and
``zipkin_json`` (api/receivers.py translation).
"""

from __future__ import annotations

import io
import socket
import ssl
import struct
import threading
import time

from tempo_tpu.observability.metrics import Counter

_records_total = Counter(
    "tempo_distributor_kafka_records_total", "Kafka records consumed"
)
_decode_errors_total = Counter(
    "tempo_distributor_kafka_decode_errors_total", "Kafka messages that failed decode"
)
_poll_errors_total = Counter(
    "tempo_distributor_kafka_errors_total", "Kafka consumer poll errors"
)

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — RecordBatch v2 integrity. The native slice-by-8
# (ops/native.py tt_crc32c, ~1 GB/s) carries the fetch hot path; the
# table loop below is the no-toolchain fallback.

# reserved synthetic partition range for member heartbeats: far above
# any real topic's partition count, so the offsets-log keys never
# collide with data partitions
_HEARTBEAT_PART_BASE = 1 << 20

_CRC32C_POLY = 0x82F63B78
_crc32c_table = []
for _n in range(256):
    _c = _n
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _crc32c_table.append(_c)


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _crc32c_table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    from tempo_tpu.ops import native

    if native.available():
        return native.crc32c(data, crc)
    return _crc32c_py(data, crc)


# ---------------------------------------------------------------------------
# Primitive wire codecs (big-endian) and zigzag varints.


class Writer:
    def __init__(self):
        self.buf = io.BytesIO()

    def i8(self, v):
        self.buf.write(struct.pack(">b", v))

    def i16(self, v):
        self.buf.write(struct.pack(">h", v))

    def i32(self, v):
        self.buf.write(struct.pack(">i", v))

    def u32(self, v):
        self.buf.write(struct.pack(">I", v))

    def i64(self, v):
        self.buf.write(struct.pack(">q", v))

    def string(self, s: str | None):
        if s is None:
            self.i16(-1)
        else:
            b = s.encode()
            self.i16(len(b))
            self.buf.write(b)

    def bytes_(self, b: bytes | None):
        if b is None:
            self.i32(-1)
        else:
            self.i32(len(b))
            self.buf.write(b)

    def varint(self, v: int):
        # zigzag
        z = (v << 1) ^ (v >> 63) if v < 0 else v << 1
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                self.buf.write(bytes([b | 0x80]))
            else:
                self.buf.write(bytes([b]))
                return

    def raw(self, b: bytes):
        self.buf.write(b)

    def getvalue(self) -> bytes:
        return self.buf.getvalue()


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n):
        if self.pos + n > len(self.data):
            raise EOFError("kafka: short buffer")
        v = self.data[self.pos : self.pos + n]
        self.pos += n
        return v

    def i8(self):
        return struct.unpack(">b", self._take(1))[0]

    def i16(self):
        return struct.unpack(">h", self._take(2))[0]

    def i32(self):
        return struct.unpack(">i", self._take(4))[0]

    def u32(self):
        return struct.unpack(">I", self._take(4))[0]

    def i64(self):
        return struct.unpack(">q", self._take(8))[0]

    def string(self):
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self):
        n = self.i32()
        return None if n < 0 else self._take(n)

    def varint(self) -> int:
        shift = 0
        z = 0
        while True:
            b = self._take(1)[0]
            z |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        return (z >> 1) ^ -(z & 1)  # un-zigzag

    def remaining(self) -> int:
        return len(self.data) - self.pos


# ---------------------------------------------------------------------------
# RecordBatch v2.


class CorruptBatchError(ValueError):
    """A record batch failed its CRC32C check — distinct from protocol
    desync errors so poison-skip logic never misfires on those.

    `next_offset` is the first offset after the corrupt batch (from the
    batch header's lastOffsetDelta, sanity-bounded) so the consumer can
    skip the WHOLE batch in one step instead of grinding through one
    fetch+CRC cycle per record (ADVICE r1 #3)."""

    def __init__(self, msg: str, next_offset: int | None = None):
        super().__init__(msg)
        self.next_offset = next_offset


def encode_record_batch(
    records: list[tuple[bytes | None, bytes]],
    base_offset: int = 0,
    timestamp_ms: int | None = None,
) -> bytes:
    """records = [(key, value)] → one magic-2 batch."""
    ts = int(time.time() * 1000) if timestamp_ms is None else timestamp_ms
    body = Writer()
    for i, (key, value) in enumerate(records):
        rec = Writer()
        rec.i8(0)  # attributes
        rec.varint(0)  # timestampDelta
        rec.varint(i)  # offsetDelta
        if key is None:
            rec.varint(-1)
        else:
            rec.varint(len(key))
            rec.raw(key)
        rec.varint(len(value))
        rec.raw(value)
        rec.varint(0)  # headers
        rb = rec.getvalue()
        body.varint(len(rb))
        body.raw(rb)

    crc_part = Writer()
    crc_part.i16(0)  # attributes: no compression
    crc_part.i32(len(records) - 1)  # lastOffsetDelta
    crc_part.i64(ts)  # firstTimestamp
    crc_part.i64(ts)  # maxTimestamp
    crc_part.i64(-1)  # producerId
    crc_part.i16(-1)  # producerEpoch
    crc_part.i32(-1)  # baseSequence
    crc_part.i32(len(records))
    crc_part.raw(body.getvalue())
    crc_bytes = crc_part.getvalue()

    batch = Writer()
    batch.i64(base_offset)
    batch.i32(4 + 1 + 4 + len(crc_bytes))  # batchLength: from leaderEpoch on
    batch.i32(-1)  # partitionLeaderEpoch
    batch.i8(2)  # magic
    batch.u32(crc32c(crc_bytes))
    batch.raw(crc_bytes)
    return batch.getvalue()


def decode_record_batches(data: bytes, expect_base: int | None = None
                          ) -> list[tuple[int, bytes | None, bytes]]:
    """record set (possibly several batches, possibly truncated tail) →
    [(offset, key, value)]. A truncated final batch — normal in Kafka
    fetch responses — is silently dropped. `expect_base` is the offset
    the caller fetched at: batch-skip math is only trusted when the
    corrupt batch's baseOffset is plausibly anchored to it (baseOffset
    lives OUTSIDE the CRC'd region, so it can itself be the garbage).

    A CRC-corrupt batch raises CorruptBatchError ONLY when no records
    were decoded before it; otherwise the good prefix is returned so the
    caller can deliver + commit it first and hit the corrupt batch at
    the start of its next fetch (poison-skip without losing the valid
    records that shared the response)."""
    out = []
    r = Reader(data)
    while r.remaining() >= 61:  # minimal batch header
        batch_start_records = len(out)
        try:
            base_offset = r.i64()
            batch_len = r.i32()
            if batch_len < 49:
                # batchLen lives OUTSIDE the CRC'd region; a negative or
                # sub-header value is garbage, and `r.pos = end` with
                # end <= the batch's own start would REWIND the cursor —
                # re-parsing the same bytes forever (fuzz-found hang)
                if out:
                    return out
                raise CorruptBatchError(
                    "kafka: implausible batch length", next_offset=None)
            if r.remaining() < batch_len:
                break  # truncated tail
            end = r.pos + batch_len
            r.i32()  # leader epoch
            magic = r.i8()
            crc = r.u32()
            crc_body = r.data[r.pos : end]
            if magic != 2:
                r.pos = end
                continue
            if crc32c(crc_body) != crc:
                if out:
                    return out  # deliver the good prefix first
                # lastOffsetDelta and the record count both live in the
                # corrupt body, so either could itself be the flipped
                # bits. Trust the delta only when it is SELF-CONSISTENT
                # (delta == count-1, the invariant producers write) and
                # within OFFSET-domain bounds; otherwise skip a single
                # offset — over-skipping would silently drop valid
                # batches. The bound is how many records this batch
                # could plausibly hold: an uncompressed record encodes
                # to >= 7 bytes, so batchLen/7 records. (batchLen itself
                # is a BYTE count — comparing offsets against it, as a
                # naive guard would, is far too permissive since
                # bytes >> records.) Compression can pack tighter than
                # 7 B/record, but a too-TIGHT bound only degrades to the
                # safe single-offset skip; a too-loose one loses data.
                # the header prefix (baseOffset, batchLen) is NOT CRC'd
                # either: anchor it to the offset the caller requested (a
                # broker answers with the batch containing that offset)
                # before trusting any skip math derived from it
                # 49 = the non-record bytes batchLen covers (leaderEpoch
                # i32 + magic + crc u32 + the 40-byte CRC'd header before
                # the records array) — including them would loosen the
                # bound by up to 7 offsets, enough for a self-consistent
                # corrupt delta to land inside the NEXT valid batch
                max_records = max(1, (batch_len - 49) // 7)
                anchored = (expect_base is None
                            or base_offset <= expect_base
                            < base_offset + max_records)
                next_off = None
                if anchored:
                    next_off = base_offset + 1
                    try:
                        rr = Reader(crc_body)
                        rr.i16()  # attributes
                        delta = rr.i32()
                        rr.i64(); rr.i64(); rr.i64()  # ts, ts, producerId
                        rr.i16(); rr.i32()  # producerEpoch, baseSequence
                        count = rr.i32()
                        if 0 <= delta < max_records and delta == count - 1:
                            next_off = base_offset + delta + 1
                    except EOFError:
                        pass
                raise CorruptBatchError(
                    "kafka: record batch crc32c mismatch",
                    next_offset=next_off)
            r.i16()  # attributes
            r.i32()  # lastOffsetDelta
            r.i64()  # firstTimestamp
            r.i64()  # maxTimestamp
            r.i64()  # producerId
            r.i16()  # producerEpoch
            r.i32()  # baseSequence
            n = r.i32()
            for _ in range(n):
                rec_len = r.varint()
                rec_end = r.pos + rec_len
                r.i8()  # attributes
                r.varint()  # tsDelta
                off_delta = r.varint()
                klen = r.varint()
                key = bytes(r._take(klen)) if klen >= 0 else None
                vlen = r.varint()
                value = bytes(r._take(vlen)) if vlen >= 0 else b""
                r.pos = rec_end  # skip headers
                out.append((base_offset + off_delta, key, value))
            r.pos = end
        except EOFError:
            # half-decoded records from the torn batch are NOT valid
            # output — returning them would deliver garbage and commit
            # offsets past bytes that never decoded
            del out[batch_start_records:]
            break
        except CorruptBatchError:
            raise  # the CRC path's own, fully-annotated error
        except (struct.error, ValueError, IndexError, OverflowError):
            # structurally malformed batch whose corruption dodged the
            # CRC (the length prefix and baseOffset live OUTSIDE the
            # CRC'd region): same policy as a CRC mismatch — drop this
            # batch's half-decoded records, deliver any good PRIOR
            # batches first, else surface the documented error so the
            # consumer's poison-skip engages instead of refetching the
            # same offset forever
            del out[batch_start_records:]
            if out:
                return out
            raise CorruptBatchError("kafka: malformed record batch "
                                    "structure", next_offset=None)
    return out


# ---------------------------------------------------------------------------
# Connection: framed synchronous request/response.

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_SASL_HANDSHAKE = 17
API_API_VERSIONS = 18
API_SASL_AUTHENTICATE = 36

ERR_OFFSET_OUT_OF_RANGE = 1


class BrokerConnection:
    def __init__(
        self, host: str, port: int, client_id="tempo-tpu", tls=False, timeout=10.0,
        sasl: tuple[str, str] | None = None,
    ):
        self.host, self.port = host, port
        self.client_id = client_id
        self._corr = 0
        sock = socket.create_connection((host, port), timeout=timeout)
        if tls:
            sock = ssl.create_default_context().wrap_socket(sock, server_hostname=host)
        self.sock = sock
        self._lock = threading.Lock()
        if sasl is not None:
            self._sasl_plain(*sasl)

    def _sasl_plain(self, username: str, password: str) -> None:
        """SASL/PLAIN (SaslHandshake v1 + SaslAuthenticate v0) — what
        Pub/Sub Lite's Kafka endpoint and most managed Kafkas require."""
        w = Writer()
        w.string("PLAIN")
        r = self.request(API_SASL_HANDSHAKE, 1, w.getvalue())
        err = r.i16()
        if err:
            raise KafkaError(err, "sasl_handshake")
        w = Writer()
        w.bytes_(b"\x00" + username.encode() + b"\x00" + password.encode())
        r = self.request(API_SASL_AUTHENTICATE, 0, w.getvalue())
        err = r.i16()
        msg = r.string()
        if err:
            raise KafkaError(err, f"sasl_authenticate: {msg}")

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

    def request(self, api_key: int, api_version: int, body: bytes) -> Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            hdr = Writer()
            hdr.i16(api_key)
            hdr.i16(api_version)
            hdr.i32(corr)
            hdr.string(self.client_id)
            payload = hdr.getvalue() + body
            self.sock.sendall(struct.pack(">i", len(payload)) + payload)
            resp = self._read_frame()
        r = Reader(resp)
        rcorr = r.i32()
        if rcorr != corr:
            # desync: this connection can never be trusted again
            raise ConnectionError(f"kafka: correlation mismatch {rcorr} != {corr}")
        return r

    def _read_frame(self) -> bytes:
        size_b = self._recvn(4)
        (size,) = struct.unpack(">i", size_b)
        return self._recvn(size)

    def _recvn(self, n: int) -> bytes:
        chunks = []
        while n:
            c = self.sock.recv(n)
            if not c:
                raise ConnectionError("kafka: broker closed connection")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)


# ---------------------------------------------------------------------------
# Client: metadata + offsets + fetch + produce + group offsets.


class KafkaError(Exception):
    def __init__(self, code: int, where: str):
        super().__init__(f"kafka error {code} in {where}")
        self.code = code


class KafkaClient:
    """Minimal cluster client. Connections are opened lazily per broker
    node; the bootstrap connection serves metadata."""

    def __init__(
        self, brokers: list[str], client_id="tempo-tpu", tls=False, timeout=10.0,
        sasl: tuple[str, str] | None = None, metadata_ttl_s: float = 30.0,
    ):
        self.bootstrap = [self._hostport(b) for b in brokers]
        self.client_id = client_id
        self.tls = tls
        self.timeout = timeout
        self.sasl = sasl
        self.metadata_ttl_s = metadata_ttl_s
        self._conns: dict[tuple[str, int], BrokerConnection] = {}
        self._nodes: dict[int, tuple[str, int]] = {}
        self._meta_cache: dict[tuple, tuple[float, dict]] = {}
        self._coord_cache: dict[str, tuple[str, int]] = {}

    @staticmethod
    def _hostport(s: str) -> tuple[str, int]:
        host, _, port = s.rpartition(":")
        return host, int(port)

    def _connect(self, addr: tuple[str, int]) -> BrokerConnection:
        conn = self._conns.get(addr)
        if conn is None:
            conn = BrokerConnection(
                addr[0], addr[1], self.client_id, self.tls, self.timeout, self.sasl
            )
            self._conns[addr] = conn
        return conn

    def close(self):
        for c in self._conns.values():
            c.close()
        self._conns.clear()
        self._meta_cache.clear()
        self._coord_cache.clear()

    def _req(self, conn: BrokerConnection, api_key: int, version: int, body: bytes) -> Reader:
        """Request with dead-connection eviction: a socket failure closes
        and drops the cached connection so the next call reconnects,
        instead of retrying a dead socket forever."""
        try:
            return conn.request(api_key, version, body)
        except (OSError, EOFError, ConnectionError):
            for addr, c in list(self._conns.items()):
                if c is conn:
                    del self._conns[addr]
            conn.close()
            self._meta_cache.clear()
            self._coord_cache.clear()
            raise

    def _any(self) -> BrokerConnection:
        last = None
        for addr in self.bootstrap:
            try:
                return self._connect(addr)
            except OSError as e:
                last = e
        raise ConnectionError(f"kafka: no bootstrap broker reachable: {last}")

    def node(self, node_id: int) -> BrokerConnection:
        addr = self._nodes.get(node_id)
        return self._connect(addr) if addr else self._any()

    # -- Metadata (v1), TTL-cached — standard clients refresh metadata on
    # an interval or on error, not per poll
    def metadata(self, topics: list[str], force: bool = False) -> dict[str, dict[int, int]]:
        """topic → {partition → leader node id}; also learns broker addrs."""
        key = tuple(sorted(topics))
        cached = self._meta_cache.get(key)
        if cached and not force and time.monotonic() - cached[0] < self.metadata_ttl_s:
            return cached[1]
        w = Writer()
        w.i32(len(topics))
        for t in topics:
            w.string(t)
        r = self._req(self._any(), API_METADATA, 1, w.getvalue())
        for _ in range(r.i32()):  # brokers
            node_id = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            self._nodes[node_id] = (host, port)
        r.i32()  # controller id
        out: dict[str, dict[int, int]] = {}
        for _ in range(r.i32()):  # topics
            err = r.i16()
            name = r.string()
            r.i8()  # is_internal
            parts = {}
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                if perr == 0:
                    parts[pid] = leader
            if err == 0:
                out[name] = parts
        # only cache complete answers — an errored/auto-creating topic or
        # empty partition set must be re-queried next poll, not frozen
        # for a TTL
        if all(out.get(t) for t in topics):
            self._meta_cache[key] = (time.monotonic(), out)
        return out

    def invalidate(self) -> None:
        """Drop cached metadata + coordinator (after a KafkaError, e.g.
        NOT_LEADER after a failover, so the next poll re-discovers)."""
        self._meta_cache.clear()
        self._coord_cache.clear()

    # -- ListOffsets (v1): timestamp -2 earliest, -1 latest
    def list_offset(self, topic: str, partition: int, timestamp: int, leader: int) -> int:
        w = Writer()
        w.i32(-1)  # replica
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.i64(timestamp)
        r = self._req(self.node(leader), API_LIST_OFFSETS, 1, w.getvalue())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # timestamp
                off = r.i64()
                if err:
                    raise KafkaError(err, "list_offsets")
                return off
        raise ValueError("kafka: empty list_offsets response")

    # -- Fetch (v4)
    def fetch(
        self, topic: str, partition: int, offset: int, leader: int,
        max_wait_ms=500, min_bytes=1, max_bytes=8 << 20,
    ) -> tuple[list[tuple[int, bytes | None, bytes]], int]:
        """→ (records, high_watermark)."""
        w = Writer()
        w.i32(-1)  # replica
        w.i32(max_wait_ms)
        w.i32(min_bytes)
        w.i32(max_bytes)
        w.i8(0)  # isolation: read_uncommitted
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.i64(offset)
        w.i32(max_bytes)
        r = self._req(self.node(leader), API_FETCH, 4, w.getvalue())
        r.i32()  # throttle
        records, hw = [], -1
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                hw = r.i64()
                r.i64()  # last stable offset
                n_aborted = r.i32()
                for _ in range(max(0, n_aborted)):
                    r.i64()
                    r.i64()
                record_set = r.bytes_() or b""
                if err:
                    raise KafkaError(err, "fetch")
                # brokers return whole batches; drop records below the
                # requested offset (standard client behavior)
                records = [
                    rec for rec in decode_record_batches(
                        record_set, expect_base=offset)
                    if rec[0] >= offset
                ]
        return records, hw

    # -- Produce (v3)
    def produce(self, topic: str, partition: int, records: list[tuple[bytes | None, bytes]], leader: int | None = None) -> int:
        if leader is None:
            leader = self.metadata([topic])[topic][partition]
        batch = encode_record_batch(records)
        w = Writer()
        w.string(None)  # transactional id
        w.i16(-1)  # acks: all
        w.i32(10_000)
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.bytes_(batch)
        r = self._req(self.node(leader), API_PRODUCE, 3, w.getvalue())
        base = -1
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                base = r.i64()
                r.i64()  # log append time
                if err:
                    raise KafkaError(err, "produce")
        r.i32()  # throttle
        return base

    # -- Group offsets via coordinator (cached; re-discovered on error)
    def coordinator(self, group: str) -> BrokerConnection:
        addr = self._coord_cache.get(group)
        if addr is not None:
            try:
                return self._connect(addr)
            except OSError:
                del self._coord_cache[group]
        w = Writer()
        w.string(group)
        r = self._req(self._any(), API_FIND_COORDINATOR, 0, w.getvalue())
        err = r.i16()
        node_id = r.i32()
        host = r.string()
        port = r.i32()
        if err:
            raise KafkaError(err, "find_coordinator")
        self._nodes[node_id] = (host, port)
        self._coord_cache[group] = (host, port)
        return self._connect((host, port))

    def commit_offset(self, group: str, topic: str, partition: int, offset: int):
        w = Writer()
        w.string(group)
        w.i32(-1)  # generation
        w.string("")  # member id
        w.i64(-1)  # retention
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        w.i64(offset)
        w.string(None)  # metadata
        r = self._req(self.coordinator(group), API_OFFSET_COMMIT, 2, w.getvalue())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                if err:
                    raise KafkaError(err, "offset_commit")

    def fetch_offset(self, group: str, topic: str, partition: int) -> int:
        """Committed offset, or -1 if none."""
        w = Writer()
        w.string(group)
        w.i32(1)
        w.string(topic)
        w.i32(1)
        w.i32(partition)
        r = self._req(self.coordinator(group), API_OFFSET_FETCH, 1, w.getvalue())
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                off = r.i64()
                r.string()  # metadata
                err = r.i16()
                if err:
                    raise KafkaError(err, "offset_fetch")
                return off
        return -1


# ---------------------------------------------------------------------------
# Receiver: consume loop → distributor push.


class KafkaReceiverConfig:
    def __init__(
        self,
        brokers: list[str],
        topic: str = "otlp_spans",
        group_id: str = "tempo-tpu",
        encoding: str = "otlp_proto",  # or zipkin_json
        tenant: str = "single-tenant",
        member_index: int = 0,
        members: int = 1,
        poll_interval_s: float = 0.2,
        tls: bool = False,
        start_at: str = "latest",  # or earliest
        sasl_username: str | None = None,
        sasl_password: str | None = None,
        heartbeat_interval_s: float = 2.0,  # 0 disables liveness
        liveness_timeout_s: float = 10.0,
    ):
        self.brokers = brokers
        self.topic = topic
        self.group_id = group_id
        self.encoding = encoding
        self.tenant = tenant
        self.member_index = member_index
        self.members = members
        self.poll_interval_s = poll_interval_s
        self.tls = tls
        self.start_at = start_at
        self.heartbeat_interval_s = heartbeat_interval_s
        self.liveness_timeout_s = liveness_timeout_s
        if (sasl_username is None) != (sasl_password is None):
            raise ValueError(
                "kafka receiver: sasl_username and sasl_password must be "
                "set together (check env substitution for the missing one)"
            )
        self.sasl = (sasl_username, sasl_password) if sasl_username is not None else None


def decode_message(encoding: str, value: bytes) -> list:
    """message value → list[ResourceSpans]."""
    if encoding == "otlp_proto":
        from .receivers import otlp_http_to_batches

        return otlp_http_to_batches(value)
    if encoding == "zipkin_json":
        from .receivers import zipkin_json_to_batches

        return zipkin_json_to_batches(value)
    raise ValueError(f"kafka: unknown encoding {encoding!r}")


class KafkaReceiver:
    """Background consumer pushing decoded batches into `push_fn(tenant,
    batches)`. Offsets are committed after a successful push, so a crash
    re-delivers (at-least-once) — trace combining downstream dedupes."""

    def __init__(self, cfg: KafkaReceiverConfig, push_fn):
        self.cfg = cfg
        self.push_fn = push_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.client = KafkaClient(cfg.brokers, tls=cfg.tls, sasl=cfg.sasl)
        self._offsets: dict[int, int] = {}
        self._reset_parts: set[int] = set()
        self._last_beat = 0.0
        # seeded with the full roster, NOT []: the keep-previous-view
        # fallback for coordinator outages must have a sane "previous
        # view" even when the outage hits the very first sweep
        self._live: list[int] = list(range(cfg.members))
        self._live_checked = 0.0
        self._started = time.time()
        # peer index → (last heartbeat value, monotonic time it changed)
        self._peer_seen: dict[int, tuple[int, float]] = {}
        self._warned_blind = False  # one blind-liveness warning per life
        self.records_consumed = 0
        self.decode_errors = 0
        self.offset_resets = 0
        from tempo_tpu.observability.log import get_logger

        self._log = get_logger("tempo_tpu.kafka")

    def _heartbeat_if_due(self) -> None:
        """Publish liveness through the group coordinator: commit the
        current unix time as the "offset" of this member's reserved
        synthetic partition. Survivable by construction — a failed
        heartbeat just ages us toward the timeout."""
        c = self.cfg
        if c.members <= 1 or c.heartbeat_interval_s <= 0:
            return
        now = time.time()
        if now - self._last_beat < c.heartbeat_interval_s:
            return
        try:
            # milliseconds: the offset is an int64, and whole seconds
            # would alias away sub-second liveness timeouts
            self.client.commit_offset(
                c.group_id, c.topic,
                _HEARTBEAT_PART_BASE + c.member_index, int(now * 1000))
            self._last_beat = now
        except Exception:  # noqa: BLE001 — next beat retries
            # a moved coordinator must not strand an IDLE member's
            # heartbeats until its next data commit fails: re-discover
            # now, or peers declare us dead and adopt our partitions
            self.client.invalidate()

    def _live_members(self) -> list[int]:
        """Member indices with a fresh heartbeat (self always counts).
        Cached at heartbeat cadence so a poll round costs at most one
        liveness sweep, not one per partition."""
        c = self.cfg
        if c.members <= 1 or c.heartbeat_interval_s <= 0:
            return list(range(c.members))
        now = time.time()
        # startup grace: until one full timeout has passed, assume the
        # configured roster is alive — peers that start seconds apart
        # must come up in the static split, not thrash partitions
        if now - self._started < c.liveness_timeout_s:
            return list(range(c.members))
        if self._live and now - self._live_checked < c.heartbeat_interval_s:
            return self._live
        live = []
        mono = time.monotonic()
        for i in range(c.members):
            if i == c.member_index:
                live.append(i)
                continue
            try:
                ts_ms = self.client.fetch_offset(
                    c.group_id, c.topic, _HEARTBEAT_PART_BASE + i)
            except Exception:  # noqa: BLE001 — coordinator unreachable
                # UNKNOWN is not DEAD: during a coordinator outage every
                # member's sweep fails for every peer at once — defaulting
                # to "all dead" would have the whole group consume the
                # whole topic concurrently. Keep the previous view until
                # the coordinator answers again.
                if i in self._live:
                    live.append(i)
                continue
            if ts_ms < 0:
                continue  # never heartbeated
            # liveness = the peer's heartbeat VALUE advanced recently on
            # OUR monotonic clock — never a cross-host wall-clock
            # comparison, which a few seconds of skew would turn into a
            # permanent false death (code-review r4)
            prev = self._peer_seen.get(i)
            if prev is None or prev[0] != ts_ms:
                self._peer_seen[i] = (ts_ms, mono)
                live.append(i)
            elif mono - prev[1] <= c.liveness_timeout_s:
                live.append(i)
        if c.members > 1 and live == [c.member_index] \
                and self._last_beat > 0:
            # Liveness says "everyone but me is gone". Before adopting
            # the whole topic, distinguish dead peers from a BLIND
            # readback path (broker that never serves the synthetic
            # heartbeat partition, or offset state wiped mid-flight) by
            # reading back our OWN heartbeat: if that is unreadable
            # despite our commits succeeding, every member is reaching
            # this same conclusion at once — silent group-wide duplicate
            # consumption (ADVICE r4). Hold the static split and warn.
            own_ok = False
            try:
                own_ok = self.client.fetch_offset(
                    c.group_id, c.topic,
                    _HEARTBEAT_PART_BASE + c.member_index) >= 0
            except Exception:  # noqa: BLE001 — coordinator unreachable
                pass
            if not own_ok:
                if not self._warned_blind:
                    self._log.warning(
                        "kafka group %s: own heartbeat does not read back "
                        "from the coordinator — liveness is blind; holding "
                        "the static %d-way split", c.group_id, c.members)
                    self._warned_blind = True
                live = list(range(c.members))
        if self._live != live:
            self._log.info("kafka group %s liveness: members %s of %d",
                           c.group_id, live, c.members)
        self._live, self._live_checked = live, now
        return live

    def _my_partitions(self, parts: dict[int, int]) -> dict[int, int]:
        """STICKY deterministic split over live members: a partition
        whose static owner (p % members) is alive stays put; only dead
        owners' partitions fold onto the survivors (live[p % len(live)]).
        All-alive reduces to the static split, and one death moves
        exactly the dead member's share — reshuffling healthy members'
        partitions would open an at-least-once dual-fetch window across
        the whole topic for every roster change (code-review r4)."""
        c = self.cfg
        live = self._live_members()
        if not live:
            live = [c.member_index]
        n = len(live)

        def owner(p: int) -> int:
            static = p % c.members
            return static if static in live else live[p % n]

        return {p: l for p, l in parts.items()
                if owner(p) == c.member_index}

    def poll_once(self) -> int:
        """One fetch round over owned partitions. Returns records pushed."""
        c = self.cfg
        self._heartbeat_if_due()
        meta = self.client.metadata([c.topic])
        parts = self._my_partitions(meta.get(c.topic, {}))
        # partitions reassigned away (a member revived) restart from the
        # group's committed offset on re-adoption, not a stale local one —
        # including a pending out-of-range reset, which after another
        # member's hours of commits would replay the whole partition
        for p in list(self._offsets):
            if p not in parts:
                self._offsets.pop(p)
                self._reset_parts.discard(p)
        n = 0
        for partition, leader in sorted(parts.items()):
            if partition not in self._offsets:
                committed = (
                    -1
                    if partition in self._reset_parts
                    else self.client.fetch_offset(c.group_id, c.topic, partition)
                )
                if committed >= 0:
                    self._offsets[partition] = committed
                else:
                    ts = (
                        -2
                        if c.start_at == "earliest" or partition in self._reset_parts
                        else -1
                    )
                    self._offsets[partition] = self.client.list_offset(
                        c.topic, partition, ts, leader
                    )
                    self._reset_parts.discard(partition)
            offset = self._offsets[partition]
            try:
                records, _hw = self.client.fetch(c.topic, partition, offset, leader)
            except KafkaError as e:
                if e.code == ERR_OFFSET_OUT_OF_RANGE:
                    # retention deleted segments under our offset —
                    # re-resolve from the log start next round, bypassing
                    # the (stale) committed offset
                    # (the auto.offset.reset=earliest behavior)
                    self._offsets.pop(partition, None)
                    self._reset_parts.add(partition)
                    self.offset_resets += 1
                    continue
                raise
            except CorruptBatchError as e:
                # corrupt batch (CRC mismatch): poison-skip past the whole
                # batch when its header's offset delta is self-consistent
                # (delta == count-1), so an N-record batch costs one
                # fetch instead of N; inconsistent headers skip one offset
                self.decode_errors += 1
                _decode_errors_total.inc()
                self._offsets[partition] = max(
                    offset + 1, e.next_offset or 0)
                continue
            if not records:
                continue
            for off, _key, value in records:
                try:
                    batches = decode_message(c.encoding, value)
                except Exception:
                    self.decode_errors += 1
                    _decode_errors_total.inc()
                    n += 1
                    self._offsets[partition] = off + 1
                    continue
                if batches:
                    self.push_fn(c.tenant, batches)
                n += 1
                self._offsets[partition] = off + 1
            self.client.commit_offset(
                c.group_id, c.topic, partition, self._offsets[partition]
            )
        self.records_consumed += n
        if n:
            _records_total.inc(n)
        return n

    def run(self):
        backoff = self.cfg.poll_interval_s
        while not self._stop.is_set():
            try:
                n = self.poll_once()
                backoff = self.cfg.poll_interval_s
                if n == 0:
                    self._stop.wait(self.cfg.poll_interval_s)
            except Exception as e:  # noqa: BLE001 — receiver must survive
                _poll_errors_total.inc()
                self.client.invalidate()  # re-discover leaders/coordinator
                self._log.warning(
                    "kafka poll failed (topic %s, backoff %.1fs): %s",
                    self.cfg.topic, backoff, e,
                )
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)

    def start(self):
        self._thread = threading.Thread(target=self.run, daemon=True, name="kafka-receiver")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.client.close()


def pubsub_lite_receiver(cfg: dict, push_fn) -> KafkaReceiver:
    """Pub/Sub Lite receiver (Shopify fork extra, shim.go:97) via its
    Kafka-compatible endpoint: TLS + SASL/PLAIN where the username is
    the literal ``__token__`` and the password an OAuth access token.
    All KafkaReceiverConfig keys pass through (member split, start_at,
    poll interval); pubsub-lite aliases map on top."""
    merged = {
        "brokers": ["kafka.pubsublite.googleapis.com:443"],
        "tls": True,
        "sasl_username": "__token__",
        **{k: v for k, v in cfg.items() if k not in ("subscription", "token")},
    }
    if "subscription" in cfg:
        merged.setdefault("group_id", cfg["subscription"])
    if "token" in cfg:
        merged.setdefault("sasl_password", cfg["token"])
    if not merged.get("sasl_password"):
        # fail fast at config load, not with an AttributeError per poll
        raise ValueError(
            "pubsub_lite receiver requires `token` (OAuth access token used "
            "as the SASL/PLAIN password) or explicit sasl_password"
        )
    return KafkaReceiver(KafkaReceiverConfig(**merged), push_fn)
