"""Minimal Apache-Thrift wire codecs (binary + compact protocols).

The reference ingests Jaeger spans through otel-collector's jaeger
receiver (modules/distributor/receiver/shim.go:75-138), which speaks
thrift on the wire: TBinaryProtocol for the collector HTTP endpoint and
TCompactProtocol for the UDP agent. No thrift library is vendored here;
these are self-contained codecs for the subset thrift IDL uses
(struct/list/string/i16/i32/i64/double/bool/binary), decoding to a
generic ``{field_id: value}`` tree — schema interpretation lives with the
caller (api/jaeger.py).

Both directions are implemented so tests can fabricate exactly what a
Jaeger client emits.
"""

from __future__ import annotations

import struct

# thrift type ids (TType)
T_STOP = 0
T_BOOL = 2
T_BYTE = 3
T_DOUBLE = 4
T_I16 = 6
T_I32 = 8
T_I64 = 10
T_STRING = 11
T_STRUCT = 12
T_MAP = 13
T_SET = 14
T_LIST = 15

# message types
MSG_CALL = 1
MSG_REPLY = 2
MSG_ONEWAY = 4


class ThriftError(ValueError):
    pass


# Bound on struct/container nesting: jaeger.thrift nests 4 deep; a
# crafted payload of 1-byte struct headers must exhaust this cap (clean
# ThriftError) rather than the Python recursion limit (RecursionError
# escaping the malformed-payload handling).
MAX_DEPTH = 64


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.depth = 0

    def descend(self) -> None:
        self.depth += 1
        if self.depth > MAX_DEPTH:
            raise ThriftError("thrift nesting too deep")

    def ascend(self) -> None:
        self.depth -= 1

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise ThriftError("truncated thrift payload")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]


# --------------------------------------------------------------- binary


class BinaryProtocol:
    """TBinaryProtocol (strict): big-endian fixed-width ints,
    i32-length-prefixed strings, typed field headers."""

    VERSION_1 = 0x80010000

    # -- decode --

    def read_struct(self, r: _Reader) -> dict:
        r.descend()
        out = {}
        while True:
            ftype = r.u8()
            if ftype == T_STOP:
                r.ascend()
                return out
            (fid,) = struct.unpack(">h", r.take(2))
            out[fid] = self.read_value(r, ftype)

    def read_value(self, r: _Reader, ftype: int):
        if ftype == T_BOOL:
            return r.u8() != 0
        if ftype == T_BYTE:
            return struct.unpack(">b", r.take(1))[0]
        if ftype == T_DOUBLE:
            return struct.unpack(">d", r.take(8))[0]
        if ftype == T_I16:
            return struct.unpack(">h", r.take(2))[0]
        if ftype == T_I32:
            return struct.unpack(">i", r.take(4))[0]
        if ftype == T_I64:
            return struct.unpack(">q", r.take(8))[0]
        if ftype == T_STRING:
            (n,) = struct.unpack(">i", r.take(4))
            if n < 0:
                raise ThriftError("negative string length")
            return r.take(n)
        if ftype == T_STRUCT:
            return self.read_struct(r)
        if ftype in (T_LIST, T_SET):
            r.descend()
            etype = r.u8()
            (n,) = struct.unpack(">i", r.take(4))
            if n < 0:
                raise ThriftError("negative list size")
            out = [self.read_value(r, etype) for _ in range(n)]
            r.ascend()
            return out
        if ftype == T_MAP:
            r.descend()
            ktype, vtype = r.u8(), r.u8()
            (n,) = struct.unpack(">i", r.take(4))
            if n < 0:
                raise ThriftError("negative map size")
            out = {self.read_value(r, ktype): self.read_value(r, vtype)
                   for _ in range(n)}
            r.ascend()
            return out
        raise ThriftError(f"unsupported thrift type {ftype}")

    def read_message(self, r: _Reader) -> tuple[str, int, int]:
        """Returns (name, msg_type, seqid); caller then reads args struct."""
        (version,) = struct.unpack(">I", r.take(4))
        if version & 0xFFFF0000 != self.VERSION_1:
            raise ThriftError("bad binary-protocol version")
        msg_type = version & 0xFF
        (n,) = struct.unpack(">i", r.take(4))
        name = r.take(n).decode()
        (seqid,) = struct.unpack(">i", r.take(4))
        return name, msg_type, seqid

    # -- encode (tests / clients) --

    def write_value(self, out: bytearray, ftype: int, v) -> None:
        if ftype == T_BOOL:
            out.append(1 if v else 0)
        elif ftype == T_BYTE:
            out += struct.pack(">b", v)
        elif ftype == T_DOUBLE:
            out += struct.pack(">d", v)
        elif ftype == T_I16:
            out += struct.pack(">h", v)
        elif ftype == T_I32:
            out += struct.pack(">i", v)
        elif ftype == T_I64:
            out += struct.pack(">q", v)
        elif ftype == T_STRING:
            b = v.encode() if isinstance(v, str) else bytes(v)
            out += struct.pack(">i", len(b)) + b
        elif ftype == T_STRUCT:
            out += self.encode_struct(v)
        elif ftype in (T_LIST, T_SET):
            etype, items = v
            out.append(etype)
            out += struct.pack(">i", len(items))
            for it in items:
                self.write_value(out, etype, it)
        else:
            raise ThriftError(f"unsupported thrift type {ftype}")

    def encode_struct(self, fields: list) -> bytes:
        """fields: [(fid, ftype, value), ...]"""
        out = bytearray()
        for fid, ftype, v in fields:
            out.append(ftype)
            out += struct.pack(">h", fid)
            self.write_value(out, ftype, v)
        out.append(T_STOP)
        return bytes(out)

    def encode_message(self, name: str, msg_type: int, seqid: int,
                       args: list) -> bytes:
        out = bytearray()
        out += struct.pack(">I", self.VERSION_1 | msg_type)
        nb = name.encode()
        out += struct.pack(">i", len(nb)) + nb
        out += struct.pack(">i", seqid)
        out += self.encode_struct(args)
        return bytes(out)


# -------------------------------------------------------------- compact

# compact field types (distinct numbering from TType)
CT_BOOL_TRUE = 1
CT_BOOL_FALSE = 2
CT_BYTE = 3
CT_I16 = 4
CT_I32 = 5
CT_I64 = 6
CT_DOUBLE = 7
CT_BINARY = 8
CT_LIST = 9
CT_SET = 10
CT_MAP = 11
CT_STRUCT = 12

_TTYPE_TO_CT = {T_BOOL: CT_BOOL_TRUE, T_BYTE: CT_BYTE, T_I16: CT_I16,
                T_I32: CT_I32, T_I64: CT_I64, T_DOUBLE: CT_DOUBLE,
                T_STRING: CT_BINARY, T_LIST: CT_LIST, T_SET: CT_SET,
                T_MAP: CT_MAP, T_STRUCT: CT_STRUCT}


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        if n & ~0x7F == 0:
            out.append(n)
            return
        out.append((n & 0x7F) | 0x80)
        n >>= 7


def _read_varint(r: _Reader) -> int:
    shift = 0
    result = 0
    while True:
        b = r.u8()
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result
        shift += 7
        if shift > 70:
            raise ThriftError("varint too long")


class CompactProtocol:
    """TCompactProtocol: zigzag varints, delta-encoded field ids, bools
    folded into the field header, little-endian doubles (the Apache
    implementations' de-facto spec)."""

    PROTOCOL_ID = 0x82
    VERSION = 1

    # -- decode --

    def read_struct(self, r: _Reader) -> dict:
        r.descend()
        out = {}
        last_fid = 0
        while True:
            head = r.u8()
            if head == T_STOP:
                r.ascend()
                return out
            delta = (head >> 4) & 0x0F
            ctype = head & 0x0F
            if delta:
                fid = last_fid + delta
            else:
                fid = _unzigzag(_read_varint(r))
            last_fid = fid
            out[fid] = self.read_value(r, ctype)

    def read_value(self, r: _Reader, ctype: int):
        if ctype == CT_BOOL_TRUE:
            return True
        if ctype == CT_BOOL_FALSE:
            return False
        if ctype == CT_BYTE:
            return struct.unpack(">b", r.take(1))[0]
        if ctype in (CT_I16, CT_I32, CT_I64):
            return _unzigzag(_read_varint(r))
        if ctype == CT_DOUBLE:
            return struct.unpack("<d", r.take(8))[0]
        if ctype == CT_BINARY:
            return r.take(_read_varint(r))
        if ctype == CT_STRUCT:
            return self.read_struct(r)
        if ctype in (CT_LIST, CT_SET):
            r.descend()
            head = r.u8()
            size = (head >> 4) & 0x0F
            etype = head & 0x0F
            if size == 15:
                size = _read_varint(r)
            if etype in (CT_BOOL_TRUE, CT_BOOL_FALSE):
                out = [r.u8() == CT_BOOL_TRUE for _ in range(size)]
            else:
                out = [self.read_value(r, etype) for _ in range(size)]
            r.ascend()
            return out
        if ctype == CT_MAP:
            r.descend()
            size = _read_varint(r)
            if size == 0:
                r.ascend()
                return {}
            kv = r.u8()
            ktype, vtype = (kv >> 4) & 0x0F, kv & 0x0F
            out = {self.read_value(r, ktype): self.read_value(r, vtype)
                   for _ in range(size)}
            r.ascend()
            return out
        raise ThriftError(f"unsupported compact type {ctype}")

    def read_message(self, r: _Reader) -> tuple[str, int, int]:
        if r.u8() != self.PROTOCOL_ID:
            raise ThriftError("not a compact-protocol message")
        b = r.u8()
        if b & 0x1F != self.VERSION:
            raise ThriftError("bad compact-protocol version")
        msg_type = (b >> 5) & 0x07
        seqid = _read_varint(r)
        name = r.take(_read_varint(r)).decode()
        return name, msg_type, seqid

    # -- encode --

    def write_value(self, out: bytearray, ttype: int, v) -> None:
        if ttype == T_BOOL:  # only inside lists; field bools use header
            out.append(CT_BOOL_TRUE if v else CT_BOOL_FALSE)
        elif ttype == T_BYTE:
            out += struct.pack(">b", v)
        elif ttype in (T_I16, T_I32, T_I64):
            _write_varint(out, _zigzag(v))
        elif ttype == T_DOUBLE:
            out += struct.pack("<d", v)
        elif ttype == T_STRING:
            b = v.encode() if isinstance(v, str) else bytes(v)
            _write_varint(out, len(b))
            out += b
        elif ttype == T_STRUCT:
            out += self.encode_struct(v)
        elif ttype in (T_LIST, T_SET):
            etype, items = v
            ct = _TTYPE_TO_CT[etype]
            if len(items) < 15:
                out.append((len(items) << 4) | ct)
            else:
                out.append(0xF0 | ct)
                _write_varint(out, len(items))
            for it in items:
                self.write_value(out, etype, it)
        else:
            raise ThriftError(f"unsupported thrift type {ttype}")

    def encode_struct(self, fields: list) -> bytes:
        out = bytearray()
        last_fid = 0
        for fid, ftype, v in fields:
            if ftype == T_BOOL:
                ct = CT_BOOL_TRUE if v else CT_BOOL_FALSE
            else:
                ct = _TTYPE_TO_CT[ftype]
            delta = fid - last_fid
            if 0 < delta <= 15:
                out.append((delta << 4) | ct)
            else:
                out.append(ct)
                _write_varint(out, _zigzag(fid))
            last_fid = fid
            if ftype != T_BOOL:
                self.write_value(out, ftype, v)
        out.append(T_STOP)
        return bytes(out)

    def encode_message(self, name: str, msg_type: int, seqid: int,
                       args: list) -> bytes:
        out = bytearray([self.PROTOCOL_ID,
                         ((msg_type & 0x07) << 5) | self.VERSION])
        _write_varint(out, seqid)
        nb = name.encode()
        _write_varint(out, len(nb))
        out += nb
        out += self.encode_struct(args)
        return bytes(out)


def decode_struct(data: bytes, protocol: str = "binary") -> dict:
    proto = BinaryProtocol() if protocol == "binary" else CompactProtocol()
    return proto.read_struct(_Reader(data))


def decode_message(data: bytes):
    """Sniff the protocol from the first byte and decode a full message.
    Returns (name, msg_type, seqid, args_struct)."""
    if not data:
        raise ThriftError("empty message")
    r = _Reader(data)
    if data[0] == CompactProtocol.PROTOCOL_ID:
        proto = CompactProtocol()
    elif data[0] == 0x80:
        proto = BinaryProtocol()
    else:
        raise ThriftError(f"unknown thrift protocol byte {data[0]:#x}")
    name, msg_type, seqid = proto.read_message(r)
    return name, msg_type, seqid, proto.read_struct(r)
