"""Jaeger-UI query bridge: the cmd/tempo-query role.

The reference ships cmd/tempo-query, a Jaeger gRPC storage plugin that
lets the Jaeger UI (and Grafana 7.4's Jaeger datasource) read traces out
of Tempo (cmd/tempo-query/main.go:24-60, tempo/plugin.go). Here the
bridge is served in-process over HTTP instead: the Jaeger query-service
JSON API (`/jaeger/api/...`) translated onto the framework's native
trace/search model — no sidecar process needed.

Endpoints (Jaeger query-service contract):
  GET /jaeger/api/services                       → {"data": [service, ...]}
  GET /jaeger/api/services/{svc}/operations      → {"data": [op, ...]}
  GET /jaeger/api/traces/{trace_id}              → {"data": [jaeger trace]}
  GET /jaeger/api/traces?service=&operation=&limit=&start=&end=
                                                 → {"data": [jaeger trace, ...]}
"""

from __future__ import annotations

from tempo_tpu import tempopb
from tempo_tpu.db.pool import run_jobs

from .params import _duration_ms

_KIND_NAME = {
    tempopb.Span.SPAN_KIND_CLIENT: "client",
    tempopb.Span.SPAN_KIND_SERVER: "server",
    tempopb.Span.SPAN_KIND_PRODUCER: "producer",
    tempopb.Span.SPAN_KIND_CONSUMER: "consumer",
    tempopb.Span.SPAN_KIND_INTERNAL: "internal",
}


def _any_to_jaeger(v: "tempopb.AnyValue") -> tuple[str, object]:
    which = v.WhichOneof("value")
    if which == "bool_value":
        return "bool", v.bool_value
    if which == "int_value":
        return "int64", v.int_value
    if which == "double_value":
        return "float64", v.double_value
    if which == "bytes_value":
        return "binary", v.bytes_value.hex()
    return "string", v.string_value


def _tags(attributes) -> list:
    out = []
    for kv in attributes:
        typ, val = _any_to_jaeger(kv.value)
        out.append({"key": kv.key, "type": typ, "value": val})
    return out


def trace_to_jaeger(trace: "tempopb.Trace") -> dict:
    """OTLP-shaped tempopb.Trace → one Jaeger-UI JSON trace."""
    processes: dict[str, dict] = {}
    proc_ids: dict[str, str] = {}  # service name → pid
    spans = []
    trace_id_hex = ""
    for rs in trace.batches:
        svc = "unknown"
        proc_tags = []
        for kv in rs.resource.attributes:
            if kv.key == "service.name":
                svc = kv.value.string_value or svc
            else:
                typ, val = _any_to_jaeger(kv.value)
                proc_tags.append({"key": kv.key, "type": typ, "value": val})
        pid = proc_ids.get(svc)
        if pid is None:
            pid = proc_ids[svc] = f"p{len(proc_ids) + 1}"
            processes[pid] = {"serviceName": svc, "tags": proc_tags}
        for ss in rs.scope_spans:
            for s in ss.spans:
                trace_id_hex = trace_id_hex or s.trace_id.hex()
                js = {
                    "traceID": s.trace_id.hex(),
                    "spanID": s.span_id.hex(),
                    "operationName": s.name,
                    "startTime": s.start_time_unix_nano // 1000,
                    "duration": max(0, (s.end_time_unix_nano
                                        - s.start_time_unix_nano)) // 1000,
                    "processID": pid,
                    "references": [],
                    "tags": _tags(s.attributes),
                    "logs": [
                        {"timestamp": ev.time_unix_nano // 1000,
                         "fields": ([{"key": "event", "type": "string",
                                      "value": ev.name}]
                                    + _tags(ev.attributes))}
                        for ev in s.events
                    ],
                }
                if s.kind in _KIND_NAME:
                    js["tags"].append({"key": "span.kind", "type": "string",
                                       "value": _KIND_NAME[s.kind]})
                if s.status.code == 2:
                    js["tags"].append({"key": "error", "type": "bool",
                                       "value": True})
                if s.parent_span_id:
                    js["references"].append({
                        "refType": "CHILD_OF",
                        "traceID": s.trace_id.hex(),
                        "spanID": s.parent_span_id.hex(),
                    })
                for link in s.links:
                    js["references"].append({
                        "refType": "FOLLOWS_FROM",
                        "traceID": link.trace_id.hex(),
                        "spanID": link.span_id.hex(),
                    })
                spans.append(js)
    return {"traceID": trace_id_hex, "spans": spans, "processes": processes}


def _envelope(data, errors=None) -> dict:
    """Jaeger structuredResponse: the UI reads data/total/limit/offset/
    errors (jaeger query-service http_handler structuredResponse)."""
    return {"data": data, "total": len(data), "limit": 0, "offset": 0,
            "errors": errors}


class JaegerQueryBridge:
    """Serves the Jaeger query-service API from an App."""

    def __init__(self, app):
        self.app = app

    def services(self, tenant: str) -> dict:
        resp = self.app.queriers[0].search_tag_values(tenant, "service.name")
        return _envelope(sorted(resp.tag_values))

    OPERATIONS_SCAN_LIMIT = 200

    def operations(self, tenant: str, service: str) -> dict:
        """Operation names for one service. Service-filtered via a search
        over that service's traces (root operation names; bounded scan) —
        the unfiltered "name" tag-values index spans all services and
        would pollute the UI dropdown with other services' operations."""
        if not service:
            resp = self.app.queriers[0].search_tag_values(tenant, "name")
            return _envelope(sorted(resp.tag_values))
        req = tempopb.SearchRequest()
        req.tags["service.name"] = service
        req.limit = self.OPERATIONS_SCAN_LIMIT
        sresp = self.app.search(tenant, req)
        ops = {m.root_trace_name for m in sresp.traces
               if m.root_trace_name and m.root_service_name == service}
        return _envelope(sorted(ops))

    def trace_by_id(self, tenant: str, trace_id: bytes):
        resp = self.app.find_trace(tenant, trace_id)
        if not resp.trace.batches:
            return None
        return _envelope([trace_to_jaeger(resp.trace)])

    def search(self, tenant: str, query: dict) -> dict:
        from .params import InvalidArgument

        try:
            req = tempopb.SearchRequest()
            if query.get("service"):
                req.tags["service.name"] = query["service"]
            if query.get("operation"):
                req.tags["name"] = query["operation"]
            # jaeger sends start/end in µs epoch
            if query.get("start"):
                req.start = int(int(query["start"]) // 1_000_000)
            if query.get("end"):
                req.end = int(int(query["end"]) // 1_000_000) + 1
            if query.get("minDuration"):
                req.min_duration_ms = _duration_ms(query["minDuration"])
            if query.get("maxDuration"):
                req.max_duration_ms = _duration_ms(query["maxDuration"])
            if query.get("tags"):
                # jaeger-ui sends a JSON object; logfmt from older
                # clients (the jaeger query-service accepts both)
                import json as _json

                try:
                    pairs = _json.loads(query["tags"]).items()
                except (ValueError, AttributeError):
                    pairs = (p.split("=", 1) for p in query["tags"].split()
                             if "=" in p)
                for k, v in pairs:
                    req.tags[str(k)] = str(v)
            # `lookback` arrives alongside explicit start/end (the UI
            # computes the window client-side) — nothing to apply
            req.limit = int(query.get("limit", 20))
        except ValueError as e:
            raise InvalidArgument(f"bad jaeger search params: {e}") from None
        sresp = self.app.search(tenant, req)

        def fetch(meta):
            full = self.app.find_trace(tenant, bytes.fromhex(meta.trace_id))
            return trace_to_jaeger(full.trace) if full.trace.batches else None

        hydrated, _ = run_jobs(list(sresp.traces), fetch, workers=10)
        # run_jobs completion order is nondeterministic; restore the
        # search's newest-first ordering
        order = {m.trace_id: i for i, m in enumerate(sresp.traces)}
        hydrated.sort(key=lambda j: order.get(j["traceID"], 1 << 30))
        return _envelope(hydrated)

    def dependencies(self) -> dict:
        """The UI unconditionally fetches /api/dependencies for its
        System Architecture tab. Edge data lives in the metrics-
        generator's service-graph series here (reference parity:
        tempo-query also returns an empty set — dependencies come from
        a separate job in Jaeger deployments)."""
        return _envelope([])
