"""OpenCensus ingest receiver: the OC agent TraceService over gRPC.

Role-equivalent to the reference's embedded otel-collector opencensus
receiver (modules/distributor/receiver/shim.go factories; default agent
port 55678). OC's `Export` is a *bidirectional stream* where the first
request carries `node`/`resource` and later ones may omit them — the
handler keeps per-stream state and applies the last seen. `Config` is
answered with an empty echo (the collector does the same when no
sampling config is pushed).

Translation OC → OTLP (our wire model):
  trace_id/span_id/parent  bytes, verbatim
  name                     TruncatableString.value
  kind                     SERVER→SPAN_KIND_SERVER, CLIENT→SPAN_KIND_CLIENT
  start/end time           Timestamp → unix nanos
  attributes               string/int/bool/double → AnyValue
  annotations              → span events
  status.code (gRPC)       nonzero → STATUS_CODE_ERROR (message kept)
  node.service_info.name   → resource service.name (resource labels merged,
                           per-span resource overrides the request one)
"""

from __future__ import annotations

from tempo_tpu import tempopb
from tempo_tpu.tempopb import opencensus_pb2 as ocpb
from tempo_tpu.utils.ids import pad_trace_id

OC_TRACE_SERVICE = "opencensus.proto.agent.trace.v1.TraceService"

_OC_KIND = {
    ocpb.OCSpan.SERVER: tempopb.Span.SPAN_KIND_SERVER,
    ocpb.OCSpan.CLIENT: tempopb.Span.SPAN_KIND_CLIENT,
}


def _ts_nanos(ts) -> int:
    return int(ts.seconds) * 1_000_000_000 + int(ts.nanos)


def _set_attr(kv, v) -> None:
    which = v.WhichOneof("value")
    if which == "string_value":
        kv.value.string_value = v.string_value.value
    elif which == "int_value":
        kv.value.int_value = v.int_value
    elif which == "bool_value":
        kv.value.bool_value = v.bool_value
    elif which == "double_value":
        kv.value.double_value = v.double_value


def oc_request_to_batches(req, node=None, resource=None) -> list:
    """One OC ExportTraceServiceRequest → [ResourceSpans] (grouped by
    effective resource: request-level unless a span overrides)."""
    node = req.node if req.HasField("node") else node
    resource = req.resource if req.HasField("resource") else resource

    def resource_key(res):
        if res is None:
            return ()
        return (res.type, tuple(sorted(res.labels.items())))

    groups: dict[tuple, tempopb.ResourceSpans] = {}
    for span in req.spans:
        res = span.resource if span.HasField("resource") else resource
        key = resource_key(res)
        rs = groups.get(key)
        if rs is None:
            rs = groups[key] = tempopb.ResourceSpans()
            svc = None
            if node is not None and node.service_info.name:
                svc = node.service_info.name
            if res is not None:
                for k, v in sorted(res.labels.items()):
                    if k in ("service.name", "service_name"):
                        # explicit resource label beats node.service_info
                        # (per-span resource overrides depend on this);
                        # either way exactly ONE service.name is emitted
                        svc = v
                        continue
                    kv = rs.resource.attributes.add()
                    kv.key = k
                    kv.value.string_value = v
                if res.type:
                    kv = rs.resource.attributes.add()
                    kv.key = "opencensus.resourcetype"
                    kv.value.string_value = res.type
            kv = rs.resource.attributes.add()
            kv.key = "service.name"
            kv.value.string_value = svc or "unknown"
            scope = rs.scope_spans.add().scope
            scope.name = "opencensus-receiver"
            if node is not None and node.library_info.core_library_version:
                scope.version = node.library_info.core_library_version
        s = rs.scope_spans[0].spans.add()
        s.trace_id = pad_trace_id(span.trace_id)
        s.span_id = span.span_id[:8].rjust(8, b"\x00")
        if span.parent_span_id:
            s.parent_span_id = span.parent_span_id[:8].rjust(8, b"\x00")
        s.name = span.name.value
        if span.tracestate.entries:
            s.trace_state = ",".join(
                f"{e.key}={e.value}" for e in span.tracestate.entries)
        s.kind = _OC_KIND.get(span.kind, tempopb.Span.SPAN_KIND_UNSPECIFIED)
        s.start_time_unix_nano = _ts_nanos(span.start_time)
        s.end_time_unix_nano = _ts_nanos(span.end_time)
        for k, v in span.attributes.attribute_map.items():
            kv = s.attributes.add()
            kv.key = k
            _set_attr(kv, v)
        for te in span.time_events.time_event:
            if te.WhichOneof("value") == "annotation":
                ev = s.events.add()
                ev.time_unix_nano = _ts_nanos(te.time)
                ev.name = te.annotation.description.value
                for k, v in te.annotation.attributes.attribute_map.items():
                    kv = ev.attributes.add()
                    kv.key = k
                    _set_attr(kv, v)
        if span.HasField("status") and span.status.code != 0:
            s.status.code = tempopb.Status.STATUS_CODE_ERROR
            s.status.message = span.status.message
    return list(groups.values())


def make_oc_handler(push_fn, tenant_from=None):
    """grpc GenericRpcHandler serving the OC TraceService; register it on
    any grpc.Server (the distributor's, alongside OTLP)."""
    import grpc

    def export(request_iterator, context):
        node = resource = None
        tenant = tenant_from(context) if tenant_from else "single-tenant"
        for req in request_iterator:
            if req.HasField("node"):
                node = req.node
            if req.HasField("resource"):
                resource = req.resource
            batches = oc_request_to_batches(req, node, resource)
            if batches:
                push_fn(tenant, batches)
            yield ocpb.OCExportTraceServiceResponse()

    def config(request_iterator, context):
        for req in request_iterator:
            yield ocpb.OCUpdatedLibraryConfig()

    return grpc.method_handlers_generic_handler(OC_TRACE_SERVICE, {
        "Export": grpc.stream_stream_rpc_method_handler(
            export,
            request_deserializer=ocpb.OCExportTraceServiceRequest.FromString,
            response_serializer=ocpb.OCExportTraceServiceResponse.SerializeToString,
        ),
        "Config": grpc.stream_stream_rpc_method_handler(
            config,
            request_deserializer=ocpb.OCCurrentLibraryConfig.FromString,
            response_serializer=ocpb.OCUpdatedLibraryConfig.SerializeToString,
        ),
    })
