"""Device mesh construction.

The TPU reinterpretation of the reference's data-distribution strategies
(SURVEY.md §2.5): block/page shards map onto mesh axes the way search jobs
map onto queriers. One axis — "shards" — carries the scan fan-out
(pages × blocks are data-parallel); collectives ride ICI within a slice
and DCN across slices, replacing the goroutine fan-out + Results channel.
"""

from __future__ import annotations

import contextlib
import threading

import jax
from jax.sharding import Mesh

SCAN_AXIS = "shards"

# Collective-program dispatch order must be IDENTICAL on every device:
# two threads enqueueing shard_map programs concurrently can interleave
# the per-device queues (dev0 runs A then B, dev1 runs B then A) and the
# collectives rendezvous-deadlock — observed as a multi-minute zero-CPU
# hang. ONE process-wide lock covers every dispatch site (scan kernels,
# the dictionary probe, any future collective): per-engine locks are not
# enough, because the probe dispatches during query compilation while a
# different engine thread may be mid-scan on the same devices.
dispatch_lock = threading.Lock()


@contextlib.contextmanager
def locked_collective(rec=None):
    """Hold the process-wide collective dispatch lock, attributing the
    time spent QUEUED behind other dispatches to the profiler record's
    `lock_wait` stage (rec = observability.profile dispatch record or
    None). Under concurrent mesh searches this wait is serialization the
    operator can't otherwise see — it looks like kernel time.

    The wait is BOUNDED (`search_dispatch_lock_timeout_s`, via
    robustness.GUARD.lock_timeout_s): a dispatch wedged while holding
    this lock used to block every later submitter forever (the PR 1
    rendezvous-deadlock class). A timed-out wait now books a device
    fault into the circuit breaker and raises DispatchLockTimeout, so
    the submitter falls back to the host path instead of stacking.
    <= 0 restores the unbounded wait."""
    import time

    from tempo_tpu.robustness import BREAKER, GUARD, FAULTS
    from tempo_tpu.robustness.dispatch import DispatchLockTimeout
    from tempo_tpu.observability import metrics as obs

    timeout = GUARD.lock_timeout_s
    t0 = time.perf_counter()
    if timeout and timeout > 0:
        ok = dispatch_lock.acquire(timeout=timeout)
    else:
        ok = dispatch_lock.acquire()
    if not ok:
        obs.dispatch_lock_timeouts.inc()
        BREAKER.record_fault("lock_timeout", mode="mesh")
        raise DispatchLockTimeout(
            f"collective dispatch lock not acquired within {timeout:.1f}s"
            " — another dispatch is wedged while holding it")
    try:
        if rec is not None:
            rec.add_stage("lock_wait", time.perf_counter() - t0)
        if FAULTS.active:
            # simulates a dispatch wedged INSIDE the collective section
            # (holding the lock): later submitters hit the bounded wait
            FAULTS.hit("dispatch_lock_hang")
        yield
    finally:
        dispatch_lock.release()


def scan_mesh_axes() -> tuple[str, ...]:
    return (SCAN_AXIS,)


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (SCAN_AXIS,))


def shard_map_compat(f, mesh, in_specs, out_specs, check: bool = False):
    """jax.shard_map across jax versions: newer jax exposes it top-level
    with `check_vma`; older releases only have the experimental module
    with the same knob spelled `check_rep`. Every distributed kernel
    routes through here so a version bump is a one-line change."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check)
