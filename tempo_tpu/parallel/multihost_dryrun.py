"""Localhost multi-process dryrun of the multi-host serving path.

Validates BASELINE config 5's shape without TPU hardware: N OS processes
join one JAX distributed runtime (gloo collectives over loopback — the
DCN stand-in), each simulating a host with M CPU "chips"; the scan mesh
spans all N*M devices; every process drives the production
`TempoDB.search` over the same backend corpus; per-host staging places
only the process-local page shards (multiblock.stack_blocks
make_array_from_callback path); and the launcher asserts every process
returns the identical answer, equal to the host oracle.

Run directly (`python -m tempo_tpu.parallel.multihost_dryrun`) or via
`__graft_entry__.dryrun_multihost(n)`. Reference analog: the querier
worker fleet joining the frontend over gRPC
(/root/reference/modules/querier/worker/worker.go:23-51) — here the
"join" is jax.distributed and the result merge is on-device collectives.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import tempfile


def _corpus(n=32, seed=0):
    from tempo_tpu.search.data import SearchData

    rng = random.Random(seed)
    entries = []
    for i in range(n):
        sd = SearchData(trace_id=rng.randbytes(16))
        sd.start_s = 1_600_000_000 + seed * 1000 + i
        sd.end_s = sd.start_s + 5
        sd.dur_ms = rng.randint(1, 10_000)
        sd.root_service = rng.choice(["frontend", "checkout"])
        sd.root_name = "GET /"
        sd.kvs = {
            "service.name": {sd.root_service},
            "http.status_code": {str(rng.choice([200, 500]))},
        }
        entries.append(sd)
    return entries


def _query():
    from tempo_tpu import tempopb

    req = tempopb.SearchRequest()
    req.tags["service.name"] = "frontend"
    req.min_duration_ms = 100
    req.limit = 1000  # no early quit: every process scans everything
    return req


def _build_corpus(root: str) -> int:
    """Write 4 deterministic blocks; returns the host-oracle match count."""
    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.search.columnar import PageGeometry
    from tempo_tpu.search.data import search_data_matches

    db = TempoDB(LocalBackend(os.path.join(root, "blocks")),
                 os.path.join(root, "wal-writer"),
                 TempoDBConfig(search_geometry=PageGeometry(8, 8),
                               auto_mesh=False))
    req = _query()
    expected = 0
    for b in range(4):
        entries = _corpus(32, seed=b)
        expected += sum(1 for sd in entries if search_data_matches(sd, req))
        db.write_block_direct(
            "t1",
            sorted((sd.trace_id, b"\x00", sd.start_s, sd.end_s)
                   for sd in entries),
            search_entries=entries,
        )
    return expected


def worker_main(process_id: int, num_processes: int, port: int,
                root: str, devices_per_proc: int) -> None:
    """One simulated host: join the runtime, mesh over ALL global
    devices, drive TempoDB.search, dump a result digest."""
    from tempo_tpu.parallel.multihost import init_distributed

    ok = init_distributed(coordinator=f"127.0.0.1:{port}",
                          num_processes=num_processes,
                          process_id=process_id,
                          cpu_devices_per_host=devices_per_proc)
    assert ok
    import jax

    assert jax.process_count() == num_processes, jax.process_count()

    from tempo_tpu.backend.local import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.parallel.mesh import make_mesh
    from tempo_tpu.search.columnar import PageGeometry

    mesh = make_mesh()  # global: spans every process's devices
    assert mesh.devices.size == num_processes * jax.local_device_count()
    db = TempoDB(LocalBackend(os.path.join(root, "blocks")),
                 os.path.join(root, f"wal-{process_id}"),
                 TempoDBConfig(search_geometry=PageGeometry(8, 8)),
                 mesh=mesh)
    db.poll()
    results = db.search("t1", _query())
    resp = results.response()
    digest = {
        "process_id": process_id,
        "global_devices": int(mesh.devices.size),
        "trace_ids": sorted(t.trace_id for t in resp.traces),
        "inspected_traces": results.metrics.inspected_traces,
        "inspected_blocks": results.metrics.inspected_blocks,
    }
    with open(os.path.join(root, f"digest-{process_id}.json"), "w") as f:
        json.dump(digest, f)


def run(n_processes: int = 2, devices_per_proc: int = 2,
        timeout_s: float = 300.0) -> dict:
    """Launcher: build corpus, spawn the workers, assert all digests are
    identical and match the host oracle."""
    import socket

    with tempfile.TemporaryDirectory() as root:
        expected = _build_corpus(root)
        with socket.socket() as s:  # free port for the coordinator
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "tempo_tpu.parallel.multihost_dryrun",
                 "--worker", str(pid), str(n_processes), str(port), root,
                 str(devices_per_proc)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))),
            )
            for pid in range(n_processes)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            outs.append(out.decode(errors="replace"))
        for p, out in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"worker failed rc={p.returncode}:\n{out[-4000:]}")
        digests = []
        for pid in range(n_processes):
            with open(os.path.join(root, f"digest-{pid}.json")) as f:
                digests.append(json.load(f))
        base = {k: v for k, v in digests[0].items() if k != "process_id"}
        for d in digests[1:]:
            got = {k: v for k, v in d.items() if k != "process_id"}
            assert got == base, (
                f"process {d['process_id']} diverged:\n{got}\nvs\n{base}")
        assert len(base["trace_ids"]) == expected, (
            len(base["trace_ids"]), expected)
        assert base["inspected_blocks"] == 4
        return {
            "n_processes": n_processes,
            "global_devices": base["global_devices"],
            "matches": len(base["trace_ids"]),
            "expected": expected,
        }


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker_main(int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
                    sys.argv[5], int(sys.argv[6]))
    else:
        out = run()
        print(f"dryrun_multihost: {out['matches']} matches "
              f"(expected {out['expected']}) identical across "
              f"{out['n_processes']} processes / {out['global_devices']} "
              f"global devices — OK")
