from .mesh import make_mesh, scan_mesh_axes
from .dist_search import DistributedScanEngine

__all__ = ["make_mesh", "scan_mesh_axes", "DistributedScanEngine"]
