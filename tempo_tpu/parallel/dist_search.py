"""Distributed scan: shard_map over the page axis + XLA collectives.

The TPU-native replacement for the reference's querier fan-out + Results
channel funnel (SURVEY.md §2.6): pages are sharded across the mesh's
"shards" axis, every device scans its local slice with the same predicate
kernel, then

  - match/inspected counts reduce with lax.psum (the Results counters),
  - per-shard top-k candidates all_gather and re-reduce to a global
    top-k (the frontend's result merge),

so one jit call returns the globally-merged answer on every device with
collectives riding ICI — no host round-trips per shard.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tempo_tpu.search.columnar import ColumnarPages
from tempo_tpu.search.engine import (
    DEVICE_ARRAYS,
    DEFAULT_TOP_K,
    entry_match_mask,
    masked_topk,
    pad_page_axis,
)
from tempo_tpu.search.pipeline import CompiledQuery
from .mesh import SCAN_AXIS


@dataclass
class ShardedPages:
    device: dict          # name -> jnp array sharded over the page axis
    n_pages: int          # real page count (pre-padding)
    pages: ColumnarPages  # host container
    # dict_probe.DeviceDict sharded over the VALUE axis when the block's
    # dictionary cleared the device-probe threshold (and, with the
    # offload planner enabled, its cost model — which charges the mesh
    # probe's all_gather/collective overhead) at staging time
    staged_dict: object = None
    # packed-residency width descriptor (search/packing.py): static per
    # staged block, part of the dist kernel's jit shape key
    widths: tuple | None = None
    # structural span columns (search/structural.py): REPLICATED by
    # default — the parent joins index the global span axis; the
    # structural verdict computes outside shard_map and enters the scan
    # page-sharded. With search_structural_shard_spans the segment
    # reshards trace-whole per page shard (span_sharded=True) and the
    # verdict evaluates INSIDE the shard over the local chunk.
    span_device: dict | None = None
    span_sharded: bool = False


class DistributedScanEngine:
    """Mesh-wide scan engine. API mirrors search.engine.ScanEngine but
    arrays live sharded across devices and the kernel runs under
    shard_map.

    `probe_min_vals`: the device-probe staging threshold, with
    cfg.search_device_probe_min_vals semantics everywhere: None = the
    dict_probe default (50k), <= 0 forces host-only. The PARAMETER
    default is 0 — constructing this engine without the knob keeps its
    historical never-stage-dictionaries behavior (the serving path's
    mesh batching lives in MultiBlockEngine, which has its own
    plumbing)."""

    def __init__(self, mesh: Mesh, top_k: int = DEFAULT_TOP_K,
                 probe_min_vals: int | None = 0):
        self.mesh = mesh
        self.top_k = top_k
        self.n_shards = mesh.devices.size
        self.probe_min_vals = probe_min_vals

    # ---- staging ----

    def stage(self, pages: ColumnarPages) -> ShardedPages:
        """Pad the page axis to a multiple of the shard count and place
        each array with a NamedSharding over the scan axis. Value
        dictionaries above the probe threshold stage value-axis-sharded
        for the mesh probe kernel (planner-vetoed like every other
        staging site — the decision accounts the all_gather cost via its
        n_shards input)."""
        import time

        from tempo_tpu.observability import profile
        from tempo_tpu.search.engine import stage_block_dict

        from tempo_tpu.search import packing

        n = self.n_shards
        B = -(-pages.n_pages // n) * n
        spec = NamedSharding(self.mesh, P(SCAN_AXIS))
        host = pad_page_axis(pages, B)
        widths = None
        if packing.PACKING.enabled:
            # packed residency: the sharded staging packs the same
            # per-column widths the single-block stage would choose
            widths = packing.PACKING.plan_widths(
                len(pages.key_dict), len(pages.val_dict),
                pages.max_dur_ms())
            if widths is not None:
                host = packing.pack_columns(host, widths)
        t0 = time.perf_counter()
        dev = {name: jax.device_put(arr, spec)
               for name, arr in host.items()}
        profile.observe_stage("h2d", "mesh", time.perf_counter() - t0,
                              nbytes=sum(int(v.nbytes)
                                         for v in host.values()))
        sd = stage_block_dict(pages, self.probe_min_vals,
                              n_shards=self.n_shards, mesh=self.mesh)
        from tempo_tpu.search.structural import STRUCTURAL

        span_dev = None
        span_sharded = False
        if STRUCTURAL.enabled:
            span_host = STRUCTURAL.stage_single(pages, B)
            if span_host is not None:
                if STRUCTURAL.shard_spans:
                    sh = STRUCTURAL.shard_span_segment(
                        span_host, self.n_shards, B,
                        pages.geometry.entries_per_page)
                    if sh is not None:
                        # segment-aligned sharding: every span array
                        # splits on its leading axis, aligned with the
                        # page sharding — per-shard span HBM ~1/P
                        span_dev = {k: jax.device_put(v, spec)
                                    for k, v in sh.items()}
                        span_sharded = True
                if span_dev is None:
                    # replicate (P()): parent pointers index the global
                    # span axis, which a page shard cannot see locally
                    rep = NamedSharding(self.mesh, P())
                    span_dev = {k: jax.device_put(v, rep)
                                for k, v in span_host.items()}
        return ShardedPages(device=dev, n_pages=pages.n_pages, pages=pages,
                            staged_dict=sd, widths=widths,
                            span_device=span_dev,
                            span_sharded=span_sharded)

    # ---- kernel ----

    @functools.partial(jax.jit, static_argnames=("self", "n_terms",
                                                 "top_k", "widths",
                                                 "plan", "span_sharded",
                                                 "shard_tail"))
    def _dist_kernel(self, kv_key, kv_val, entry_start, entry_end,
                     entry_dur, entry_valid, term_keys, val_ranges,
                     dur_lo, dur_hi, win_start, win_end, val_hits=None,
                     entry_dur_res=None, span_cols=None, s_tables=None,
                     *, n_terms: int, top_k: int, widths=None,
                     plan=None, span_sharded=False, shard_tail: int = 0):
        E = entry_valid.shape[1]
        local_flat = kv_key.shape[0] // self.n_shards * E
        pages_total = int(kv_key.shape[0])

        struct_mask = None
        sh_span_cols = sh_s_tables = None
        if plan is not None and not span_sharded:
            # structural verdicts evaluate over the REPLICATED span
            # columns outside shard_map (the parent joins index the
            # global span axis), then shard with the page axis below
            from tempo_tpu.search.structural import structural_entry_mask

            page_block = jnp.zeros(entry_valid.shape[0], dtype=jnp.int32)
            struct_mask = structural_entry_mask(
                kv_key, kv_val, entry_dur, entry_valid, page_block,
                entry_dur_res, span_cols, s_tables, plan=plan,
                widths=widths)
        elif plan is not None:
            # segment-aligned sharded spans: the chunk-local columns go
            # INTO the shard region and the joins stay shard-local
            sh_span_cols, sh_s_tables = span_cols, s_tables

        def shard_fn(kv_key, kv_val, entry_start, entry_end, entry_dur,
                     entry_valid, term_keys, val_ranges,
                     dur_lo, dur_hi, win_start, win_end, val_hits,
                     entry_dur_res, struct_mask, sh_span_cols,
                     sh_s_tables):
            if shard_tail:
                # remainder-shard ragged tail (static layout
                # descriptor, search_structural_remainder_pages): the
                # trailing pad pages live on the last shard(s); their
                # entries are already invalid, so this mask is
                # byte-identical — it records the layout in the jit key
                pp = entry_valid.shape[0]
                gpage = (jax.lax.axis_index(SCAN_AXIS).astype(jnp.int32)
                         * pp + jnp.arange(pp, dtype=jnp.int32))
                entry_valid = entry_valid & (
                    gpage < jnp.int32(pages_total - shard_tail))[:, None]
            mask = entry_match_mask(
                kv_key, kv_val, entry_start, entry_end, entry_dur,
                entry_valid, term_keys, val_ranges, dur_lo, dur_hi,
                win_start, win_end, n_terms=n_terms, val_hits=val_hits,
                entry_dur_res=entry_dur_res, widths=widths,
            )
            if struct_mask is not None:
                mask = mask & struct_mask
            if plan is not None and span_sharded:
                from tempo_tpu.search.structural import \
                    structural_entry_mask

                page_block = jnp.zeros(entry_valid.shape[0],
                                       dtype=jnp.int32)
                mask = mask & structural_entry_mask(
                    kv_key, kv_val, entry_dur, entry_valid, page_block,
                    entry_dur_res, sh_span_cols, sh_s_tables, plan=plan,
                    widths=widths)
            local_count = jnp.sum(mask, dtype=jnp.int32)
            local_inspected = jnp.sum(entry_valid, dtype=jnp.int32)
            scores, idx = masked_topk(mask, entry_start, top_k)
            # localize → globalize flat indices
            shard = jax.lax.axis_index(SCAN_AXIS).astype(jnp.int32)
            gidx = idx + shard * local_flat
            # reduce across the mesh: counts psum, candidates all_gather
            count = jax.lax.psum(local_count, SCAN_AXIS)
            inspected = jax.lax.psum(local_inspected, SCAN_AXIS)
            all_scores = jax.lax.all_gather(scores, SCAN_AXIS).reshape(-1)
            all_idx = jax.lax.all_gather(gidx, SCAN_AXIS).reshape(-1)
            k = min(top_k, all_scores.shape[0])
            top_scores, pos = jax.lax.top_k(all_scores, k)
            return count, inspected, top_scores, all_idx[pos]

        from tempo_tpu.parallel.mesh import shard_map_compat

        return shard_map_compat(
            shard_fn, mesh=self.mesh,
            # val_hits (the device-probe hit mask) replicates like the
            # other predicate tables; a None leaf makes its spec a no-op;
            # the packed-duration residual shards with the page axis.
            # Sharded span columns split on their leading axis (chunk-
            # per-shard span axis / page axis); structural parameter
            # tables replicate.
            in_specs=(P(SCAN_AXIS), P(SCAN_AXIS), P(SCAN_AXIS), P(SCAN_AXIS),
                      P(SCAN_AXIS), P(SCAN_AXIS),
                      P(), P(), P(), P(), P(), P(), P(), P(SCAN_AXIS),
                      P(SCAN_AXIS), P(SCAN_AXIS), P()),
            out_specs=(P(), P(), P(), P()),
            # all_gather+top_k yields identical values on every shard, but
            # the replication checker can't infer it through the gather
            check=False,
        )(kv_key, kv_val, entry_start, entry_end, entry_dur, entry_valid,
          term_keys, val_ranges, dur_lo, dur_hi, win_start, win_end,
          val_hits, entry_dur_res, struct_mask, sh_span_cols,
          sh_s_tables)

    # ---- public API ----

    def scan_staged(self, sp: ShardedPages, cq: CompiledQuery):
        from tempo_tpu.observability import profile
        from tempo_tpu.search import query_stats

        # attributed: a query running through the distributed engine
        # bills its mesh dispatch (stages incl. lock_wait) to the
        # active QueryStats — same contract as the batched paths
        with query_stats.attributed_dispatch(), \
                profile.dispatch("mesh") as rec:
            d = sp.device
            k = self.top_k
            while k < cq.limit:
                k *= 2
            from tempo_tpu.search.engine import ScanEngine

            with rec.stage("build"):
                tk, vr, dlo, dhi, ws, we = ScanEngine.query_device_params(cq)
            vh = getattr(cq, "val_hits", None)
            widths = getattr(sp, "widths", None)
            st = getattr(cq, "structural", None)
            plan = None if st is None else st.plan
            s_tables = None if st is None else st.device_tables()
            span_cols = (getattr(sp, "span_device", None)
                         if st is not None else None)
            span_sharded = bool(st is not None
                                and getattr(sp, "span_sharded", False))
            from tempo_tpu.search.structural import STRUCTURAL

            # this engine's staging always pads minimally, but the
            # ragged-tail descriptor only enters the jit key under the
            # remainder-shard gate (off = the historical key exactly)
            shard_tail = 0
            if STRUCTURAL.remainder_pages:
                shard_tail = int(d["kv_key"].shape[0]) - int(sp.n_pages)
            miss = rec.compile_check(
                ("dist", d["kv_key"].shape, str(d["kv_key"].dtype),
                 str(d["kv_val"].dtype), vr.shape,
                 None if vh is None else (tuple(vh.shape), str(vh.dtype)),
                 widths, cq.n_terms, k,
                 None if st is None else st.shape_sig(), span_sharded,
                 shard_tail))
            from tempo_tpu.parallel.mesh import locked_collective

            # process-wide collective-ordering lock (parallel.mesh):
            # shared with the multiblock engine and the dictionary probe,
            # so no two threads can interleave per-device shard_map
            # queues; time queued behind others lands in lock_wait
            stage = "compile" if miss else "execute"
            with locked_collective(rec):
                with rec.stage(stage):
                    out = self._dist_kernel(
                        d["kv_key"], d["kv_val"],
                        d["entry_start"], d["entry_end"], d["entry_dur"],
                        d["entry_valid"],
                        tk, vr, dlo, dhi, ws, we, vh,
                        d.get("entry_dur_res"), span_cols, s_tables,
                        n_terms=cq.n_terms, top_k=k, widths=widths,
                        plan=plan, span_sharded=span_sharded,
                        shard_tail=shard_tail,
                    )
            # fence after releasing the collective lock: a fenced wait
            # under dispatch_lock would stall every other mesh dispatch
            # behind this kernel (lock-order suite); the stage timer
            # accumulates so kernel time still books to compile/execute
            with rec.stage(stage):
                rec.fence(out)
            from tempo_tpu.search.engine import fetch_scan_out

            with rec.stage("d2h"):
                res = fetch_scan_out(out)
            rec.add_bytes(d2h=res[2].nbytes + res[3].nbytes + 8)
            # scan_bytes: the planner's per-byte scan-rate feed (physical
            # staged bytes this dispatch read — packed when packing is on)
            rec.set(n_pages=sp.n_pages, shards=self.n_shards,
                    scan_bytes=sum(int(a.nbytes) for a in d.values()))
        return res

    def scan(self, pages: ColumnarPages, cq: CompiledQuery):
        return self.scan_staged(self.stage(pages), cq)

    def results(self, sp: ShardedPages, cq: CompiledQuery,
                scores: np.ndarray, idx: np.ndarray) -> list:
        from tempo_tpu.search.engine import ScanEngine

        helper = ScanEngine(self.top_k)
        # ShardedPages and StagedPages share the fields results() needs
        return helper.results(sp, cq, scores, idx)
