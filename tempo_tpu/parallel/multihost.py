"""Multi-host wiring: jax.distributed initialization + helpers.

The TPU-native replacement for the reference's cross-process worker
fabric (querier worker pools dialing frontends over gRPC,
modules/querier/worker/worker.go:23-51): hosts join one JAX distributed
runtime, the device mesh spans every host's chips (ICI within a slice,
DCN across — SURVEY.md §2.6), and the scan engine's collectives do the
cross-host reduction that the reference does with response merging.

Config/env contract (cli/config.py `distributed:` section):

    distributed:
      coordinator: "10.0.0.1:8476"   # or ${TEMPO_COORDINATOR}
      num_processes: 8               # or ${TEMPO_NUM_PROCESSES}
      process_id: ${TEMPO_PROCESS_ID}
      cpu_devices_per_host: 0        # >0 = CPU dryrun (gloo collectives)

A v5e-64 deployment (BASELINE config 5) is 16 hosts × 4 chips:
num_processes=16, coordinator on host 0, one process per host; the
"shards" mesh axis then spans all 64 chips.
"""

from __future__ import annotations

import os


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     cpu_devices_per_host: int | str | None = 0) -> bool:
    """Join the JAX distributed runtime. Args fall back to
    TEMPO_COORDINATOR / TEMPO_NUM_PROCESSES / TEMPO_PROCESS_ID env vars.
    Returns True if distributed mode was initialized, False when no
    coordinator is configured (single-host mode — the common case).

    Must run before anything touches jax devices. With
    cpu_devices_per_host > 0 the process simulates that many chips on
    CPU with gloo collectives — the localhost dryrun path
    (__graft_entry__.dryrun_multihost)."""
    coordinator = coordinator or os.environ.get("TEMPO_COORDINATOR", "")
    if not coordinator:
        return False
    # YAML env substitution delivers strings — coerce
    if num_processes is None or num_processes == "":
        num_processes = int(os.environ.get("TEMPO_NUM_PROCESSES", "0")) or None
    else:
        num_processes = int(num_processes)
    if process_id is None or process_id == "":
        pid_env = os.environ.get("TEMPO_PROCESS_ID")
        process_id = int(pid_env) if pid_env is not None else None
    else:
        process_id = int(process_id)
    # empty env substitution / bare YAML key → disabled, like the others
    cpu_devices_per_host = int(cpu_devices_per_host or 0)

    import jax

    if cpu_devices_per_host:
        # config.update, NOT env: the axon sitecustomize imports jax at
        # interpreter start, so JAX_PLATFORMS set in-process is ignored
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", int(cpu_devices_per_host))
        except AttributeError:
            # older jax: XLA_FLAGS still works as long as no backend has
            # initialized yet (this runs before any device op). REPLACE
            # any inherited device-count flag (e.g. the test harness's
            # 8-device setting) — this process must get exactly its own
            import re

            flags = os.environ.get("XLA_FLAGS", "")
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "", flags)
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{int(cpu_devices_per_host)}").strip()
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except AttributeError:
            pass  # older jax: flag spelled differently / unavailable
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def ownership_members() -> tuple[list[str], str]:
    """(fleet member ids, this process's id) for the HBM ownership map
    (search/ownership.py), derived from the distributed env contract
    WITHOUT importing jax — a write-only process must not initialize a
    device backend just to learn the fleet shape. Single-host (no
    TEMPO_NUM_PROCESSES) is a one-member fleet that owns everything.
    Every process derives the identical ordered list, so the placement
    tables agree fleet-wide with zero coordination."""
    n = int(os.environ.get("TEMPO_NUM_PROCESSES", "0") or 0)
    pid = int(os.environ.get("TEMPO_PROCESS_ID", "0") or 0)
    if n > 1:
        return [f"host-{i}" for i in range(n)], f"host-{pid}"
    return ["self"], "self"


def is_multiprocess() -> bool:
    import jax

    return jax.process_count() > 1


def process_index() -> int:
    import jax

    return jax.process_index()
