"""tempo-tpu: a TPU-native distributed tracing backend.

A ground-up rebuild of the capabilities of Shopify/tempo (Grafana Tempo,
FlatBuffer-search era — see /root/repo/SURVEY.md): multi-tenant span
ingestion, WAL-backed immutable block building, object-storage-only
persistence, bloom+index trace-by-ID lookup, compaction/retention, and a
columnar tag-search engine whose hot scan path runs as JAX/XLA kernels on
TPU, sharded over a `jax.sharding.Mesh` with ICI collectives.

Layer map (mirrors SURVEY.md §1, reinterpreted TPU-first):

  backend/    object storage (local, in-memory mock; s3/gcs/azure gated)
  encoding/   immutable block format vT1 (pages, index, bloom)
  tempopb/    wire model (OTLP-compatible protobuf) + helpers
  model/      trace object codecs (v1 raw proto, v2 framed)
  wal/        write-ahead log with crash replay
  search/     columnar search blocks + the JAX scan engine (north star)
  ops/        jax/pallas kernels used by search
  parallel/   device mesh, shard_map distribution, collectives
  db/         tempodb orchestration: blocklist, poller, compaction, pool
  modules/    distributor / ingester / querier / frontend / overrides
  api/        HTTP+gRPC surface
  utils/      hashing, ids, test fabricators
"""

__version__ = "0.1.0"
