"""Kernels and native host runtime.

- `native`: ctypes bindings to the C++ host runtime (codecs, hashing) —
  the counterpart of the reference's vendored Go asm codec libraries.
- JAX/Pallas device kernels used by the search engine live alongside
  (see tempo_tpu.search.engine).
"""
