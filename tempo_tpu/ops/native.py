"""ctypes bindings for the native C++ host runtime (native/libtempotpu.so).

The runtime wraps system libzstd/liblz4/libsnappy block codecs — the
role the reference fills with vendored Go asm codec libraries (klauspost
zstd/s2/snappy, pierrec lz4 — SURVEY.md §7 native mapping). Build with
``make -C native`` (see native/Makefile); everything degrades gracefully
to pure-python paths when the .so is absent.
"""

from __future__ import annotations

import ctypes
import os
import struct

_LIB = None
_TRIED = False

_SO_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "libtempotpu.so"),
    os.path.join(os.path.dirname(__file__), "libtempotpu.so"),
]


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    rebuilt = False
    for attempt in (0, 1):
        for p in _SO_PATHS:
            p = os.path.abspath(p)
            if os.path.exists(p):
                try:
                    lib = ctypes.CDLL(p)
                    _bind(lib)
                    _LIB = lib
                    return _LIB
                except AttributeError:
                    # stale .so missing a newer REQUIRED symbol — rebuild
                    # once, then give up gracefully (fallback paths take
                    # over). The unlink is best-effort: a read-only
                    # install must degrade, not crash the first caller
                    if not rebuilt:
                        rebuilt = True
                        try:
                            os.unlink(p)
                        except OSError:
                            pass
                        _try_build()
                        continue
                except OSError:
                    continue
        if attempt == 0 and not rebuilt:
            _try_build()
    return _LIB


def _try_build():
    """The .so is not committed (platform-specific); build it on first use
    when a toolchain is present."""
    import subprocess

    native_dir = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..", "native")
    )
    if not os.path.exists(os.path.join(native_dir, "Makefile")):
        return
    try:
        subprocess.run(["make", "-C", native_dir], capture_output=True,
                       timeout=120, check=False)
    except (OSError, subprocess.TimeoutExpired):
        pass


def _bind(lib):
    for name in ("tt_zstd_compress", "tt_zstd_decompress",
                 "tt_lz4_compress", "tt_lz4_decompress",
                 "tt_snappy_compress", "tt_snappy_decompress"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_longlong
        fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                       ctypes.c_char_p, ctypes.c_size_t]
    lib.tt_zstd_compress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                     ctypes.c_char_p, ctypes.c_size_t,
                                     ctypes.c_int]
    # OPTIONAL symbol (added r4): a stale .so without it must still bind
    # — zstd_decompress falls back to the grow loop, nothing is lost
    try:
        lib.tt_zstd_content_size.restype = ctypes.c_longlong
        lib.tt_zstd_content_size.argtypes = [ctypes.c_char_p,
                                             ctypes.c_size_t]
    except AttributeError:
        pass
    lib.tt_xxhash64.restype = ctypes.c_ulonglong
    lib.tt_xxhash64.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_ulonglong]
    lib.tt_crc32c.restype = ctypes.c_uint
    lib.tt_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint]
    # OPTIONAL symbol (added r5): stale .so must still bind
    try:
        lib.tt_ingest_regroup.restype = ctypes.c_longlong
        lib.tt_ingest_regroup.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_longlong,
            ctypes.c_char_p, ctypes.c_size_t,
        ]
    except AttributeError:
        pass
    # OPTIONAL symbol (span-section variant): a stale .so without it
    # still binds — structural-gated ingest then falls back to the
    # Python walk, everything else keeps the native fast path
    try:
        lib.tt_ingest_regroup2.restype = ctypes.c_longlong
        lib.tt_ingest_regroup2.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_char_p, ctypes.c_size_t,
        ]
    except AttributeError:
        pass
    lib.tt_substr_scan.restype = ctypes.c_longlong
    lib.tt_substr_scan.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
        ctypes.c_char_p, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_int), ctypes.c_longlong,
    ]


def available() -> bool:
    return _load() is not None


_LEN_HDR = struct.Struct("<Q")  # uncompressed length prefix for lz4/snappy raw blocks


class NativeBufferTooSmall(RuntimeError):
    pass


def _roundtrip(fn_name: str, data: bytes, bound: int, *extra) -> bytes:
    lib = _load()
    out = ctypes.create_string_buffer(bound)
    n = getattr(lib, fn_name)(data, len(data), out, bound, *extra)
    if n == -2:
        raise NativeBufferTooSmall(fn_name)
    if n < 0:
        raise RuntimeError(f"{fn_name} failed ({n})")
    return out.raw[:n]


def zstd_compress(data: bytes, level: int = 3) -> bytes:
    return _roundtrip("tt_zstd_compress", data, len(data) + (len(data) >> 6) + 1024, level)


# corrupt/hostile frame headers must not drive allocations: nothing we
# write exceeds this (pages ~1 MiB, completion flush 30 MiB)
_ZSTD_MAX_ONESHOT = 1 << 30


def zstd_decompress(data: bytes) -> bytes:
    # frames from our compressor declare their content size: allocate
    # EXACTLY once. The 32x-guess-and-grow loop (which zeroed a 32 MB
    # buffer per 1 MB page) remains for sizeless/concatenated foreign
    # frames, stale libraries without the size symbol, and declared
    # sizes a corrupt header inflated past the sanity cap.
    size_fn = getattr(_load(), "tt_zstd_content_size", None)
    if size_fn is not None:
        size = size_fn(data, len(data))
        if 0 <= size <= _ZSTD_MAX_ONESHOT:
            try:
                return _roundtrip("tt_zstd_decompress", data,
                                  max(1, int(size)))
            except NativeBufferTooSmall:
                pass  # multi-frame input: header size < total output
        elif size == -1:
            raise RuntimeError("zstd decompress failed: not a zstd frame")
    bound = max(1 << 16, len(data) * 32)
    for _ in range(4):
        try:
            return _roundtrip("tt_zstd_decompress", data, bound)
        except NativeBufferTooSmall:
            bound *= 8
    raise RuntimeError("zstd decompress failed: frame too large")


def lz4_compress(data: bytes) -> bytes:
    body = _roundtrip("tt_lz4_compress", data, len(data) + (len(data) // 255) + 64)
    return _LEN_HDR.pack(len(data)) + body


def lz4_decompress(data: bytes) -> bytes:
    (n,) = _LEN_HDR.unpack_from(data)
    return _roundtrip("tt_lz4_decompress", data[_LEN_HDR.size:], int(n))


def snappy_compress(data: bytes) -> bytes:
    body = _roundtrip("tt_snappy_compress", data, len(data) + (len(data) // 6) + 64)
    return _LEN_HDR.pack(len(data)) + body


def snappy_decompress(data: bytes) -> bytes:
    (n,) = _LEN_HDR.unpack_from(data)
    return _roundtrip("tt_snappy_decompress", data[_LEN_HDR.size:], int(n))


def xxhash64(data: bytes, seed: int = 0) -> int:
    lib = _load()
    return int(lib.tt_xxhash64(data, len(data), seed))


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _load()
    return int(lib.tt_crc32c(data, len(data), crc))


class InvalidTraceId(ValueError):
    """Native walker saw a span with a 0- or >16-byte trace id; the
    caller re-runs the Python path so the user-visible error matches."""


def ingest_regroup(batch_blobs: list, max_search_bytes: int,
                   spans: bool = False, max_spans: int = 512,
                   max_span_kvs: int = 16):
    """Native single-pass regroup + search-data extraction over
    SERIALIZED ResourceSpans (tt_ingest_regroup). Returns
    (n_spans, [(padded_tid, start_s, end_s, segment, search_data)],
    summaries) where `summaries` is the raw per-span feed for the
    metrics generator (string table + 56B rows; decoded off the ack
    path by generator.push_summary_blob). None when the loaded .so
    predates the symbol (stale build) — callers fall back to the
    Python walk.

    ``spans=True`` (the structural-engine ingest path) additionally
    emits the per-trace SPAN SECTION into each search_data payload
    (tt_ingest_regroup2, byte-identical to the Python
    collect_span_rows walk, capped at max_spans/max_span_kvs); when
    the loaded .so predates that symbol, returns None so the caller
    keeps the Python walk."""
    lib = _load()
    if lib is None or not hasattr(lib, "tt_ingest_regroup"):
        return None
    if spans and not hasattr(lib, "tt_ingest_regroup2"):
        return None
    src = b"".join(_LEN32.pack(len(b)) + b for b in batch_blobs)
    cap = max(4096, len(src) * 2 + 1024)
    while True:
        dst = ctypes.create_string_buffer(cap)
        if spans:
            got = lib.tt_ingest_regroup2(
                src, len(src), max_search_bytes, 1,
                int(max_spans), int(max_span_kvs), dst, cap)
        else:
            got = lib.tt_ingest_regroup(src, len(src), max_search_bytes,
                                        dst, cap)
        if got == -3:
            cap *= 2
            continue
        if got == -4:
            raise InvalidTraceId("invalid trace id length")
        if got < 0:
            raise RuntimeError(f"tt_ingest_regroup failed ({got})")
        buf = dst.raw[:got]
        break
    n_traces, n_spans = _LEN32.unpack_from(buf, 0)[0], \
        _LEN32.unpack_from(buf, 4)[0]
    out = []
    off = 8
    for _ in range(n_traces):
        tid = buf[off:off + 16]
        start_s, end_s = struct.unpack_from("<II", buf, off + 16)
        off += 24
        (seg_len,) = _LEN32.unpack_from(buf, off)
        off += 4
        seg = buf[off:off + seg_len]
        off += seg_len
        (sd_len,) = _LEN32.unpack_from(buf, off)
        off += 4
        sd = buf[off:off + sd_len]
        off += sd_len
        out.append((tid, start_s, end_s, seg, sd))
    return n_spans, out, buf[off:]


_LEN32 = struct.Struct("<I")


def substr_scan(packed: bytes, offsets, needle: bytes):
    """Ids of packed-dictionary strings containing `needle`.
    `offsets` is an int64 numpy array of n+1 byte offsets."""
    import numpy as np

    lib = _load()
    n = len(offsets) - 1
    cap = max(1024, n // 8)
    off_p = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
    while True:
        out = np.empty(cap, dtype=np.int32)
        got = lib.tt_substr_scan(
            packed, off_p, n, needle, len(needle),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), cap,
        )
        if got == -2:
            cap = min(n, cap * 8)
            continue
        if got < 0:
            raise RuntimeError(f"tt_substr_scan failed ({got})")
        return out[:got].copy()
