"""Jit-purity lint: no host round-trips inside kernel functions.

TiLT (arxiv 2301.12030) gets this property by CONSTRUCTION — queries
lower to kernels with static shapes and no host round-trips mid-kernel.
This codebase writes its kernels by hand, so the same discipline is
enforced as lint over every function that reaches ``jax.jit`` or
``shard_map_compat`` (including nested defs like vmap/fori_loop bodies
and same-module helpers such as ``multi_entry_mask``):

  - no clock reads (``time.time()`` traces once and freezes — the value
    is a compile-time constant, almost never what the author meant);
  - no ``.item()`` / ``int()`` / ``float()`` on tracer values (host
    sync mid-trace: TracerConversionError at best, a silent d2h fence
    at worst);
  - no ``np.asarray`` / ``np.array`` on tracers (host materialization);
  - no Python ``if``/``while`` on tracer values (ConcretizationTypeError
    — the branch must be ``jnp.where`` / ``lax.cond``). ``x is None``
    tests are exempt: None-ness is static at trace time.

Cache-key hygiene rides along: a ``jax.jit`` kernel's keyword-only args
are this codebase's shape-affecting knobs (``n_terms``, ``top_k``,
``n_needle_max``) — every one must be in ``static_argnames``, or each
distinct VALUE becomes a silent retrace. The pow2-padding helpers
(``_pow2``, ``stack_queries``, ``stage_host``) exist so those statics
take log-many values; the checker pins the static declaration, bench
pins the compile counts.

Taint model (deliberately simple, tuned to this codebase's kernels):
parameters minus statics are tracers; assignments propagate taint,
EXCEPT through ``.shape``/``.ndim``/``.dtype``/``.size``/``len()``
reads, which are static under jit. Closure variables from an enclosing
kernel keep the enclosing classification.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Checker, Finding, Module, Package

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
_CLOCK_MODS = {"time", "_time"}
_NP_NAMES = {"np", "numpy"}
_NP_HOST_FNS = {"asarray", "array", "frombuffer", "copy"}
# packed-residency width-descriptor parameter names (search/packing.py
# unpack helpers + the kernels' `widths` static) AND the structural
# query engine's plan descriptors (search/structural.py `plan` — the
# compiled query tree the kernel lowering recurses over at trace time):
# a descriptor decides SHAPES and branch structure at trace time, so a
# tracer reaching one is a guaranteed ConcretizationTypeError — and a
# non-static python value would silently retrace per distinct value.
# The rule only fires for helpers that actually BRANCH on the parameter
# (descriptor dispatchers) — a numeric parameter that merely shares a
# name (`def weighted(x, w)`) is ordinary traced data, not a
# descriptor. `span_sharded` is the span-layout descriptor (segment-
# aligned span sharding): the dist kernels and any helper that selects
# the replicated-vs-sharded evaluation placement branch on it at trace
# time — a tracer reaching it would pick a layout per VALUE, exactly
# the retrace/concretization failure the widths rule exists for. The
# stacked plan descriptor (plan-shape stacking) rides the existing
# `plan` entry: the coalesced kernels thread the same static plan.
# `bucket` is the shape-bucket descriptor (shape-bucketed cross-plan
# stacking): the bucketed evaluator unpacks slot tiers and the has-
# relations arm from it at trace time. `shard_tail` is the ragged-tail
# layout descriptor (remainder-shard staging): the dist kernels select
# the tail-masking arm on it — both decide branch structure exactly
# like `span_sharded` and must stay in the static jit key. `tier` is
# the hot-tier page-capacity descriptor (live-tier rolling stages): the
# hot dispatch selects the capacity-masking arm on it at trace time,
# and keeping it static is what makes absorbs within a capacity tier
# re-enter the same compiled kernel instead of retracing per size.
# `buckets` is the analytics count kernel's two-limb latency-threshold
# descriptor and `agg`/`n_keys` the ?agg= dense key-space sizes
# (search/analytics.py): all three select the aggregate-reduction arm
# and size its key range at trace time, so they belong to the static
# jit key for exactly the `widths`/`plan` reason.
_DESCRIPTOR_PARAMS = {"w", "dw", "widths", "plan", "span_sharded",
                      "bucket", "shard_tail", "tier", "buckets", "agg",
                      "n_keys"}


def _branches_on_param(helper: ast.AST, param: str) -> bool:
    """Does the helper's body test `param` in an if/while condition (or
    compare it / call methods on it inside one)? That is the descriptor-
    dispatcher shape the taint rule exists for."""
    for node in ast.walk(helper):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            for n in ast.walk(node.test):
                if isinstance(n, ast.Name) and n.id == param:
                    return True
    return False


@dataclass
class _KernelRoot:
    mod: Module
    qual: str
    node: ast.AST
    statics: frozenset       # static (non-tracer) parameter names
    via: str                 # "jax.jit" | "shard_map"


def _decorator_jit_statics(dec: ast.AST):
    """static_argnames from @jax.jit / @functools.partial(jax.jit, ...);
    None when the decorator isn't a jit form."""
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return frozenset()
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return frozenset()
    if isinstance(dec, ast.Call):
        fn = dec.func
        is_partial = (isinstance(fn, ast.Attribute)
                      and fn.attr == "partial") or \
                     (isinstance(fn, ast.Name) and fn.id == "partial")
        if is_partial and dec.args:
            inner = dec.args[0]
            if (isinstance(inner, ast.Attribute) and inner.attr == "jit") \
                    or (isinstance(inner, ast.Name) and inner.id == "jit"):
                for kw in dec.keywords:
                    if kw.arg in ("static_argnames", "static_argnums"):
                        names = set()
                        for el in ast.walk(kw.value):
                            if isinstance(el, ast.Constant) \
                                    and isinstance(el.value, str):
                                names.add(el.value)
                        return frozenset(names)
                return frozenset()
        # jax.jit(fn, static_argnames=...) used as a decorator factory
        if isinstance(fn, ast.Attribute) and fn.attr == "jit":
            names = set()
            for kw in dec.keywords:
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        names.add(el.value)
            return frozenset(names)
    return None


def _params(func: ast.AST) -> list:
    a = func.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + ([a.vararg.arg] if a.vararg else [])
            + [p.arg for p in a.kwonlyargs]
            + ([a.kwarg.arg] if a.kwarg else []))


class JitPurityChecker(Checker):
    id = "jit-purity"
    helper_depth = 2

    def check(self, pkg: Package) -> list[Finding]:
        findings: list[Finding] = []
        roots = list(self._roots(pkg))
        seen: set = set()
        for root in roots:
            self._check_kernel(pkg, root.mod, root.qual, root.node,
                               root.statics, findings, seen,
                               depth=0, root_desc=root.via)
            if root.via == "jax.jit":
                self._check_static_decl(root, findings)
        return findings

    # ---- discovery ----

    def _roots(self, pkg: Package):
        for mod, qual, node in pkg.functions():
            statics = None
            for dec in getattr(node, "decorator_list", []):
                statics = _decorator_jit_statics(dec)
                if statics is not None:
                    break
            if statics is not None:
                yield _KernelRoot(mod, qual, node, statics, "jax.jit")
        # functions passed (by name) to shard_map_compat/shard_map:
        # resolve within the defining scope — the idiom is a nested
        # shard_fn def handed to the wrapper a few lines later
        for mod, qual, node in pkg.functions():
            local_defs = {
                ch.name: ch for ch in ast.walk(node)
                if isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef))
                and ch is not node
            }
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) or not call.args:
                    continue
                fn = call.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if name not in ("shard_map_compat", "shard_map"):
                    continue
                arg0 = call.args[0]
                if isinstance(arg0, ast.Name) and arg0.id in local_defs:
                    yield _KernelRoot(
                        mod, f"{qual}.{arg0.id}", local_defs[arg0.id],
                        frozenset(), "shard_map")

    # ---- per-kernel analysis ----

    def _check_kernel(self, pkg: Package, mod: Module, qual: str,
                      func: ast.AST, statics: frozenset, findings: list,
                      seen: set, depth: int, root_desc: str,
                      closure_tainted: frozenset = frozenset()) -> None:
        key = (mod.dotted, qual, statics)
        if key in seen:
            return
        seen.add(key)
        tainted = set(p for p in _params(func) if p not in statics)
        tainted |= set(closure_tainted)

        def expr_tainted(expr: ast.AST) -> bool:
            """Does this expression carry tracer data? Names read only
            through shape/dtype accessors or len() don't."""
            stack = [(expr, False)]
            while stack:
                node, shielded = stack.pop()
                if isinstance(node, ast.Attribute) \
                        and node.attr in _SHAPE_ATTRS:
                    shielded = True
                elif isinstance(node, ast.Call):
                    fn = node.func
                    if isinstance(fn, ast.Name) and fn.id == "len":
                        shielded = True
                elif isinstance(node, ast.Name) and not shielded:
                    if node.id in tainted:
                        return True
                elif isinstance(node, (ast.Lambda, ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                stack.extend((c, shielded)
                             for c in ast.iter_child_nodes(node))
            return False

        def is_none_test(test: ast.AST) -> bool:
            """`x is None` / `x is not None` (possibly and-ed): static
            at trace time."""
            if isinstance(test, ast.BoolOp):
                return all(is_none_test(v) for v in test.values)
            if isinstance(test, ast.UnaryOp) \
                    and isinstance(test.op, ast.Not):
                return is_none_test(test.operand)
            return (isinstance(test, ast.Compare)
                    and len(test.ops) == 1
                    and isinstance(test.ops[0], (ast.Is, ast.IsNot))
                    and isinstance(test.comparators[0], ast.Constant)
                    and test.comparators[0].value is None)

        def flag(node, kind: str, msg: str, hint: str) -> None:
            findings.append(Finding(
                checker=self.id, path=mod.rel, line=node.lineno,
                message=f"{qual}() [reaches {root_desc}]: {msg}",
                hint=hint,
                key=f"{kind}:{qual}:{msg[:60]}"))

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # vmap/fori_loop body: same kernel context; its own
                    # params are tracers, closure taint flows in
                    self._check_kernel(
                        pkg, mod, f"{qual}.{stmt.name}", stmt,
                        frozenset(), findings, seen, depth, root_desc,
                        closure_tainted=frozenset(tainted))
                    continue
                if isinstance(stmt, (ast.If, ast.While)):
                    if not is_none_test(stmt.test) \
                            and expr_tainted(stmt.test):
                        kw = ("while" if isinstance(stmt, ast.While)
                              else "if")
                        flag(stmt, "tracer-branch",
                             f"Python `{kw}` on a tracer value — the "
                             "branch runs at TRACE time, not on device "
                             "(ConcretizationTypeError or a silently "
                             "frozen branch)",
                             "use jnp.where / jax.lax.cond / "
                             "jax.lax.fori_loop, or make the value a "
                             "static_argnames kwarg")
                    walk(stmt.body)
                    walk(stmt.orelse)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    if expr_tainted(stmt.iter):
                        flag(stmt, "tracer-iter",
                             "Python `for` over a tracer — the loop "
                             "unrolls at trace time over unknown length",
                             "use jax.lax.fori_loop / scan")
                    else:
                        # loop variables of a static-range loop stay
                        # static (for t in range(n_terms))
                        pass
                    walk(stmt.body)
                    walk(stmt.orelse)
                    continue
                if isinstance(stmt, ast.Try):
                    for block in (stmt.body, stmt.orelse, stmt.finalbody):
                        walk(block)
                    for h in stmt.handlers:
                        walk(h.body)
                    continue
                if isinstance(stmt, ast.With):
                    walk(stmt.body)
                    continue
                # taint propagation through simple assignment
                if isinstance(stmt, ast.Assign) and stmt.value is not None:
                    src_tainted = expr_tainted(stmt.value)
                    for tgt in stmt.targets:
                        for nm in ast.walk(tgt):
                            if isinstance(nm, ast.Name):
                                if src_tainted:
                                    tainted.add(nm.id)
                                else:
                                    tainted.discard(nm.id)
                self._scan_calls(pkg, mod, qual, stmt, tainted,
                                 expr_tainted, flag, findings, seen,
                                 depth, root_desc)

        walk(getattr(func, "body", []))

    def _scan_calls(self, pkg, mod, qual, stmt, tainted, expr_tainted,
                    flag, findings, seen, depth, root_desc) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Lambda,)):
                continue
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in ("time", "perf_counter", "monotonic") \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id in _CLOCK_MODS:
                    flag(node, "clock",
                         f"clock read (time.{fn.attr}()) inside a jit "
                         "body — traces ONCE and freezes as a constant",
                         "take timestamps outside the kernel and pass "
                         "them in as arguments")
                elif fn.attr == "item":
                    flag(node, "item",
                         ".item() inside a jit body — host sync on a "
                         "tracer",
                         "keep the value on device; sync after the "
                         "kernel returns")
                elif fn.attr in _NP_HOST_FNS \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id in _NP_NAMES \
                        and any(expr_tainted(a) for a in node.args):
                    flag(node, "np-host",
                         f"np.{fn.attr}() on a tracer inside a jit body "
                         "— host materialization mid-trace",
                         "use jnp (stays on device), or hoist the "
                         "conversion out of the kernel")
            elif isinstance(fn, ast.Name):
                if fn.id in ("int", "float", "bool") and node.args \
                        and expr_tainted(node.args[0]):
                    flag(node, "scalar-sync",
                         f"{fn.id}() on a tracer inside a jit body — "
                         "forces a host sync (TracerConversionError "
                         "under jit)",
                         "keep it as a 0-d device array, or make the "
                         "source value static")
                elif depth < self.helper_depth:
                    callee = self._resolve_helper(pkg, mod, fn.id)
                    if callee is not None:
                        helper_mod, helper_qual, helper_node = callee
                        # width descriptors must be STATIC: a helper
                        # whose descriptor param receives tracer data
                        # would branch on it at trace time (the packed-
                        # residency unpack helpers all do; helpers that
                        # never branch on the name are exempt)
                        hp = _params(helper_node)
                        bad = [
                            hp[i] for i, a in enumerate(node.args)
                            if i < len(hp) and hp[i] in _DESCRIPTOR_PARAMS
                            and expr_tainted(a)
                            and _branches_on_param(helper_node, hp[i])
                        ] + [
                            kw.arg for kw in node.keywords
                            if kw.arg in _DESCRIPTOR_PARAMS
                            and expr_tainted(kw.value)
                            and _branches_on_param(helper_node, kw.arg)
                        ]
                        for p in bad:
                            flag(node, "descriptor-taint",
                                 f"passes tracer data as width "
                                 f"descriptor {p!r} of {fn.id}() — "
                                 "descriptors select shapes/branches "
                                 "at trace time and must be static",
                                 "thread the descriptor through "
                                 "static_argnames (the `widths` jit "
                                 "static) instead of a traced value")
                        statics = self._classify_call(helper_node, node,
                                                      expr_tainted)
                        self._check_kernel(
                            pkg, helper_mod, helper_qual, helper_node,
                            statics, findings, seen, depth + 1,
                            root_desc)

    def _resolve_helper(self, pkg: Package, mod: Module, name: str):
        """A called helper analyzed in kernel context: same module
        first, then an imported package symbol."""
        for m, qual, node in pkg.functions():
            if m is mod and qual == name:
                return (m, qual, node)
        for stmt in ast.walk(mod.tree):
            if isinstance(stmt, ast.ImportFrom) and stmt.module:
                for alias in stmt.names:
                    if (alias.asname or alias.name) != name:
                        continue
                    base = stmt.module
                    if stmt.level:
                        parts = mod.dotted.split(".")
                        parts = parts[: len(parts) - stmt.level]
                        base = ".".join(parts + [stmt.module])
                    target = pkg.by_dotted.get(base)
                    if target is None:
                        continue
                    for m, qual, node in pkg.functions():
                        if m is target and qual == alias.name:
                            return (m, qual, node)
        return None

    @staticmethod
    def _classify_call(helper: ast.AST, call: ast.Call,
                       expr_tainted) -> frozenset:
        """Helper params bound to NON-tracer actuals are static for
        this call's analysis."""
        params = _params(helper)
        statics = set()
        for i, arg in enumerate(call.args):
            if i < len(params) and not expr_tainted(arg):
                statics.add(params[i])
        for kw in call.keywords:
            if kw.arg and not expr_tainted(kw.value):
                statics.add(kw.arg)
        return frozenset(statics)

    # ---- cache-key hygiene ----

    def _check_static_decl(self, root: _KernelRoot,
                           findings: list) -> None:
        """Keyword-only args of a jit kernel are the shape-affecting
        knobs in this codebase (n_terms, top_k, ...): each must be
        declared static, or every distinct value silently retraces AND
        the pow2-padding discipline (dict_probe._pow2 bucketing) stops
        bounding the compile count."""
        kwonly = [p.arg for p in root.node.args.kwonlyargs]
        missing = [p for p in kwonly if p not in root.statics]
        for p in missing:
            findings.append(Finding(
                checker=self.id, path=root.mod.rel,
                line=root.node.lineno,
                message=(f"{root.qual}() keyword-only arg {p!r} is not "
                         "in static_argnames — shape-affecting kwargs "
                         "must be static or every value retraces"),
                hint="add it to static_argnames and route callers "
                     "through the pow2-padding helpers so it takes "
                     "log-many values",
                key=f"static-decl:{root.qual}:{p}"))
