"""Noop-contract checker: "knob off = one attribute read, byte-identical
output" — enforced statically.

Every observability/robustness layer in this codebase carries the same
contract: with its gate knob off, the hot path pays ONE attribute read
and nothing else — no clock read, no lock acquire, no metric write, no
allocation-heavy record protocol. Bench asserts the <2% overhead
dynamically; this checker pins the SHAPE that makes it true:

``gated-function`` rules
    a function that IS the gate (``profile.dispatch``,
    ``query_stats.begin``, ``breaker.allow_device`` ...) must test its
    gate expression before any clock read, lock acquire, or metric
    write. Work placed before the gate runs on the disabled path too —
    exactly the drift the contract forbids.

``guarded-call`` rules
    a record-protocol call (``FAULTS.hit``, ``TELEMETRY.record_*``,
    ``self.coalescer.submit``) must be dominated by its gate test —
    either lexically inside an ``if`` mentioning the gate, or after an
    early-return gate in an enclosing block. Call sites gate so the
    disarmed steady state never even enters the registry.

Both registries are data (:data:`GATED_FUNCTIONS`,
:data:`GUARDED_CALLS`): a new knob is one declaration, and the fixture
self-tests construct the checker with their own registries.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Checker, Finding, Package

_CLOCK_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
                "thread_time"}
_METRIC_WRITE_ATTRS = {"inc", "observe", "set"}


@dataclass(frozen=True)
class GatedFunction:
    """``qualname`` in ``module`` must test ``gate_attrs`` (any of them)
    before clock/lock/metric work. ``knob`` names the config knob the
    gate implements — it appears in the finding so the operator-facing
    contract is traceable."""

    module: str             # dotted, e.g. tempo_tpu.observability.profile
    qualname: str           # e.g. DispatchProfiler.dispatch
    gate_attrs: tuple       # attr names that constitute the gate test
    knob: str


@dataclass(frozen=True)
class GuardedCall:
    """Calls ``<receiver>.<method>`` (method exact or a listed prefix)
    must be dominated by a test mentioning ``guard_attr`` (on any
    receiver — the idiom is one singleton, but ``self.x is not None``
    guards match through ``guard_name``)."""

    receiver: str           # terminal name of the receiver, e.g. FAULTS
    methods: tuple          # exact names
    method_prefixes: tuple  # prefixes, e.g. ("record_",)
    guard_attr: str         # e.g. "active", "enabled"
    guard_name: str         # name whose mention in a test also guards
    knob: str


GATED_FUNCTIONS = (
    GatedFunction("tempo_tpu.observability.profile",
                  "DispatchProfiler.dispatch", ("enabled",),
                  "search_profiling_enabled"),
    GatedFunction("tempo_tpu.observability.profile",
                  "DispatchProfiler.observe_stage", ("enabled",),
                  "search_profiling_enabled"),
    GatedFunction("tempo_tpu.search.query_stats", "begin", ("enabled",),
                  "search_query_stats_enabled"),
    GatedFunction("tempo_tpu.robustness.breaker",
                  "CircuitBreaker.allow_device", ("enabled", "_state"),
                  "search_breaker_enabled"),
    GatedFunction("tempo_tpu.robustness.breaker",
                  "CircuitBreaker.record_success", ("enabled", "_state"),
                  "search_breaker_enabled"),
    GatedFunction("tempo_tpu.robustness.dispatch", "DispatchGuard.run",
                  ("enabled", "active"), "search_breaker_enabled"),
    # owner-routed HBM: every placement lookup is internally gated, so
    # ownership disabled costs one attribute read wherever it is
    # consulted (the batcher additionally guards its call sites — see
    # the OWNERSHIP guarded-call rule below)
    GatedFunction("tempo_tpu.search.ownership", "OwnershipMap.owns_group",
                  ("enabled",), "search_hbm_ownership_enabled"),
    GatedFunction("tempo_tpu.search.ownership", "OwnershipMap.owns_block",
                  ("enabled",), "search_hbm_ownership_enabled"),
    GatedFunction("tempo_tpu.search.ownership",
                  "OwnershipMap.owner_index", ("enabled",),
                  "search_hbm_ownership_enabled"),
    # heat-adaptive replication: with rf <= 1 the heat table never
    # records (no clock read, no lock), replica lookups return empty
    # after one attribute read, and the demotion sweep is a no-op —
    # rf=1 placement stays bit for bit the single-owner behavior
    GatedFunction("tempo_tpu.search.ownership",
                  "OwnershipMap.record_access", ("replicated",),
                  "search_hbm_ownership_hot_rate"),
    GatedFunction("tempo_tpu.search.ownership",
                  "OwnershipMap.replica_indices", ("replicated",),
                  "search_hbm_ownership_rf"),
    GatedFunction("tempo_tpu.search.ownership",
                  "OwnershipMap.replicas_of", ("replicated",),
                  "search_hbm_ownership_rf"),
    GatedFunction("tempo_tpu.search.ownership",
                  "OwnershipMap.sweep", ("replicated",),
                  "search_hbm_ownership_hot_rate"),
    GatedFunction("tempo_tpu.search.ownership",
                  "OwnershipMap.is_replica", ("enabled",),
                  "search_hbm_ownership_enabled"),
    # hedged dispatch: the disarmed timer (rf <= 1) must not read a
    # clock, take its lock, or update the Jacobson/Karels estimate —
    # one attribute read per call site
    GatedFunction("tempo_tpu.search.ownership", "HedgeTimer.observe",
                  ("armed",), "search_hbm_ownership_rf"),
    GatedFunction("tempo_tpu.search.ownership", "HedgeTimer.delay_s",
                  ("armed",), "search_hedge_delay_ms"),
    GatedFunction("tempo_tpu.search.ownership", "HedgeTimer._on_stage",
                  ("armed",), "search_hbm_ownership_rf"),
    # packed HBM residency: width planning and mask packing are the
    # gate functions — disabled staging pays one attribute read and
    # keeps the byte-identical legacy layout
    GatedFunction("tempo_tpu.search.packing",
                  "PackedResidency.plan_widths", ("enabled",),
                  "search_packed_residency"),
    GatedFunction("tempo_tpu.search.packing",
                  "PackedResidency.pack_hits", ("enabled",),
                  "search_packed_residency"),
    # structural query engine: the per-request gate — disabled search
    # paths pay one attribute read and return None before any tag get,
    # parse, or cache touch
    GatedFunction("tempo_tpu.search.structural", "structural_query",
                  ("enabled",), "search_structural_enabled"),
    # plan-shape query stacking: the coalescer's grouping gate — with
    # stacking off, a structural submit reads one attribute and takes
    # the solo-flush path, never computing a group key
    GatedFunction("tempo_tpu.search.structural",
                  "StructuralGate.stack_group_key", ("stack_enabled",),
                  "search_structural_stack_enabled"),
    # segment-aligned span sharding: the placement-time reshard gate —
    # off means one attribute read and the byte-identical replicated
    # span layout at every staging site
    GatedFunction("tempo_tpu.search.structural",
                  "StructuralGate.shard_span_segment", ("shard_spans",),
                  "search_structural_shard_spans"),
    # shape-bucketed cross-plan stacking: the canonicalization gate —
    # off means one attribute read and stack_group_key keeps the
    # byte-identical exact-plan grouping
    GatedFunction("tempo_tpu.search.structural",
                  "StructuralGate.bucket_group_key", ("bucket_enabled",),
                  "search_structural_bucket_enabled"),
    # remainder-shard mesh layout: the staging pad gate — off means one
    # attribute read and the pow2 page-axis layout exactly as before
    GatedFunction("tempo_tpu.search.structural",
                  "StructuralGate.remainder_pad", ("remainder_pages",),
                  "search_structural_remainder_pages"),
    # hot-tier live search: every ingest/search/poll hook is internally
    # gated — disabled deployments pay one attribute read per push, per
    # cut, per search leg, and the legacy per-entry walk stays
    # byte-identical (tests/test_live_tier.py asserts the identity)
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.absorb",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.mark_cut",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.drop_tenant",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier",
                  "LiveTier.mark_poll_visible", ("enabled",),
                  "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.poll_visible",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.search",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.subscribe",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.unsubscribe",
                  ("enabled",), "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier",
                  "LiveTier.has_subscribers", ("enabled",),
                  "search_live_tier_enabled"),
    GatedFunction("tempo_tpu.search.live_tier", "LiveTier.notify_push",
                  ("enabled",), "search_live_tier_enabled"),
    # device-side aggregate analytics: the ingest hook gates first —
    # the default-off deployment's push-ack path pays one attribute
    # read before any blob decode, clock read, or planner touch
    GatedFunction("tempo_tpu.search.analytics",
                  "AnalyticsEngine.consume_blob", ("enabled",),
                  "search_analytics_enabled"),
    # dogfood self-ingest: span lowering and query-stat annotation only
    # run when self-traces actually flow into the `_selftrace` tenant —
    # the default-off deployment pays one attribute read before any
    # tracer lookup, clock read, or span synthesis
    GatedFunction("tempo_tpu.observability.selftrace",
                  "SelfTraceGate.lower_dispatch", ("ingest_enabled",),
                  "selftrace_ingest_enabled"),
    GatedFunction("tempo_tpu.observability.selftrace",
                  "SelfTraceGate.annotate_query", ("ingest_enabled",),
                  "selftrace_ingest_enabled"),
    # anomaly flight recorder: a disabled recorder must not snapshot
    # subsystems, read clocks, or take its lock when a trigger fires
    GatedFunction("tempo_tpu.observability.flightrecorder",
                  "FlightRecorder.record", ("enabled",),
                  "selftrace_ingest_enabled"),
)

GUARDED_CALLS = (
    GuardedCall("FAULTS", ("hit",), (), "active", "FAULTS",
                "robustness_faults"),
    GuardedCall("TELEMETRY", ("set_queue_state",), ("record_",),
                "enabled", "TELEMETRY", "ingest_telemetry_enabled"),
    GuardedCall("coalescer", ("submit",), (), "coalescer", "coalescer",
                "search_coalesce_max_queries"),
    # hot-path ownership lookups must be dominated by the one-attribute
    # gate read — the disabled serving path never enters the map (the
    # heat-table feed rides the same gate: record_access additionally
    # self-gates on `replicated`, so rf=1 deployments pay one read)
    GuardedCall("OWNERSHIP", ("owns_group", "record_access"), (),
                "enabled", "OWNERSHIP", "search_hbm_ownership_enabled"),
    # hedge-timer touches (the delay derivation reads a lock +
    # estimator state, observe() reads the clock's output) only behind
    # the armed flag: with search_hbm_ownership_rf <= 1 no call site
    # may reach the timer — no clock read, no lock, no thread spawn
    GuardedCall("HEDGE", ("observe", "delay_s"), (), "armed", "HEDGE",
                "search_hbm_ownership_rf"),
    # staging-site packing calls likewise: the disabled path must not
    # even compute the width-planner inputs (duration rollup maxes)
    GuardedCall("PACKING", ("plan_widths", "pack_hits"), (), "enabled",
                "PACKING", "search_packed_residency"),
    # structural span staging: the disabled path must not even inspect
    # blocks for span segments, let alone stack/pad/upload them
    GuardedCall("STRUCTURAL", ("stack_spans", "stage_single"), (),
                "enabled", "STRUCTURAL", "search_structural_enabled"),
    # plan-shape stacking: group-key computation only behind the
    # stacking gate — a disabled coalescer submit stays on the exact
    # solo-flush path
    GuardedCall("STRUCTURAL", ("stack_group_key",), (), "stack_enabled",
                "STRUCTURAL", "search_structural_stack_enabled"),
    # span-sharding: the reshard (an O(spans) numpy pass) only behind
    # its gate — disabled staging keeps the replicated layout untouched
    GuardedCall("STRUCTURAL", ("shard_span_segment",), (), "shard_spans",
                "STRUCTURAL", "search_structural_shard_spans"),
    # remainder-shard staging: the minimal-multiple pad computation
    # only behind its gate — disabled staging keeps the pow2 layout
    # without even calling the pad helper
    GuardedCall("STRUCTURAL", ("remainder_pad",), (), "remainder_pages",
                "STRUCTURAL", "search_structural_remainder_pages"),
    # hot-tier hooks on the ingest/search hot paths: every call site
    # must be dominated by the one-attribute gate read so the disabled
    # deployment never enters the tier (poll_visible/has_subscribers
    # are consulted inside guard tests themselves and stay covered by
    # their internal gates)
    GuardedCall("LIVE_TIER", ("absorb", "mark_cut", "search",
                              "mark_poll_visible", "subscribe",
                              "unsubscribe", "notify_push"), (),
                "enabled", "LIVE_TIER", "search_live_tier_enabled"),
    # aggregate analytics hooks: the ingest feed and the query-side
    # batch staging both only behind the one-attribute gate read (the
    # batcher folds the gate into `want_agg` = enabled AND the request
    # opted in — mentioning it in a test guards like the gate itself)
    GuardedCall("ANALYTICS", ("consume_blob", "stage_for_batch"), (),
                "enabled", "want_agg", "search_analytics_enabled"),
    # dogfood hooks on hot paths (dispatch finish, query-stat publish):
    # call sites gate on the one-attribute read so the default-off
    # deployment never enters the lowering/annotation protocol
    GuardedCall("SELFTRACE", ("lower_dispatch", "annotate_query"), (),
                "ingest_enabled", "SELFTRACE",
                "selftrace_ingest_enabled"),
    # flight-recorder triggers (breaker trip, watchdog, slow query)
    # live on failure paths of otherwise-hot code: each site reads
    # RECORDER.enabled before snapshotting state into a bundle
    GuardedCall("RECORDER", ("record",), (), "enabled", "RECORDER",
                "selftrace_ingest_enabled"),
)


def _mention_polarities(test: ast.AST, rule: GuardedCall) -> set:
    """Which polarities the gate mention appears in: "positive" means
    the test is truthy when the gate is ON (`if X.active:`,
    `if x is not None:`), "negated" means truthy when it is OFF
    (`if not X.active:`, `if x is None:`). An early-exit `if` guards
    its remaining siblings only in the NEGATED polarity — `if
    FAULTS.active: return` exits on the ARMED path and leaves the
    disabled path running straight into the record call. Likewise the
    `orelse` branch of a gate test is the OPPOSITE polarity of its
    body."""

    def is_mention(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and node.attr in (rule.guard_attr, rule.guard_name)) \
            or (isinstance(node, ast.Name) and node.id == rule.guard_name)

    out: set = set()

    def walk(node: ast.AST, negated: bool) -> None:
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            walk(node.operand, not negated)
            return
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.comparators[0], ast.Constant) \
                and node.comparators[0].value is None:
            # `x is None` flips polarity (truth = gate ABSENT);
            # `x is not None` keeps it
            if isinstance(node.ops[0], ast.Is):
                walk(node.left, not negated)
                return
            if isinstance(node.ops[0], ast.IsNot):
                walk(node.left, negated)
                return
        if is_mention(node):
            out.add("negated" if negated else "positive")
        for c in ast.iter_child_nodes(node):
            walk(c, negated)

    walk(test, False)
    return out


def _test_mentions_negated(test: ast.AST, rule: GuardedCall) -> bool:
    return "negated" in _mention_polarities(test, rule)


def _receiver_name(fn: ast.Attribute) -> str | None:
    """Terminal name of the receiver: FAULTS.hit -> FAULTS,
    self.coalescer.submit -> coalescer."""
    base = fn.value
    if isinstance(base, ast.Name):
        return base.id
    if isinstance(base, ast.Attribute):
        return base.attr
    return None


def _rule_matches(rule: GuardedCall, fn: ast.Attribute) -> bool:
    if _receiver_name(fn) != rule.receiver:
        return False
    if fn.attr in rule.methods:
        return True
    return any(fn.attr.startswith(p) for p in rule.method_prefixes)


class NoopContractChecker(Checker):
    id = "noop-contract"

    def __init__(self, gated=GATED_FUNCTIONS, guarded=GUARDED_CALLS):
        self.gated = tuple(gated)
        self.guarded = tuple(guarded)

    def check(self, pkg: Package) -> list[Finding]:
        findings: list[Finding] = []
        by_key = {}
        for mod, qual, node in pkg.functions():
            by_key[(mod.dotted, qual)] = (mod, node)
        for rule in self.gated:
            hit = by_key.get((rule.module, rule.qualname))
            if hit is None:
                findings.append(Finding(
                    checker=self.id, path=rule.module.replace(".", "/")
                    + ".py", line=1,
                    message=(f"gate registry names {rule.module}."
                             f"{rule.qualname} but no such function "
                             "exists — the registry drifted from the "
                             "code"),
                    hint="update GATED_FUNCTIONS in "
                         "tempo_tpu/analysis/contracts.py",
                    key=f"gate-missing:{rule.module}.{rule.qualname}"))
                continue
            mod, node = hit
            findings.extend(self._check_gated(rule, mod, node))
        # guarded-call domination is checked package-wide (the rules
        # match by receiver shape, not by symbol table)
        for mod, qual, fnode in pkg.functions():
            findings.extend(self._check_guarded(mod, qual, fnode))
        return findings

    # ---- gated functions ----

    def _check_gated(self, rule: GatedFunction, mod, func) -> list:
        findings = []
        gate_line = None
        pre_gate: list = []

        def is_gate_test(test: ast.AST) -> bool:
            for node in ast.walk(test):
                if isinstance(node, ast.Attribute) \
                        and node.attr in rule.gate_attrs:
                    return True
                if isinstance(node, ast.Name) \
                        and node.id in rule.gate_attrs:
                    return True
            return False

        # lexical scan over the TOP-LEVEL body: the gate idiom is an
        # early `if not <gate>: return ...` (or a gated return); every
        # registered function follows it, and anything before that
        # statement runs on the disabled path
        for stmt in func.body:
            if isinstance(stmt, ast.If) and is_gate_test(stmt.test):
                gate_line = stmt.lineno
                break
            if isinstance(stmt, ast.Return) and stmt.value is not None \
                    and is_gate_test(stmt.value):
                # `return X if gated else noop` boolean-gate forms
                gate_line = stmt.lineno
                break
            pre_gate.append(stmt)
        if gate_line is None:
            findings.append(Finding(
                checker=self.id, path=mod.rel, line=func.lineno,
                message=(f"{rule.qualname}() implements the "
                         f"{rule.knob} gate but no test of "
                         f"{'/'.join(rule.gate_attrs)} was found in it"),
                hint="gate first, or update the GATED_FUNCTIONS "
                     "registry if the gate moved",
                key=f"gate-absent:{rule.qualname}"))
            return findings
        for stmt in pre_gate:
            for why, line in _contract_work(stmt):
                findings.append(Finding(
                    checker=self.id, path=mod.rel, line=line,
                    message=(f"{rule.qualname}() does {why} BEFORE its "
                             f"{rule.knob} gate (line {gate_line}) — the "
                             "disabled path pays it on every call"),
                    hint="move it after the gate test, or justify the "
                         "exception in the allowlist",
                    key=f"pre-gate:{rule.qualname}:{why}"))
        return findings

    # ---- guarded calls ----

    def _check_guarded(self, mod, qual, func) -> list:
        findings = []

        def walk(stmts, guards: frozenset) -> None:
            g = guards
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                # early-return gate: `if not <guard>: return/raise/...`
                # guards the remaining siblings. Polarity matters:
                # `if <guard>: return` exits on the ARMED path and the
                # disabled path keeps going — that must NOT count.
                if isinstance(stmt, ast.If) and _exits(stmt.body):
                    for rule in self.guarded:
                        if _test_mentions_negated(stmt.test, rule):
                            g = g | {rule.knob}
                if isinstance(stmt, ast.If):
                    # polarity-aware: the body is guarded when the test
                    # is truthy-with-gate-ON, the else branch when it is
                    # truthy-with-gate-OFF — `if X.active: ... else:
                    # X.hit()` runs the record protocol exactly on the
                    # disabled path and must NOT get guard credit
                    body_g, else_g = g, g
                    for rule in self.guarded:
                        pol = _mention_polarities(stmt.test, rule)
                        if "positive" in pol:
                            body_g = body_g | {rule.knob}
                        if "negated" in pol:
                            else_g = else_g | {rule.knob}
                    walk(stmt.body, body_g)
                    walk(stmt.orelse, else_g)
                elif isinstance(stmt, (ast.For, ast.While, ast.With,
                                       ast.AsyncFor, ast.AsyncWith)):
                    walk(stmt.body, g)
                    walk(getattr(stmt, "orelse", []), g)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body, g)
                    for h in stmt.handlers:
                        walk(h.body, g)
                    walk(stmt.orelse, g)
                    walk(stmt.finalbody, g)
                self._scan_calls(stmt, g, mod, qual, findings)
            return

        walk(func.body, frozenset())
        return findings

    def _scan_calls(self, stmt, guards, mod, qual, findings) -> None:
        if isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                             ast.Try, ast.AsyncFor, ast.AsyncWith)):
            # compound statements: their test/iter/with-item expressions
            # are at this guard level; bodies were walked with inner
            # guards. With-items matter: `with TELEMETRY.record_x():`
            # is a record-protocol call too
            exprs = [getattr(stmt, "test", None),
                     getattr(stmt, "iter", None)]
            exprs += [item.context_expr
                      for item in getattr(stmt, "items", [])]
            nodes = [n for e in exprs if e is not None
                     for n in ast.walk(e)]
        else:
            nodes = list(ast.walk(stmt))
        for node in nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            for rule in self.guarded:
                if not _rule_matches(rule, node.func):
                    continue
                if rule.knob in guards:
                    continue
                # conditional-expression guard: X if <guard> else Y
                findings.append(Finding(
                    checker=self.id, path=mod.rel, line=node.lineno,
                    message=(f"{qual}() calls {rule.receiver}."
                             f"{node.func.attr}() without a dominating "
                             f"{rule.guard_name}.{rule.guard_attr} "
                             f"check — the {rule.knob}=off path enters "
                             "the record protocol"),
                    hint=f"wrap the call in `if {rule.guard_name}."
                         f"{rule.guard_attr}:` (the one-attribute-read "
                         "idiom every other site uses)",
                    key=f"unguarded:{qual}:{rule.receiver}."
                        f"{node.func.attr}"))


def _exits(body: list) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _contract_work(stmt: ast.stmt):
    """(description, line) for clock reads, lock acquires and metric
    writes inside one pre-gate statement."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            fn = node.func
            if fn.attr in _CLOCK_ATTRS and isinstance(fn.value, ast.Name) \
                    and fn.value.id in ("time", "_time"):
                yield f"a clock read (time.{fn.attr}())", node.lineno
            elif fn.attr == "acquire":
                yield "a lock acquire", node.lineno
            elif fn.attr in _METRIC_WRITE_ATTRS \
                    and isinstance(fn.value, ast.Attribute) \
                    and isinstance(fn.value.value, ast.Name) \
                    and fn.value.value.id in ("obs", "metrics"):
                yield (f"a metric write (obs.{fn.value.attr}."
                       f"{fn.attr}())"), node.lineno
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute) \
                        and ctx.attr.endswith("lock"):
                    yield "a lock acquire (with ...lock)", node.lineno
