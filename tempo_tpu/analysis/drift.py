"""Drift engine: code-vs-docs catalogs as declarations.

PRs 1-9 accumulated three hand-rolled drift tests (config knobs vs
docs/configuration.md, metric names vs docs/observability.md,
faultpoints vs docs/robustness.md), each with its own regex walk over
the source tree. This module re-bases them on the shared parse: a
catalog is ONE :class:`Catalog` declaration — an extractor over the
parsed package, the doc file(s) every extracted name must appear in,
and a sanity floor that catches a broken extractor before it silently
passes an empty set. The legacy tests are thin wrappers now
(tests/test_config_docs.py, test_observability.py, test_faults.py
assert the corresponding catalogs are clean), and a NEW catalog —
knobs, debug routes, faultpoints, metrics — is one entry in
:data:`CATALOGS`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from .core import Checker, Finding, Package

# metric-name prefixes the observability catalog covers (matches the
# legacy grep in tests/test_observability.py)
_METRIC_PREFIXES = ("tempo", "tempodb", "traces")


@dataclass(frozen=True)
class Catalog:
    """One code-vs-docs invariant. ``extract(pkg) -> dict[name, (rel,
    line)]`` walks the shared parse; every extracted name must appear in
    every file of ``docs`` (``backtick=True`` requires `name` form, the
    metric-catalog convention); fewer than ``min_names`` extracted names
    fails the catalog itself — a broken extractor must not pass
    vacuously."""

    name: str
    docs: tuple
    extract: object
    min_names: int = 1
    backtick: bool = False
    hint: str = ""


# ---- extractors (each returns {name: (rel_path, line)}) ----

def _dataclass_fields(pkg: Package, dotted: str, cls: str,
                      prefix_filter: tuple | None = None) -> dict:
    mod = pkg.by_dotted.get(dotted)
    out: dict = {}
    if mod is None:
        return out
    for node in mod.tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name == cls):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                name = stmt.target.id
                if prefix_filter is None or name.startswith(prefix_filter):
                    out[name] = (mod.rel, stmt.lineno)
    return out


def tempodb_config_fields(pkg: Package) -> dict:
    return _dataclass_fields(pkg, "tempo_tpu.db.tempodb", "TempoDBConfig")


def robustness_knob_fields(pkg: Package) -> dict:
    """The robustness TempoDBConfig knobs (search_breaker_*,
    robustness_*, the three timeout knobs) — documented in BOTH
    docs/robustness.md and docs/configuration.md."""
    fields = _dataclass_fields(pkg, "tempo_tpu.db.tempodb",
                               "TempoDBConfig")
    keep = {
        n: loc for n, loc in fields.items()
        if n.startswith(("search_breaker_", "robustness_"))
        or n in ("search_device_dispatch_timeout_s",
                 "search_dispatch_lock_timeout_s",
                 "search_request_timeout_s")
    }
    return keep


def yaml_knobs(pkg: Package) -> dict:
    """Every YAML key the config loader reads: ``*.get("<key>")`` in
    cli/config.py (the AST form of the legacy regex)."""
    mod = pkg.by_dotted.get("tempo_tpu.cli.config")
    out: dict = {}
    if mod is None:
        return out
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            key = node.args[0].value
            if key and all(c.islower() or c.isdigit() or c == "_"
                           for c in key):
                out.setdefault(key, (mod.rel, node.lineno))
    return out


def metric_names(pkg: Package) -> dict:
    """Every Counter/Gauge/Histogram registered anywhere in the
    package (first-arg string literal with a tempo/tempodb/traces
    prefix)."""
    out: dict = {}
    for mod in pkg.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                fn = node.func
                ctor = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if ctor in ("Counter", "Gauge", "Histogram") \
                        and node.args[0].value.startswith(
                            _METRIC_PREFIXES):
                    out.setdefault(node.args[0].value,
                                   (mod.rel, node.lineno))
    return out


def debug_routes(pkg: Package) -> dict:
    """Keys of the DEBUG_ROUTES dict in api/http.py — every registered
    /debug route must be documented in the observability doc's route
    index."""
    mod = pkg.by_dotted.get("tempo_tpu.api.http")
    out: dict = {}
    if mod is None:
        return out
    for node in mod.tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == "DEBUG_ROUTES" \
                    and isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        out[k.value] = (mod.rel, k.lineno)
    return out


def faultpoints(pkg: Package) -> dict:
    """Keys of the CATALOG dict in robustness/faults.py."""
    mod = pkg.by_dotted.get("tempo_tpu.robustness.faults")
    out: dict = {}
    if mod is None:
        return out
    for node in mod.tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == "CATALOG" \
                    and isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        out[k.value] = (mod.rel, k.lineno)
    return out


CATALOGS = (
    Catalog(
        name="config-fields",
        docs=("docs/configuration.md",),
        extract=tempodb_config_fields,
        min_names=30,
        hint="document the knob in docs/configuration.md, or list it "
             "under the constructor-only / renamed-knob sections",
    ),
    Catalog(
        name="yaml-knobs",
        docs=("docs/configuration.md",),
        extract=yaml_knobs,
        min_names=30,
        hint="document the YAML key in docs/configuration.md",
    ),
    Catalog(
        name="metric-names",
        docs=("docs/observability.md",),
        extract=metric_names,
        min_names=30,
        backtick=True,
        hint="add the metric to the docs/observability.md catalog table",
    ),
    Catalog(
        name="faultpoints",
        docs=("docs/robustness.md",),
        extract=faultpoints,
        min_names=8,
        backtick=True,
        hint="add the faultpoint to the docs/robustness.md catalog",
    ),
    Catalog(
        name="debug-routes",
        docs=("docs/observability.md",),
        extract=debug_routes,
        min_names=8,
        backtick=True,
        hint="document the route in docs/observability.md's /debug "
             "route index",
    ),
    Catalog(
        name="robustness-knobs",
        docs=("docs/robustness.md", "docs/configuration.md"),
        extract=robustness_knob_fields,
        min_names=8,
        hint="robustness knobs are documented in BOTH docs/robustness.md"
             " and docs/configuration.md",
    ),
)


# one parsed package per process: the legacy drift tests each wrap one
# catalog, and re-parsing 115 modules per test would waste tier-1 time
_PKG_CACHE: dict = {}


def catalog_findings(name: str, pkg_dir: str | None = None) -> list:
    """Run ONE catalog over the package — the entry the legacy drift
    tests (test_config_docs, test_observability, test_faults) wrap.
    Returns the findings; empty means the catalog is clean."""
    if pkg_dir is None:
        pkg_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    pkg_dir = os.path.abspath(pkg_dir)
    pkg = _PKG_CACHE.get(pkg_dir)
    if pkg is None:
        pkg = _PKG_CACHE[pkg_dir] = Package.load(pkg_dir)
    cats = [c for c in CATALOGS if c.name == name]
    if not cats:
        raise KeyError(f"no catalog named {name!r}; have "
                       f"{[c.name for c in CATALOGS]}")
    return DriftChecker(catalogs=cats).check(pkg)


class DriftChecker(Checker):
    id = "drift"

    def __init__(self, catalogs=CATALOGS):
        self.catalogs = tuple(catalogs)

    def check(self, pkg: Package) -> list[Finding]:
        findings: list[Finding] = []
        doc_cache: dict[str, str | None] = {}

        def doc_text(rel: str) -> str | None:
            if rel not in doc_cache:
                path = os.path.join(pkg.root, rel)
                if os.path.exists(path):
                    with open(path, encoding="utf-8") as f:
                        doc_cache[rel] = f.read()
                else:
                    doc_cache[rel] = None
            return doc_cache[rel]

        for cat in self.catalogs:
            names = cat.extract(pkg)
            if len(names) < cat.min_names:
                findings.append(Finding(
                    checker=self.id, path="tempo_tpu/analysis/drift.py",
                    line=1,
                    message=(f"catalog {cat.name!r} extracted only "
                             f"{len(names)} name(s) (floor "
                             f"{cat.min_names}) — the extractor looks "
                             "broken"),
                    hint="fix the extractor (or the floor) in "
                         "tempo_tpu/analysis/drift.py",
                    key=f"floor:{cat.name}"))
                continue
            for doc_rel in cat.docs:
                doc = doc_text(doc_rel)
                if doc is None:
                    findings.append(Finding(
                        checker=self.id, path=doc_rel, line=1,
                        message=f"catalog {cat.name!r}: doc file "
                                f"{doc_rel} is missing",
                        hint=cat.hint, key=f"missing-doc:{cat.name}:"
                                           f"{doc_rel}"))
                    continue
                for name in sorted(names):
                    needle = f"`{name}`" if cat.backtick else name
                    if needle not in doc:
                        rel, line = names[name]
                        findings.append(Finding(
                            checker=self.id, path=rel, line=line,
                            message=(f"{cat.name}: {name!r} is in the "
                                     f"code but not in {doc_rel}"),
                            hint=cat.hint,
                            key=f"{cat.name}:{name}:{doc_rel}"))
        return findings
