"""Shared walk: parse every module in the package ONCE, hand the parsed
package to each checker, collect findings, apply the allowlist.

The suite's runtime contract is tier-1 shaped: a single in-process pass
(no subprocess per file), a few hundred milliseconds for the whole
package. Checkers therefore never re-read or re-parse source — they walk
the :class:`Package`'s ASTs and use the symbol tables the shared pass
already built.
"""

from __future__ import annotations

import ast
import hashlib
import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Finding:
    """One defect: ``checker`` id, ``path:line`` location, a one-line
    ``message`` and a one-line fix ``hint``.

    The ``fingerprint`` deliberately excludes the line number: an
    allowlist entry must survive unrelated edits above the finding, and
    go STALE the moment the flagged construct itself disappears. It
    hashes (checker, path, key) where ``key`` is the checker-chosen
    stable identity — usually the enclosing symbol plus the defect kind.
    """

    checker: str            # checker id, e.g. "lock-order"
    path: str               # repo-relative, e.g. "tempo_tpu/search/batcher.py"
    line: int
    message: str
    hint: str = ""
    key: str = ""           # stable identity within (checker, path)

    @property
    def fingerprint(self) -> str:
        ident = self.key or self.message
        digest = hashlib.sha256(
            f"{self.checker}|{self.path}|{ident}".encode()).hexdigest()[:12]
        return f"{self.checker}:{self.path}:{digest}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"[{self.checker}] {loc}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        out += f"\n    fingerprint: {self.fingerprint}"
        return out

    def as_dict(self) -> dict:
        return {
            "checker": self.checker,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "fingerprint": self.fingerprint,
        }


@dataclass
class Module:
    """One parsed source file."""

    path: str               # absolute
    rel: str                # repo-relative with forward slashes
    source: str
    tree: ast.Module

    @property
    def dotted(self) -> str:
        """Module path as a dotted name (tempo_tpu.search.batcher)."""
        out = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        out = out.replace("/", ".")
        if out.endswith(".__init__"):
            out = out[: -len(".__init__")]
        return out


class Package:
    """Every module of a package parsed once — the shared pass checkers
    walk. ``root`` is the directory that CONTAINS the package dir (so
    rel paths read ``tempo_tpu/...``), or the package dir itself for
    fixture packages."""

    def __init__(self, modules: list[Module]):
        self.modules = modules
        self.by_rel = {m.rel: m for m in modules}
        self.by_dotted = {m.dotted: m for m in modules}
        self._functions: list | None = None
        self.root = ""          # rel_base dir (repo root), set by load()

    @classmethod
    def load(cls, pkg_dir: str, rel_base: str | None = None) -> "Package":
        """Parse every ``.py`` under ``pkg_dir``. ``rel_base`` is the
        directory rel paths are computed against (defaults to the parent
        of ``pkg_dir``)."""
        pkg_dir = os.path.abspath(pkg_dir)
        base = os.path.abspath(rel_base) if rel_base else \
            os.path.dirname(pkg_dir)
        modules: list[Module] = []
        for dirpath, dirnames, files in os.walk(pkg_dir):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__")
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                rel = os.path.relpath(path, base).replace(os.sep, "/")
                modules.append(Module(path=path, rel=rel, source=source,
                                      tree=ast.parse(source, filename=rel)))
        pkg = cls(modules)
        pkg.root = base
        return pkg

    # ---- shared symbol helpers ----

    def functions(self) -> list:
        """(module, qualname, node) for every function/method, with
        qualname like ``ClassName.method`` or ``func`` (nested defs get
        dotted parents). Computed once — every checker iterates this."""
        if self._functions is None:
            self._functions = [
                t for mod in self.modules
                for t in _walk_functions(mod, mod.tree, ())
            ]
        return self._functions


def _walk_functions(mod: Module, node: ast.AST, parents: tuple):
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = ".".join(parents + (child.name,))
            yield mod, qual, child
            yield from _walk_functions(mod, child, parents + (child.name,))
        elif isinstance(child, ast.ClassDef):
            yield from _walk_functions(mod, child, parents + (child.name,))


class Checker:
    """One pluggable analysis. ``id`` tags findings; ``check`` walks the
    shared parse and returns them."""

    id = "checker"

    def check(self, pkg: Package) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class Report:
    """One suite run: raw findings split by the allowlist, plus the
    stale allowlist entries (fingerprints matching nothing — themselves
    findings, so a fixed defect can't leave a dead justification
    behind)."""

    findings: list              # un-allowlisted Finding, the failures
    allowlisted: list = field(default_factory=list)   # (Finding, entry)
    stale: list = field(default_factory=list)         # stale Finding

    @property
    def failures(self) -> list:
        return self.findings + self.stale

    @property
    def exit_code(self) -> int:
        return 1 if self.failures else 0

    def render(self) -> str:
        lines = []
        for f in self.findings:
            lines.append(f.render())
        for f in self.stale:
            lines.append(f.render())
        lines.append(
            f"{len(self.findings)} finding(s), {len(self.stale)} stale "
            f"allowlist entrie(s), {len(self.allowlisted)} allowlisted")
        return "\n".join(lines)

    def as_dict(self) -> dict:
        return {
            "findings": [f.as_dict() for f in self.findings],
            "stale_allowlist": [f.as_dict() for f in self.stale],
            "allowlisted": [
                {**f.as_dict(), "justification": e.justification}
                for f, e in self.allowlisted
            ],
            "ok": not self.failures,
        }


def run_suite(pkg: Package, checkers: list, allowlist=None) -> Report:
    """Run every checker over the shared parse and split findings by the
    allowlist. An allowlist entry matches by exact fingerprint; entries
    matching no raw finding come back as ``allowlist-stale`` findings."""
    raw: list[Finding] = []
    for checker in checkers:
        raw.extend(checker.check(pkg))
    raw.sort(key=lambda f: (f.path, f.line, f.checker, f.message))
    if allowlist is None:
        return Report(findings=raw)
    open_findings: list[Finding] = []
    allowlisted: list = []
    matched: set[str] = set()
    for f in raw:
        entry = allowlist.get(f.fingerprint)
        if entry is not None:
            matched.add(f.fingerprint)
            allowlisted.append((f, entry))
        else:
            open_findings.append(f)
    stale = [
        Finding(
            checker="allowlist-stale",
            path=allowlist.rel_path,
            line=e.line,
            message=(f"allowlist entry {e.fingerprint!r} matches no "
                     "current finding — the defect it justified is gone"),
            hint="delete the [[allow]] entry (justification: "
                 f"{e.justification!r})",
            key=e.fingerprint,
        )
        for e in allowlist.entries
        if e.fingerprint not in matched
    ]
    return Report(findings=open_findings, allowlisted=allowlisted,
                  stale=stale)
