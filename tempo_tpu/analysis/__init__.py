"""Tier-1 static-analysis suite: the bug classes this codebase has hit
at RUNTIME, caught at test time instead.

PR 1 found a real mesh rendezvous deadlock (concurrent shard_map
dispatch from two threads); PR 9 could only make the lock-cycle class
detectable AFTER the fact with a bounded dispatch-lock wait; the noop
contracts ("knob off = one attribute read, byte-identical output") were
asserted only dynamically in bench. With 70+ lock uses across the
package and every roadmap item adding more threads, locks, and jit'd
kernels, these properties are enforced here as ANALYSIS over the code:

  - one shared module-parse/symbol-resolution pass over the whole
    package (:mod:`core`), pluggable :class:`core.Checker` classes;
  - ``lock-order`` — lock-acquisition graph, inter-lock cycles, and
    blocking calls while holding a lock (:mod:`locks`);
  - ``noop-contract`` — gate knobs (profiling, query stats, telemetry,
    breaker, faults, coalescer) mapped to their gate expressions; no
    clock read, lock acquire, or metric write reachable before the
    gate (:mod:`contracts`);
  - ``jit-purity`` — no host round-trips, clock reads, or tracer
    branching inside kernel functions reaching ``jax.jit`` /
    ``shard_map_compat``; jit-cache-key hygiene (:mod:`jit_purity`);
  - ``drift`` — declarative code-vs-docs catalogs (config knobs,
    metrics, faultpoints, /debug routes); the three hand-rolled drift
    tests are thin wrappers over these declarations now (:mod:`drift`);
  - ``metrics-catalog`` — every registered Counter/Gauge/Histogram has
    an observability.md catalog row AND write sites pass only the
    labels that row declares — an undocumented label mints surprise
    series cardinality (:mod:`metrics_catalog`).

``scripts/check.py`` is the CLI; ``tests/test_static_analysis.py`` runs
the suite in tier-1 and fails on any finding not justified in
``analysis/allowlist.toml`` (stale entries are themselves findings).
"""

from __future__ import annotations

from .allowlist import Allowlist, load_allowlist
from .core import Checker, Finding, Package, Report, run_suite

__all__ = [
    "Allowlist",
    "Checker",
    "Finding",
    "Package",
    "Report",
    "default_checkers",
    "load_allowlist",
    "run_suite",
]


def default_checkers() -> list:
    """The tier-1 checker set, in priority order."""
    from .contracts import NoopContractChecker
    from .drift import DriftChecker
    from .jit_purity import JitPurityChecker
    from .locks import LockOrderChecker
    from .metrics_catalog import MetricsCatalogChecker

    return [
        LockOrderChecker(),
        NoopContractChecker(),
        JitPurityChecker(),
        DriftChecker(),
        MetricsCatalogChecker(),
    ]
