"""Reviewed-findings allowlist: ``analysis/allowlist.toml``.

Each entry pairs a finding FINGERPRINT (stable under line drift — see
:class:`core.Finding`) with a human-readable justification. The suite
stays at zero by construction: an un-allowlisted finding fails, and an
entry whose fingerprint no longer matches any finding fails too (stale
— the defect it justified was fixed, so the entry must go).

Format — the array-of-tables TOML subset below, parsed by a ~40-line
reader because this container's Python (3.10) predates stdlib
``tomllib`` and the repo installs nothing::

    [[allow]]
    fingerprint = "lock-order:tempo_tpu/foo.py:ab12cd34ef56"
    justification = "why this construct is deliberate"

Only ``[[allow]]`` tables with double-quoted single-line string values
are supported; that is the whole grammar the file needs. When a real
``tomllib`` is present it is used instead, so the file stays valid TOML.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class AllowEntry:
    fingerprint: str
    justification: str
    line: int = 0


class Allowlist:
    def __init__(self, entries: list[AllowEntry], path: str = ""):
        self.entries = entries
        self.path = path
        self._by_fp = {e.fingerprint: e for e in entries}

    @property
    def rel_path(self) -> str:
        parts = self.path.replace(os.sep, "/").rsplit("tempo_tpu/", 1)
        return "tempo_tpu/" + parts[1] if len(parts) == 2 else self.path

    def get(self, fingerprint: str) -> AllowEntry | None:
        return self._by_fp.get(fingerprint)

    def __len__(self) -> int:
        return len(self.entries)


class AllowlistError(ValueError):
    """Malformed allowlist — fails the suite loudly, never silently."""


def _parse_subset(text: str, path: str) -> list[AllowEntry]:
    """The [[allow]] / key = "value" subset (module docstring)."""
    entries: list[AllowEntry] = []
    current: dict | None = None
    current_line = 0

    def close() -> None:
        nonlocal current
        if current is None:
            return
        if "fingerprint" not in current or "justification" not in current:
            raise AllowlistError(
                f"{path}:{current_line}: [[allow]] entry needs both "
                "'fingerprint' and 'justification'")
        if not current["justification"].strip():
            raise AllowlistError(
                f"{path}:{current_line}: empty justification — every "
                "allowlisted finding carries a human-readable reason")
        entries.append(AllowEntry(fingerprint=current["fingerprint"],
                                  justification=current["justification"],
                                  line=current_line))
        current = None

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[allow]]":
            close()
            current = {}
            current_line = lineno
            continue
        key, sep, value = line.partition("=")
        key = key.strip()
        value = value.strip()
        if (current is None or not sep or not value.startswith('"')
                or not value.endswith('"') or len(value) < 2):
            raise AllowlistError(
                f"{path}:{lineno}: unsupported syntax {line!r} — only "
                '[[allow]] tables with key = "value" lines are allowed')
        current[key] = value[1:-1].replace('\\"', '"')
    close()
    return entries


def load_allowlist(path: str) -> Allowlist:
    """Read an allowlist file; a missing file is an empty allowlist (a
    new checkout starts at zero entries, not at an error)."""
    if not os.path.exists(path):
        return Allowlist([], path=path)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        import tomllib  # py>=3.11: honor full TOML
    except ModuleNotFoundError:
        return Allowlist(_parse_subset(text, path), path=path)
    doc = tomllib.loads(text)
    entries = []
    for tbl in doc.get("allow", []):
        if "fingerprint" not in tbl or not str(
                tbl.get("justification", "")).strip():
            raise AllowlistError(
                f"{path}: every [[allow]] entry needs a fingerprint and "
                "a non-empty justification")
        entries.append(AllowEntry(fingerprint=str(tbl["fingerprint"]),
                                  justification=str(tbl["justification"])))
    return Allowlist(entries, path=path)


def default_path() -> str:
    return os.path.join(os.path.dirname(__file__), "allowlist.toml")
