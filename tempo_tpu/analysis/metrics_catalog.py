"""Metric-catalog checker: every metric the package registers has a
docs/observability.md catalog row, and every label a write site uses is
one that row declares.

The drift engine already pins metric NAMES into the docs (the
``metric-names`` catalog); what it cannot see is the label schema — a
call site adding an undocumented label mints a new series per value and
the catalog table silently lies about the metric's cardinality. This
checker closes that gap with two passes over the shared parse:

``uncatalogued-metric``
    a ``Counter``/``Gauge``/``Histogram`` constructed with a
    ``tempo*``-prefixed name that has no row in the observability
    catalog tables;

``unknown-label``
    a write/read call on a registered metric (``inc``, ``observe``,
    ``observe_bulk``, ``set``, ``add``, ``remove``, ``value``,
    ``labels``, ``time``) passing a literal keyword label the metric's
    catalog row does not declare. Dynamic ``**labels`` expansions are
    skipped — only literal keywords are checkable statically.

The docs side is the existing catalog-table convention — rows of
``| `name` | type | labels | meaning |`` where the labels cell holds
backticked label names (``—`` for none). The checker parses those rows
straight out of the markdown; the fixture self-tests inject a catalog
dict instead so they need no doc file.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Checker, Finding, Package

# prefixes the observability catalog covers (mirrors drift.metric_names)
_METRIC_PREFIXES = ("tempo", "tempodb", "traces")
_CTORS = ("Counter", "Gauge", "Histogram")

# every metric method whose **kwargs are label names
_LABELED_METHODS = ("inc", "observe", "observe_bulk", "set", "add",
                    "remove", "value", "labels", "time")

# receivers metric vars are reached through at call sites: the
# package-wide idiom is `obs.<metric>.<method>` (metrics module imported
# as obs/metrics), plus bare names inside the defining module
_RECEIVER_BASES = ("obs", "metrics")

# one catalog row: | `tempo_x_total` | counter | `a`, `b` | meaning |
_ROW_RE = re.compile(
    r"^\|\s*`(?P<name>[A-Za-z_][A-Za-z0-9_:]*)`\s*"
    r"\|\s*(?P<type>counter|gauge|histogram)\s*"
    r"\|(?P<labels>[^|]*)\|")
_LABEL_RE = re.compile(r"`([A-Za-z_][A-Za-z0-9_]*)`")


def parse_doc_catalog(text: str) -> dict:
    """``{metric_name: frozenset(label_names)}`` from every catalog
    table row in the doc. Rows outside the name/type/labels shape
    (e.g. the per-stage meaning tables) simply don't match."""
    out: dict = {}
    for line in text.splitlines():
        m = _ROW_RE.match(line.strip())
        if m is None:
            continue
        labels = frozenset(_LABEL_RE.findall(m.group("labels")))
        out.setdefault(m.group("name"), labels)
    return out


def _ctor_name(fn: ast.AST) -> str:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


class MetricsCatalogChecker(Checker):
    id = "metrics-catalog"

    def __init__(self, catalog: dict | None = None,
                 doc_rel: str = "docs/observability.md"):
        self._catalog = catalog
        self.doc_rel = doc_rel

    def check(self, pkg: Package) -> list[Finding]:
        catalog = self._catalog
        if catalog is None:
            path = os.path.join(pkg.root, self.doc_rel)
            if not os.path.exists(path):
                return [Finding(
                    checker=self.id, path=self.doc_rel, line=1,
                    message=f"metric catalog doc {self.doc_rel} is "
                            "missing — every registered metric needs a "
                            "catalog row",
                    hint="restore the doc (or construct the checker "
                         "with an explicit catalog)",
                    key=f"missing-doc:{self.doc_rel}")]
            with open(path, encoding="utf-8") as f:
                catalog = parse_doc_catalog(f.read())
        findings: list[Finding] = []

        # pass 1: constructors — var name -> metric name(s), and every
        # registered metric must have a catalog row
        var_to_metrics: dict = {}
        defined_in: dict = {}
        for mod in pkg.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                call = node.value
                if not (isinstance(call, ast.Call)
                        and _ctor_name(call.func) in _CTORS
                        and call.args
                        and isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, str)):
                    continue
                mname = call.args[0].value
                if not mname.startswith(_METRIC_PREFIXES):
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        var_to_metrics.setdefault(
                            tgt.id, set()).add(mname)
                        defined_in.setdefault(tgt.id, set()).add(
                            mod.dotted)
                if mname not in catalog:
                    findings.append(Finding(
                        checker=self.id, path=mod.rel, line=node.lineno,
                        message=(f"metric {mname!r} is registered but "
                                 f"has no catalog row in "
                                 f"{self.doc_rel}"),
                        hint="add a `| `name` | type | labels | "
                             "meaning |` row to the catalog table",
                        key=f"uncatalogued:{mname}"))

        # pass 2: write/read sites — literal keyword labels must be
        # catalogued for the metric behind the receiver
        for mod in pkg.modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _LABELED_METHODS):
                    continue
                recv = node.func.value
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id in _RECEIVER_BASES:
                    var = recv.attr
                elif isinstance(recv, ast.Name) \
                        and mod.dotted in defined_in.get(recv.id, ()):
                    var = recv.id
                else:
                    continue
                metrics = var_to_metrics.get(var)
                if not metrics:
                    continue
                # a var bound to several metric names (none today)
                # accepts the union — ambiguity must not manufacture
                # false positives
                allowed: set = set()
                catalogued = [m for m in metrics if m in catalog]
                if not catalogued:
                    continue        # already flagged as uncatalogued
                for m in catalogued:
                    allowed |= catalog[m]
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in allowed:
                        continue
                    mname = sorted(catalogued)[0]
                    findings.append(Finding(
                        checker=self.id, path=mod.rel, line=node.lineno,
                        message=(f"label {kw.arg!r} passed to "
                                 f"{var}.{node.func.attr}() is not in "
                                 f"{mname!r}'s catalog row "
                                 f"(catalogued: "
                                 f"{sorted(allowed) or '—'})"),
                        hint=f"add `{kw.arg}` to the metric's labels "
                             f"cell in {self.doc_rel}, or drop the "
                             "label",
                        key=f"unknown-label:{mname}:{kw.arg}"))
        return findings
