"""Lock-order analyzer: the PR 1 deadlock class, caught before it ships.

Three findings, in escalating severity:

``blocking-under-lock``
    a call that can block indefinitely (``Future.result``, ``.join``,
    ``block_until_ready`` / the profiler fence, an unbounded
    ``.acquire()`` or ``.wait()``, ``time.sleep``, a device d2h sync
    helper) made while lexically holding a known lock. This is the
    shape that turned PR 1's interleaved shard_map dispatch into a
    multi-minute zero-CPU hang, and the class PR 9's bounded
    ``dispatch_lock`` wait can only detect AFTER the stall started.

``lock-reacquire``
    a non-reentrant lock acquired while already held (directly or
    through a call chain) — self-deadlock.

``lock-cycle``
    the acquisition graph (edge A→B = B taken while A held, lexically
    or through resolved same-class/same-module calls) contains an
    inter-lock cycle — two threads walking the cycle from different
    ends deadlock.

Lock identity is CLASS-scoped (``module:Class.attr``) or module-scoped
(``module:name``) — every instance of a class shares one node, which is
exactly the granularity a lock-ORDER discipline is defined at. Aliases
resolve through assignment (``self._dispatch_lock = mesh.dispatch_lock``)
and ``threading.Condition(self._lock)`` (the condition IS that lock).
Calls resolve within the package (same scope, same class, same module,
or an imported module/symbol); unresolvable receivers contribute
nothing — the analyzer under-approximates rather than guessing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Checker, Finding, Module, Package

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# attribute-call names that can block indefinitely (receiver-typed
# refinements below: set_result is not result; cv.wait on the HELD
# condition releases it; a timeout argument bounds the wait)
_BLOCKING_ATTRS = {
    "result": "Future.result() parks this thread until another delivers",
    "join": "join() waits for another thread to finish",
    "block_until_ready": "device sync: waits for the kernel/transfer",
    "fence": "profiler fence = block_until_ready on the kernel outputs",
    "item": "device scalar sync: .item() waits for the device value",
    "wait": "unbounded wait() parks this thread",
    "acquire": "unbounded acquire() can park this thread forever",
    "sleep": "sleeping while holding a lock stalls every waiter",
}
# module-level helper functions that synchronize with the device (d2h)
_BLOCKING_NAMES = {
    "host_scan": "runs the full host-path kernel + d2h sync",
    "fetch_scan_out": "d2h sync of a dispatch's outputs",
    "fetch_coalesced_out": "d2h sync of a fused dispatch's outputs",
    "fence_arrays": "block_until_ready over kernel outputs",
}


@dataclass
class _LockDef:
    lock_id: str
    kind: str               # Lock | RLock | Condition
    mod: str                # dotted module
    line: int


@dataclass
class _FuncInfo:
    key: tuple              # (dotted_module, qualname)
    node: ast.AST
    mod: Module
    cls: str | None         # enclosing class name, if a method
    acquires: set = field(default_factory=set)      # direct lock ids
    blocks: list = field(default_factory=list)      # direct block reasons
    calls: set = field(default_factory=set)         # resolved callee keys
    # transitive closures (fixpoint-filled)
    all_acquires: set = field(default_factory=set)
    may_block: str | None = None    # reason string, if any
    # False ⇒ no with/acquire anywhere: the interprocedural re-scan can
    # skip it (no held region is possible, so no findings or edges)
    hold_potential: bool = False


class _Symbols:
    """The package's lock + import + function tables (one build)."""

    def __init__(self, pkg: Package):
        self.pkg = pkg
        self.locks: dict[str, _LockDef] = {}
        self.global_locks: dict[tuple, str] = {}   # (dotted, name) -> id
        self.class_locks: dict[tuple, str] = {}    # (dotted, cls, attr) -> id
        self.attr_index: dict[str, list] = {}      # attr -> [lock ids]
        self.imports: dict[tuple, object] = {}     # (dotted, alias) -> target
        self.funcs: dict[tuple, _FuncInfo] = {}
        self._build()

    # ---- construction ----

    def _build(self) -> None:
        for mod in self.pkg.modules:
            self._collect_imports(mod)
        for mod in self.pkg.modules:
            self._collect_lock_defs(mod)
        for mod in self.pkg.modules:
            self._collect_lock_aliases(mod)
        for mod, qual, node in self.pkg.functions():
            cls = None
            if "." in qual:
                # the nearest enclosing CLASS, if any, is the part
                # before the final def for methods; nested functions
                # inherit the method's class for self-resolution
                parts = qual.split(".")
                head = parts[0]
                if (self.class_attr_names(mod.dotted, head)
                        or self._is_class(mod, head)):
                    cls = head
            info = _FuncInfo(key=(mod.dotted, qual), node=node, mod=mod,
                             cls=cls)
            self.funcs[info.key] = info

    def _is_class(self, mod: Module, name: str) -> bool:
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == name:
                return True
        return False

    def class_attr_names(self, dotted: str, cls: str) -> list:
        return [a for (d, c, a) in self.class_locks if d == dotted
                and c == cls]

    def _collect_imports(self, mod: Module) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    self.imports[(mod.dotted, name)] = \
                        alias.name if alias.asname else name
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if node.level:
                    parts = mod.dotted.split(".")
                    # level 1 = the containing package of this module
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + [node.module])
                for alias in node.names:
                    name = alias.asname or alias.name
                    target = f"{base}.{alias.name}"
                    # module import vs symbol import: if target names a
                    # package module, the alias IS that module
                    if target in self.pkg.by_dotted:
                        self.imports[(mod.dotted, name)] = target
                    else:
                        self.imports[(mod.dotted, name)] = (base, alias.name)

    def _lock_factory(self, call: ast.AST) -> str | None:
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "threading":
            return fn.attr
        if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
            return fn.id
        return None

    def _add_lock(self, lock_id: str, kind: str, mod: Module,
                  line: int, attr: str | None = None) -> None:
        if lock_id not in self.locks:
            self.locks[lock_id] = _LockDef(lock_id, kind, mod.dotted, line)
        if attr is not None:
            self.attr_index.setdefault(attr, [])
            if lock_id not in self.attr_index[attr]:
                self.attr_index[attr].append(lock_id)

    def _collect_lock_defs(self, mod: Module) -> None:
        # module-level: name = threading.Lock()
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = self._lock_factory(node.value)
                if kind:
                    name = node.targets[0].id
                    lock_id = f"{mod.dotted}:{name}"
                    self.global_locks[(mod.dotted, name)] = lock_id
                    self._add_lock(lock_id, kind, mod, node.lineno)
        # class-scoped: self.attr = threading.Lock() anywhere in a method
        for cls_node in mod.tree.body:
            if not isinstance(cls_node, ast.ClassDef):
                continue
            for node in ast.walk(cls_node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                kind = self._lock_factory(node.value)
                if kind:
                    lock_id = f"{mod.dotted}:{cls_node.name}.{tgt.attr}"
                    key = (mod.dotted, cls_node.name, tgt.attr)
                    if key not in self.class_locks:
                        self.class_locks[key] = lock_id
                        self._add_lock(lock_id, kind, mod, node.lineno,
                                       attr=tgt.attr)

    def _collect_lock_aliases(self, mod: Module) -> None:
        """Second pass: self.attr = <known lock> and Condition(<lock>)."""
        for cls_node in mod.tree.body:
            if not isinstance(cls_node, ast.ClassDef):
                continue
            for node in ast.walk(cls_node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                key = (mod.dotted, cls_node.name, tgt.attr)
                if key in self.class_locks:
                    # Condition wrapping the class's own lock: the cv IS
                    # that lock for ordering purposes
                    if (self._lock_factory(node.value) == "Condition"
                            and isinstance(node.value, ast.Call)
                            and node.value.args):
                        inner = self.resolve_lock(
                            mod, cls_node.name, node.value.args[0], {})
                        if inner:
                            old = self.class_locks[key]
                            self.class_locks[key] = inner
                            self.locks.pop(old, None)
                            if tgt.attr in self.attr_index:
                                self.attr_index[tgt.attr] = [
                                    inner if x == old else x
                                    for x in self.attr_index[tgt.attr]]
                    continue
                lock_id = self.resolve_lock(mod, cls_node.name,
                                            node.value, {})
                if lock_id:
                    self.class_locks[key] = lock_id
                    self.attr_index.setdefault(tgt.attr, [])
                    if lock_id not in self.attr_index[tgt.attr]:
                        self.attr_index[tgt.attr].append(lock_id)

    # ---- resolution ----

    def resolve_lock(self, mod: Module, cls: str | None, expr: ast.AST,
                     local_aliases: dict) -> str | None:
        """expr -> lock id, or None when it isn't (provably) a lock."""
        if isinstance(expr, ast.Name):
            if expr.id in local_aliases:
                return local_aliases[expr.id]
            hit = self.global_locks.get((mod.dotted, expr.id))
            if hit:
                return hit
            target = self.imports.get((mod.dotted, expr.id))
            if isinstance(target, tuple):
                return self.global_locks.get(target)
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                hit = self.class_locks.get((mod.dotted, cls, expr.attr))
                if hit:
                    return hit
            if isinstance(base, ast.Name):
                target = self.imports.get((mod.dotted, base.id))
                if isinstance(target, str):
                    hit = self.global_locks.get((target, expr.attr))
                    if hit:
                        return hit
            # attr-unique fallback: exactly one class in the package
            # defines a lock under this attribute name
            cands = self.attr_index.get(expr.attr, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def resolve_call(self, mod: Module, qual: str, cls: str | None,
                     fn: ast.AST) -> tuple | None:
        """callee expr -> function key within the package, or None."""
        if isinstance(fn, ast.Name):
            # nested function in an enclosing scope of `qual`
            parts = qual.split(".")
            for i in range(len(parts), 0, -1):
                cand = (mod.dotted, ".".join(parts[:i] + [fn.id]))
                if cand in self.funcs:
                    return cand
            if (mod.dotted, fn.id) in self.funcs:
                return (mod.dotted, fn.id)
            target = self.imports.get((mod.dotted, fn.id))
            if isinstance(target, tuple):
                cand = (target[0], target[1])
                if cand in self.funcs:
                    return cand
            return None
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            if fn.value.id == "self" and cls:
                cand = (mod.dotted, f"{cls}.{fn.attr}")
                if cand in self.funcs:
                    return cand
                return None
            target = self.imports.get((mod.dotted, fn.value.id))
            if isinstance(target, str):
                cand = (target, fn.attr)
                if cand in self.funcs:
                    return cand
        return None


def _has_timeout(call: ast.Call) -> bool:
    """A BOUNDING timeout argument: `result(None)` / `wait(None)` are
    explicitly unbounded and `acquire(True)` is just blocking=True —
    none of them bound the wait."""
    if call.args:
        a = call.args[0]
        if isinstance(a, ast.Constant) and (a.value is None
                                            or a.value is True):
            return False
        return True
    return any(
        kw.arg in ("timeout", "timeout_s")
        and not (isinstance(kw.value, ast.Constant)
                 and kw.value.value is None)
        for kw in call.keywords)


def _is_nonblocking_acquire(call: ast.Call) -> bool:
    """acquire(False) / acquire(blocking=False) returns immediately."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    return any(kw.arg == "blocking"
               and isinstance(kw.value, ast.Constant)
               and kw.value.value is False for kw in call.keywords)


def _call_blocks(call: ast.Call, held_ids: set) -> str | None:
    """Why this call may block forever, or None. `held_ids` exempts
    cv.wait on the held condition (it RELEASES the lock)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        reason = _BLOCKING_NAMES.get(fn.id)
        return f"{fn.id}(): {reason}" if reason else None
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    reason = _BLOCKING_ATTRS.get(attr)
    if reason is None:
        return None
    if attr in ("result", "wait", "acquire", "join") and _has_timeout(call):
        return None        # bounded wait: stalls surface, they don't wedge
    if attr == "acquire" and _is_nonblocking_acquire(call):
        return None        # blocking=False returns immediately
    if attr == "join":
        # str.join / os.path.join take an iterable argument;
        # Thread.join() takes none (the timeout form is exempt above)
        if call.args or call.keywords:
            return None
        if isinstance(fn.value, ast.Constant):
            return None
    if attr == "sleep":
        if not (isinstance(fn.value, ast.Name)
                and fn.value.id in ("time", "_time")):
            return None
    return f".{attr}(): {reason}"


class LockOrderChecker(Checker):
    """See module docstring. New d2h-sync helpers / blocking attribute
    names register in the module-level ``_BLOCKING_NAMES`` /
    ``_BLOCKING_ATTRS`` tables."""

    id = "lock-order"

    def check(self, pkg: Package) -> list[Finding]:
        sym = _Symbols(pkg)
        findings: list[Finding] = []
        edges: dict[tuple, tuple] = {}   # (A, B) -> (rel, line)

        # per-function direct facts
        for info in sym.funcs.values():
            self._scan_function(sym, info, findings, edges)

        # transitive closure: acquires + may_block through resolved calls
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for info in sym.funcs.values():
                acq = set(info.acquires)
                blk = info.may_block or (
                    info.blocks[0][0] if info.blocks else None)
                for callee_key in info.calls:
                    callee = sym.funcs.get(callee_key)
                    if callee is None:
                        continue
                    acq |= callee.all_acquires
                    if blk is None and callee.may_block:
                        blk = (f"calls {callee_key[1]}() which may block "
                               f"({callee.may_block})")
                if acq != info.all_acquires:
                    info.all_acquires = acq
                    changed = True
                if blk != info.may_block:
                    info.may_block = blk
                    changed = True

        # second pass: interprocedural edges + blocking through calls.
        # Functions with no with/acquire can hold nothing — skip them.
        for info in sym.funcs.values():
            if info.hold_potential:
                self._scan_function(sym, info, findings, edges,
                                    interprocedural=True)

        findings.extend(self._cycles(edges, sym))
        return findings

    # ---- per-function walk ----

    def _scan_function(self, sym: _Symbols, info: _FuncInfo,
                       findings: list, edges: dict,
                       interprocedural: bool = False) -> None:
        mod, qual = info.mod, info.key[1]
        local_aliases: dict = {}

        def note_edge(held: list, lock_id: str, line: int) -> None:
            for held_id, _ in held:
                if held_id == lock_id:
                    kind = sym.locks.get(lock_id)
                    if kind is not None and kind.kind == "RLock":
                        continue
                    # reacquire findings emit on the interprocedural
                    # pass only (its held-set is a superset — same
                    # stance as the blocking findings). Sound at class
                    # granularity because calls resolve through `self`
                    # or module scope: same instance, same lock object.
                    if interprocedural:
                        findings.append(Finding(
                            checker=self.id, path=mod.rel, line=line,
                            message=(f"{qual}() re-acquires non-reentrant "
                                     f"lock {lock_id} while already "
                                     "holding it — self-deadlock"),
                            hint="split the locked region, or make the "
                                 "inner path a *_locked helper that "
                                 "asserts the caller holds the lock",
                            key=f"reacquire:{qual}:{lock_id}"))
                    continue
                edges.setdefault((held_id, lock_id),
                                 (mod.rel, line, qual))

        def scan_expr(expr: ast.AST, held: list) -> tuple:
            """One walk per statement: flag blocking calls, record
            acquire() edges, collect the call summary, and return the
            (acquired, released) lock ids so the caller can update its
            held-region (lambdas/nested defs excluded: they run later,
            on some other thread's schedule). Direct blocking findings
            emit on the interprocedural pass (whose held-set is a
            superset); summaries fill on the first."""
            acquired: list = []
            released: set = set()
            for node in _walk_no_nested(expr):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute) \
                        and fn.attr in ("acquire", "release"):
                    lock_id = sym.resolve_lock(mod, info.cls, fn.value,
                                               local_aliases)
                    if lock_id and fn.attr == "release":
                        released.add(lock_id)
                    elif lock_id:
                        info.hold_potential = True
                        if not interprocedural:
                            info.acquires.add(lock_id)
                        note_edge(held, lock_id, node.lineno)
                        acquired.append(lock_id)
                if not interprocedural:
                    why = _call_blocks(node, set())
                    if why:  # feeds the may_block summary
                        info.blocks.append((why, node.lineno))
                    callee = sym.resolve_call(mod, qual, info.cls, fn)
                    if callee:
                        info.calls.add(callee)
                if not held:
                    continue
                held_ids = {h for h, _ in held}
                why = _call_blocks(node, held_ids)
                if why is not None and isinstance(fn, ast.Attribute) \
                        and fn.attr == "wait":
                    # cv.wait on the HELD condition releases it: exempt
                    rid = sym.resolve_lock(mod, info.cls, fn.value,
                                           local_aliases)
                    if rid in held_ids:
                        why = None
                # findings emit on the interprocedural pass only: its
                # held-set is a superset of the first pass's (with-items
                # that are calls resolve there), so emitting once there
                # is complete without double-reporting
                emit = why is not None and interprocedural
                if why is None and interprocedural:
                    callee_key = sym.resolve_call(mod, qual, info.cls, fn)
                    callee = sym.funcs.get(callee_key) if callee_key \
                        else None
                    if callee is not None:
                        for lock_id in callee.all_acquires:
                            note_edge(held, lock_id, node.lineno)
                        if callee.may_block:
                            why = (f"{callee_key[1]}() may block: "
                                   f"{callee.may_block}")
                            emit = True
                if emit:
                    held_desc = ", ".join(sorted(h for h, _ in held))
                    findings.append(Finding(
                        checker=self.id, path=mod.rel,
                        line=node.lineno,
                        message=(f"{qual}() holds {held_desc} across "
                                 f"a blocking call — {why}"),
                        hint="move the blocking call outside the "
                             "locked region (stage under the lock, "
                             "wait outside), or bound the wait with "
                             "a timeout",
                        key=(f"blocking:{qual}:{held_desc}:"
                             f"{_call_desc(node)}")))
            return acquired, released

        def resolve_with_item(item: ast.withitem, held: list,
                              line: int) -> list:
            """A with-item's locks: a lock expr, or a call to a function
            whose (transitive) summary acquires locks."""
            expr = item.context_expr
            lock_id = sym.resolve_lock(mod, info.cls, expr, local_aliases)
            if lock_id:
                if not interprocedural:
                    info.acquires.add(lock_id)
                note_edge(held, lock_id, line)
                return [lock_id]
            if isinstance(expr, ast.Call):
                callee_key = sym.resolve_call(mod, qual, info.cls,
                                              expr.func)
                if callee_key is not None and not interprocedural:
                    # the context call joins the summary: locks a
                    # helper like locked_collective() acquires must
                    # propagate into THIS function's all_acquires, or
                    # cycles through with-item helpers stay invisible
                    # to callers holding other locks
                    info.calls.add(callee_key)
                callee = sym.funcs.get(callee_key) if callee_key else None
                if interprocedural and callee is not None \
                        and callee.all_acquires:
                    for lid in sorted(callee.all_acquires):
                        note_edge(held, lid, line)
                    return sorted(callee.all_acquires)
            return []

        def walk_stmts(stmts: list, held: list) -> None:
            held = list(held)
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue        # walked separately, without `held`
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    # track simple local lock aliases: x = self._lock
                    tgt = (stmt.targets[0] if isinstance(stmt, ast.Assign)
                           and len(stmt.targets) == 1 else
                           stmt.target if isinstance(stmt, ast.AnnAssign)
                           else None)
                    if isinstance(tgt, ast.Name) and stmt.value is not None:
                        lid = sym.resolve_lock(mod, info.cls, stmt.value,
                                               local_aliases)
                        if lid:
                            local_aliases[tgt.id] = lid
                if isinstance(stmt, ast.With):
                    info.hold_potential = True
                    inner = list(held)
                    for item in stmt.items:
                        got = resolve_with_item(item, inner, stmt.lineno)
                        for lid in got:
                            inner.append((lid, stmt.lineno))
                        if isinstance(item.context_expr, ast.Call):
                            for arg in (list(item.context_expr.args)
                                        + [kw.value for kw in
                                           item.context_expr.keywords]):
                                scan_expr(arg, held)
                    walk_stmts(stmt.body, inner)
                    continue
                if isinstance(stmt, (ast.If, ast.While)):
                    scan_expr(stmt.test, held)
                    walk_stmts(stmt.body, held)
                    walk_stmts(stmt.orelse, held)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr(stmt.iter, held)
                    walk_stmts(stmt.body, held)
                    walk_stmts(stmt.orelse, held)
                    continue
                if isinstance(stmt, ast.Try):
                    walk_stmts(stmt.body, held)
                    for h in stmt.handlers:
                        walk_stmts(h.body, held)
                    walk_stmts(stmt.orelse, held)
                    walk_stmts(stmt.finalbody, held)
                    continue
                acquired, released = scan_expr(stmt, held)
                if released:
                    # release() ends a bare-acquire region at this level
                    held = [(h, ln) for h, ln in held if h not in released]
                for lid in acquired:
                    # a bare .acquire() holds to the end of this block
                    held = held + [(lid, stmt.lineno)]

        body = getattr(info.node, "body", [])
        walk_stmts(body, [])
        if not interprocedural:
            info.all_acquires = set(info.acquires)
            if info.blocks:
                info.may_block = info.blocks[0][0]

    # ---- cycle reporting ----

    def _cycles(self, edges: dict, sym: _Symbols) -> list:
        graph: dict[str, set] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        for scc in _tarjan(graph):
            if len(scc) < 2:
                continue
            cyc = sorted(scc)
            sites = []
            for (a, b), (rel, line, fq) in sorted(edges.items()):
                if a in scc and b in scc:
                    sites.append(f"{a} -> {b} at {rel}:{line} ({fq})")
            rel0, line0 = "", 0
            for (a, b), (rel, line, _fq) in sorted(edges.items()):
                if a in scc and b in scc:
                    rel0, line0 = rel, line
                    break
            yield Finding(
                checker=self.id, path=rel0, line=line0,
                message=("lock-order cycle: " + " / ".join(sites)
                         + " — two threads entering from different edges "
                           "deadlock"),
                hint="impose one global order (acquire "
                     f"{cyc[0]} first everywhere) or collapse the locks",
                key="cycle:" + "->".join(cyc))


def _call_desc(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return f".{fn.attr}"
    if isinstance(fn, ast.Name):
        return fn.id
    return "call"


def _walk_no_nested(expr: ast.AST):
    """ast.walk, but do not descend into lambdas/nested defs — their
    bodies execute later, not under the current locks."""
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _tarjan(graph: dict) -> list:
    """Strongly connected components (iterative Tarjan)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs
