from .hashing import token_for, token_for_trace_id, fnv1a_32, fnv1a_64
from .ids import (
    trace_id_to_hex,
    hex_to_trace_id,
    random_trace_id,
    random_span_id,
    pad_trace_id,
    validate_trace_id,
)

__all__ = [
    "token_for",
    "token_for_trace_id",
    "fnv1a_32",
    "fnv1a_64",
    "trace_id_to_hex",
    "hex_to_trace_id",
    "random_trace_id",
    "random_span_id",
    "pad_trace_id",
    "validate_trace_id",
]
