"""Make JAX_PLATFORMS actually stick.

In images whose sitecustomize registers a TPU PJRT plugin, the env var
alone does not stop jax from handshaking the plugin's tunnel at backend
init — a cpu-targeted process then hangs on its first device op
whenever the tunnel is unhealthy. `jax.config.update("jax_platforms",
...)` is the filter that really prevents the plugin init; this helper
applies it from the env var, once, for every entry point (cli/main,
bench.py, __graft_entry__ — tests/conftest.py and parallel/multihost.py
carry their own variants with extra device-count settings).
"""

from __future__ import annotations

import os
import sys


def honor_jax_platforms(required: bool = False) -> None:
    """Apply JAX_PLATFORMS (if set) through jax.config. `required=True`
    surfaces failures loudly — entry points that WILL use jax must not
    silently proceed into the hang this guard exists to prevent."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception as e:  # noqa: BLE001
        msg = f"warning: could not apply JAX_PLATFORMS={want!r} ({e}); " \
              "device init may target an unintended platform"
        print(msg, file=sys.stderr)
        if required:
            raise
