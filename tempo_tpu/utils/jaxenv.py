"""Make JAX_PLATFORMS actually stick.

In images whose sitecustomize registers a TPU PJRT plugin, the env var
alone does not stop jax from handshaking the plugin's tunnel at backend
init — a cpu-targeted process then hangs on its first device op
whenever the tunnel is unhealthy. `jax.config.update("jax_platforms",
...)` is the filter that really prevents the plugin init; this helper
applies it from the env var, once, for every entry point (cli/main,
bench.py, __graft_entry__ — tests/conftest.py and parallel/multihost.py
carry their own variants with extra device-count settings).
"""

from __future__ import annotations

import os
import sys


def enable_compile_cache(path: str,
                         min_compile_time_s: float = 0.1) -> bool:
    """Point JAX's persistent compilation cache at `path` so a process
    restart replays XLA compiles from disk instead of re-paying them
    (the ~20-40 s first-compile at serving scale — VERDICT r4 #3).
    Safe pre-backend-init; returns False (with a stderr note) when the
    running jax build lacks the options. Reference analog: the blocklist
    poller's tenant index as restartable state
    (/root/reference/tempodb/blocklist/poller.go:134-177)."""
    try:
        import jax

        # our serving kernels at small shapes compile in 50-900 ms —
        # below the 1 s default threshold, so lower it: cold-start is
        # exactly the sum of many sub-second compiles
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
        # surface on-disk cache hits in the jit_cache_events counter
        # (result=persisted) so an operator can SEE cold-start compiles
        # being replayed from disk instead of inferring it from wall
        # time; best-effort — the metric is an observability extra
        try:
            from tempo_tpu.observability.profile import (
                watch_persistent_compile_cache,
            )

            watch_persistent_compile_cache()
        except Exception:  # noqa: BLE001 — never fail cache enablement
            pass

        def apply(d: str) -> None:
            if jax.config.jax_compilation_cache_dir == d:
                return
            jax.config.update("jax_compilation_cache_dir", d)
            # jax pins its cache object at first compile; a config
            # update alone never takes effect afterwards (code-review
            # r5, verified against jax 0.9 _initialize_cache)
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:  # noqa: BLE001 — older/newer layouts
                pass

        envdir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
        if envdir:
            # operator/harness-level location: explicit wins. jax reads
            # the env var only at IMPORT time, so a late-set variable
            # must be applied through config here or the cache silently
            # never initializes (code-review r5).
            os.makedirs(envdir, exist_ok=True)
            apply(envdir)
            return True
        cur = jax.config.jax_compilation_cache_dir
        if cur:
            # an earlier explicit/TempoDB choice wins — (re)create the
            # dir rather than stomping it (it may be configured before
            # its mount exists, or a test tempdir may have died under
            # it); repoint only if it is truly unusable
            try:
                os.makedirs(cur, exist_ok=True)
                return True
            except OSError:
                pass
        os.makedirs(path, exist_ok=True)
        apply(path)
        return True
    except Exception as e:  # noqa: BLE001 — cache is an optimization
        print(f"warning: persistent compile cache disabled ({e})",
              file=sys.stderr)
        return False


def honor_jax_platforms(required: bool = False) -> None:
    """Apply JAX_PLATFORMS (if set) through jax.config. `required=True`
    surfaces failures loudly — entry points that WILL use jax must not
    silently proceed into the hang this guard exists to prevent."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax

        jax.config.update("jax_platforms", want)
    except Exception as e:  # noqa: BLE001
        msg = f"warning: could not apply JAX_PLATFORMS={want!r} ({e}); " \
              "device init may target an unintended platform"
        print(msg, file=sys.stderr)
        if required:
            raise
