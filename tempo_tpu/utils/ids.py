"""Trace/span id helpers.

Mirrors the roles of the reference's pkg/util (trace id hex utils) and
pkg/validation/validate.go (128-bit id check).
"""

from __future__ import annotations

import os

TRACE_ID_LEN = 16  # 128-bit
SPAN_ID_LEN = 8


def random_trace_id() -> bytes:
    return os.urandom(TRACE_ID_LEN)


def random_span_id() -> bytes:
    return os.urandom(SPAN_ID_LEN)


def pad_trace_id(tid: bytes) -> bytes:
    """Left-pad a short (64-bit) trace id to 128 bits, as the reference does
    when storing ids from 64-bit emitters."""
    if len(tid) >= TRACE_ID_LEN:
        return tid[-TRACE_ID_LEN:]
    return b"\x00" * (TRACE_ID_LEN - len(tid)) + tid


def validate_trace_id(tid: bytes) -> None:
    if not tid or len(tid) > TRACE_ID_LEN:
        raise ValueError(f"invalid trace id length {len(tid) if tid else 0}")


def trace_id_to_hex(tid: bytes) -> str:
    return pad_trace_id(tid).hex()


def hex_to_trace_id(s: str) -> bytes:
    s = s.strip().lower()
    if len(s) % 2:
        s = "0" + s
    tid = bytes.fromhex(s)
    validate_trace_id(tid)
    return pad_trace_id(tid)
