"""DNS service discovery: thanos-style ``dns+`` / ``dnssrv+`` specs.

Role-equivalent to the reference's thanos DNS provider uses — memberlist
join resolution (cmd/tempo/app modules.go:294) and querier worker →
frontend discovery (modules/querier/worker/worker.go:44). Address specs:

  "host:port"                     → passed through unchanged
  "dns+host:port"                 → A lookup on host, one addr per record
  "dnssrv+_svc._proto.domain"     → SRV lookup; each target resolved to
                                    A records, port taken from the SRV

Implemented directly on the DNS wire format (RFC 1035/2782) over UDP —
header/question encode, answer parse with name-compression pointers,
additional-section A records used when the server provides glue.
Nameserver read from /etc/resolv.conf (overridable). Results are cached
for min(TTL, max_ttl) so gossip-loop callers can re-resolve every round
cheaply; failures serve the last-good answer (stale-on-error, like the
tenant-index staleness fallback in db/poller.py).
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Any

TYPE_A = 1
TYPE_AAAA = 28
TYPE_SRV = 33
CLASS_IN = 1

# one parsed resource record: (name, type, ttl, rdata) where rdata is
# "ip" for A, (prio, weight, port, target) for SRV, raw bytes otherwise
Record = tuple[str, int, int, Any]


# ---------------------------------------------------------------------------
# wire codec


def encode_query(qname: str, qtype: int, txid: int) -> bytes:
    out = struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 0)  # RD=1
    for label in qname.rstrip(".").split("."):
        b = label.encode()
        if not 0 < len(b) < 64:
            raise ValueError(f"dns: bad label in {qname!r}")
        out += bytes([len(b)]) + b
    return out + b"\x00" + struct.pack(">HH", qtype, CLASS_IN)


def _read_name(msg: bytes, pos: int, depth: int = 0) -> tuple[str, int]:
    """Decompress a (possibly pointer-compressed) name. Returns
    (name, position after the name in the original stream)."""
    if depth > 16:
        raise ValueError("dns: compression pointer loop")
    labels: list[str] = []
    while True:
        if pos >= len(msg):
            raise ValueError("dns: truncated name")
        n = msg[pos]
        if n == 0:
            return ".".join(labels), pos + 1
        if n & 0xC0 == 0xC0:  # compression pointer
            ptr = struct.unpack_from(">H", msg, pos)[0] & 0x3FFF
            suffix, _ = _read_name(msg, ptr, depth + 1)
            if suffix:
                labels.append(suffix)
            return ".".join(labels), pos + 2
        pos += 1
        labels.append(msg[pos : pos + n].decode("ascii", "replace"))
        pos += n


def parse_response(msg: bytes,
                   txid: int) -> tuple[list[Record], list[Record]]:
    """→ (answers, additionals); each record is
    (name, type, ttl, rdata-parsed). A → "ip", SRV → (prio, weight,
    port, target), others → raw bytes. All malformed-packet failures
    surface as ValueError (struct.error would otherwise slip past the
    callers' except clauses and kill the gossip thread)."""
    try:
        return _parse_response(msg, txid)
    except struct.error as e:
        raise ValueError(f"dns: malformed response: {e}") from e


def _parse_response(msg: bytes,
                    txid: int) -> tuple[list[Record], list[Record]]:
    if len(msg) < 12:
        raise ValueError("dns: short response")
    rid, flags, qd, an, ns, ar = struct.unpack_from(">HHHHHH", msg, 0)
    if rid != txid:
        raise ValueError("dns: transaction id mismatch")
    rcode = flags & 0xF
    if rcode not in (0, 3):  # NOERROR / NXDOMAIN
        raise ValueError(f"dns: server error rcode={rcode}")
    pos = 12
    for _ in range(qd):  # skip questions
        _, pos = _read_name(msg, pos)
        pos += 4

    def read_records(count: int) -> list[Record]:
        nonlocal pos
        recs: list[Record] = []
        for _ in range(count):
            name, pos2 = _read_name(msg, pos)
            pos = pos2
            rtype, rclass, ttl, rdlen = struct.unpack_from(">HHIH", msg, pos)
            pos += 10
            rdata = msg[pos : pos + rdlen]
            rd_start = pos
            pos += rdlen
            parsed: Any
            if rtype == TYPE_A and rdlen == 4:
                parsed = socket.inet_ntoa(rdata)
            elif rtype == TYPE_SRV:
                prio, weight, port = struct.unpack_from(">HHH", msg, rd_start)
                target, _ = _read_name(msg, rd_start + 6)
                parsed = (prio, weight, port, target)
            else:
                parsed = rdata
            recs.append((name.lower(), rtype, ttl, parsed))
        return recs

    answers = read_records(an)
    read_records(ns)
    additionals = read_records(ar)
    return answers, additionals


# ---------------------------------------------------------------------------
# resolver


def _validate_name(name: str, spec: str) -> None:
    """A name the wire encoder would refuse must fail at validation time,
    not per-tick — so validate by running the encoder itself (no separate
    rule to drift)."""
    try:
        encode_query(name, TYPE_A, 0)
    except ValueError as e:
        raise ValueError(f"dns spec {spec!r}: {e}") from e


def validate_spec(spec: str) -> None:
    """Reject permanently-malformed address specs (a config typo must
    fail at startup, not be silently skipped as a dead seed forever)."""
    if spec.startswith("dnssrv+"):
        name = spec[len("dnssrv+"):]
        if not name or ":" in name:
            raise ValueError(
                f"dnssrv+ spec takes a bare SRV name (port comes from the "
                f"record), got {spec!r}"
            )
        _validate_name(name, spec)
    elif spec.startswith("dns+"):
        host, _, port = spec[len("dns+"):].rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"dns+ spec needs host:port, got {spec!r}")
        _validate_name(host, spec)


def default_nameserver() -> tuple[str, int]:
    try:
        with open("/etc/resolv.conf") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2 and parts[0] == "nameserver":
                    return parts[1], 53
    except OSError:
        pass
    return "127.0.0.1", 53


class Resolver:
    """Minimal UDP stub resolver with per-name TTL cache and
    stale-on-error fallback."""

    def __init__(self, nameserver: tuple[str, int] | None = None,
                 timeout_s: float = 2.0, retries: int = 2,
                 max_ttl_s: float = 30.0, neg_ttl_s: float = 5.0):
        self.nameserver = nameserver or default_nameserver()
        self.timeout_s = timeout_s
        self.retries = retries
        self.max_ttl_s = max_ttl_s
        self.neg_ttl_s = neg_ttl_s
        self._lock = threading.Lock()
        # (qname, qtype) → (expiry_monotonic, records)
        self._cache: dict[tuple[str, int],
                          tuple[float, list[Record]]] = {}
        # negative cache: failed lookups fast-fail until this deadline so
        # a dead DNS server costs one timeout per neg_ttl, not per call
        # (the gossip loop calls resolve every tick)
        self._neg: dict[tuple[str, int], float] = {}

    def query(self, qname: str, qtype: int) -> list[Record]:
        """Answer records of the requested type (cache-aware)."""
        key = (qname.lower().rstrip("."), qtype)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit and hit[0] > now:
                return hit[1]
            if self._neg.get(key, 0) > now:
                # server known-bad: fast-fail, or fast-serve the stale
                # answer — never pay the wire timeout again within neg_ttl
                if hit:
                    return hit[1]
                raise OSError(f"dns: {qname} lookup failing (negative-cached)")
        try:
            answers, additionals = self._query_wire(qname, qtype)
        except (OSError, ValueError):
            with self._lock:
                # deadline stamped AFTER the (possibly seconds-long) wire
                # attempt, else it can expire before it's ever consulted
                self._neg[key] = time.monotonic() + self.neg_ttl_s
            if hit:  # stale-on-error
                return hit[1]
            raise
        with self._lock:
            self._neg.pop(key, None)
        records = [r for r in answers if r[1] == qtype]
        ttl = min([r[2] for r in records] or [0])
        expiry = now + min(max(ttl, 1), self.max_ttl_s)
        # glue: additional-section A records answer the SRV targets'
        # follow-up queries without another round-trip
        glue: dict[str, list[Record]] = {}
        for rec in additionals:
            if rec[1] == TYPE_A:
                glue.setdefault(rec[0], []).append(rec)
        with self._lock:
            self._cache[key] = (expiry, records)
            for gname, recs in glue.items():
                gttl = min(r[2] for r in recs)
                gexp = now + min(max(gttl, 1), self.max_ttl_s)
                self._cache[(gname, TYPE_A)] = (gexp, recs)
        return records

    def _query_wire(self, qname: str,
                    qtype: int) -> tuple[list[Record], list[Record]]:
        last: Exception | None = None
        for _ in range(self.retries + 1):
            txid = random.randrange(1, 0xFFFF)
            pkt = encode_query(qname, qtype, txid)
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                sock.settimeout(self.timeout_s)
                # connect() makes the kernel drop datagrams from any
                # other source — spoofed replies must match addr AND txid
                sock.connect(self.nameserver)
                sock.send(pkt)
                resp = sock.recv(4096)
                if len(resp) >= 4 and struct.unpack_from(">H", resp, 2)[0] & 0x0200:
                    # TC bit: the answer didn't fit in UDP (a large
                    # cluster's SRV set easily passes 512 bytes) — without
                    # this, discovery silently shrinks to whatever the
                    # server squeezed in. RFC 7766: retry over TCP.
                    return self._query_tcp(pkt, txid)
                return parse_response(resp, txid)
            except (OSError, ValueError, struct.error) as e:
                last = e
            finally:
                sock.close()
        raise last if last else OSError("dns: query failed")

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise OSError("dns: tcp connection closed mid-response")
            buf += chunk
        return buf

    def _query_tcp(self, pkt: bytes,
                   txid: int) -> tuple[list[Record], list[Record]]:
        """RFC 7766 fallback for truncated UDP answers: same query over
        TCP with 2-byte length framing."""
        with socket.create_connection(self.nameserver,
                                      timeout=self.timeout_s) as s:
            s.settimeout(self.timeout_s)
            s.sendall(struct.pack(">H", len(pkt)) + pkt)
            (ln,) = struct.unpack(">H", self._recv_exact(s, 2))
            resp = self._recv_exact(s, ln)
        return parse_response(resp, txid)

    # -- spec resolution ----------------------------------------------------

    def resolve_spec(self, spec: str) -> list[str]:
        """One address spec → list of host:port strings (see module doc)."""
        if spec.startswith("dnssrv+"):
            name = spec[len("dnssrv+"):]
            out: list[str] = []
            for _name, _t, _ttl, (_prio, _weight, port, target) in self.query(
                name, TYPE_SRV
            ):
                if not target.rstrip("."):
                    continue  # RFC 2782 root target "." = decidedly unavailable
                ips = [p for _, t, _, p in self.query(target, TYPE_A) if t == TYPE_A]
                out.extend(f"{ip}:{port}" for ip in ips)
            return sorted(set(out))
        if spec.startswith("dns+"):
            hostport = spec[len("dns+"):]
            host, _, port = hostport.rpartition(":")
            if not host:
                raise ValueError(f"dns+ spec needs host:port, got {spec!r}")
            ips = [p for _, t, _, p in self.query(host, TYPE_A) if t == TYPE_A]
            return sorted({f"{ip}:{port}" for ip in ips})
        return [spec]

    def resolve_all(self, specs: list[str]) -> list[str]:
        """Resolve a mixed list of specs; per-spec failures are skipped
        (a dead seed must not stop the gossip loop)."""
        out: list[str] = []
        for spec in specs:
            try:
                out.extend(self.resolve_spec(spec))
            except (OSError, ValueError):
                continue
        # de-dup, stable order
        seen: set[str] = set()
        return [a for a in out if not (a in seen or seen.add(a))]


_default: Resolver | None = None


def default_resolver() -> Resolver:
    global _default
    if _default is None:
        _default = Resolver()
    return _default
