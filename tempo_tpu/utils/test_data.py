"""Deterministic trace fabricators for tests and benchmarks.

Role-equivalent to the reference's pkg/util/test/req.go:14-50 (MakeSpan /
MakeBatch / MakeTrace) and pkg/util.TraceInfo (deterministic regeneration
from a seed, shared by vulture and e2e so readers can verify content
without a side channel).
"""

from __future__ import annotations

import random

from tempo_tpu import tempopb
from tempo_tpu.utils.ids import random_span_id

_SERVICES = [
    "frontend", "checkout", "cart", "payments", "shipping",
    "inventory", "auth", "search", "recs", "gateway",
]
_OPS = ["GET /", "POST /api", "db.query", "cache.get", "publish", "consume"]


def make_span(rng: random.Random, trace_id: bytes,
              start_ns: int | None = None, dur_ns: int | None = None) -> tempopb.Span:
    s = tempopb.Span()
    s.trace_id = trace_id
    s.span_id = rng.randbytes(8)
    s.name = rng.choice(_OPS)
    s.kind = rng.randint(1, 5)
    # spans of one trace cluster around a common epoch so durations are sane
    s.start_time_unix_nano = (
        start_ns if start_ns is not None
        else 1_600_000_000_000_000_000 + rng.randint(0, 3_600_000_000_000)
    )
    s.end_time_unix_nano = s.start_time_unix_nano + (
        dur_ns if dur_ns is not None else rng.randint(1_000_000, 2_000_000_000)
    )
    kv = s.attributes.add()
    kv.key = "http.status_code"
    kv.value.int_value = rng.choice([200, 200, 200, 404, 500])
    kv = s.attributes.add()
    kv.key = "component"
    kv.value.string_value = rng.choice(["grpc", "http", "db"])
    return s


def make_batch(rng: random.Random, trace_id: bytes, spans: int = 2,
               service: str | None = None) -> tempopb.ResourceSpans:
    rs = tempopb.ResourceSpans()
    kv = rs.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = service or rng.choice(_SERVICES)
    ss = rs.scope_spans.add()
    ss.scope.name = "tempo-tpu-test"
    for _ in range(spans):
        ss.spans.append(make_span(rng, trace_id))
    return rs


def make_trace(trace_id: bytes, seed: int | None = None, batches: int = 2,
               spans_per_batch: int = 2) -> tempopb.Trace:
    """Deterministic for a given (trace_id, seed)."""
    rng = random.Random(seed if seed is not None else trace_id)
    t = tempopb.Trace()
    for _ in range(batches):
        t.batches.append(make_batch(rng, trace_id, spans=spans_per_batch))
    return t
