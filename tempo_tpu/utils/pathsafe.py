"""One rule for what may become a filesystem path component.

Tenant ids arrive from an attacker-controllable header and are joined
into backend paths; block ids and object names are internal but cheap
to pin to the same rule. A single helper keeps the API-layer tenant
validation and the LocalBackend defense-in-depth from drifting apart.
"""

from __future__ import annotations

MAX_COMPONENT = 150
_FORBIDDEN = set("/\\\x00")


def check_path_component(part: str, what: str = "path component") -> str:
    """`part` unchanged, or ValueError: separators, NULs, relative
    components (. / ..), emptiness, unprintables, and absurd lengths are
    all rejected before any os.path.join sees the value."""
    if (not part or len(part) > MAX_COMPONENT
            or part in (".", "..")
            or any(c in _FORBIDDEN for c in part)
            or not part.isprintable()):
        raise ValueError(f"invalid {what} {part[:40]!r}")
    return part
