"""Small bounded LRU map shared by the serving-path memo caches.

The same "OrderedDict + lock + cap" idiom kept getting re-written inline
(batcher plan/prune memos, frontend batch shards, tempodb job lists) with
subtly divergent eviction/locking each time; this is the one shared
implementation. Values are opaque; callers needing compound invalidation
(epoch checks, promotion) do it on the value they get back.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any


class BoundedCache:
    """Thread-safe LRU: `get` refreshes recency, `put` evicts the least
    recently used entry past `cap`. Keys and values are opaque
    (hashable keys; callers own the value types)."""

    def __init__(self, cap: int):
        self.cap = cap
        self._d: OrderedDict[Any, Any] = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key: Any, default: Any = None) -> Any:
        with self._lock:
            v = self._d.get(key, default)
            if key in self._d:
                self._d.move_to_end(key)
            return v

    def put(self, key: Any, value: Any) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.cap:
                self._d.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def values(self) -> list[Any]:
        with self._lock:
            return list(self._d.values())
