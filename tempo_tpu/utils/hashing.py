"""Ring-token and shard hashing.

Equivalent roles to the reference's pkg/util/hash.go:7-16 (fnv1a token used
to place a (tenant, traceID) on the distributor ring) and the fnv32 bloom
shard key (tempodb/encoding/common/bloom.go). Implemented here as pure
functions over bytes; a vectorized numpy variant is provided for bulk
sharding on the ingest path.
"""

from __future__ import annotations

import numpy as np

_FNV1A_32_OFFSET = 0x811C9DC5
_FNV1A_32_PRIME = 0x01000193
_FNV1A_64_OFFSET = 0xCBF29CE484222325
_FNV1A_64_PRIME = 0x100000001B3
_MASK32 = 0xFFFFFFFF
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_32(data: bytes, seed: int = _FNV1A_32_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * _FNV1A_32_PRIME) & _MASK32
    return h


def fnv1a_64(data: bytes, seed: int = _FNV1A_64_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * _FNV1A_64_PRIME) & _MASK64
    return h


def token_for(tenant: str, trace_id: bytes) -> int:
    """Ring token for a (tenant, trace id) pair — 32-bit fnv1a over the
    tenant bytes then the trace id bytes, matching the placement role of
    the reference's util.TokenFor."""
    return fnv1a_32(trace_id, seed=fnv1a_32(tenant.encode("utf-8")))


def token_for_trace_id(trace_id: bytes) -> int:
    return fnv1a_32(trace_id)


def fnv1a_32_batch(ids: np.ndarray) -> np.ndarray:
    """Vectorized fnv1a-32 over a [N, L] uint8 array of fixed-length keys.

    Used for bulk bloom-shard assignment when building blocks: one pass per
    byte position, vectorized over N keys.
    """
    assert ids.dtype == np.uint8 and ids.ndim == 2
    h = np.full(ids.shape[0], _FNV1A_32_OFFSET, dtype=np.uint64)
    prime = np.uint64(_FNV1A_32_PRIME)
    mask = np.uint64(_MASK32)
    for col in range(ids.shape[1]):
        h ^= ids[:, col].astype(np.uint64)
        h = (h * prime) & mask
    return h.astype(np.uint32)
