"""Ring-token and shard hashing.

Equivalent roles to the reference's pkg/util/hash.go:7-16 (fnv1a token used
to place a (tenant, traceID) on the distributor ring) and the fnv32 bloom
shard key (tempodb/encoding/common/bloom.go). Implemented here as pure
functions over bytes; a vectorized numpy variant is provided for bulk
sharding on the ingest path.
"""

from __future__ import annotations

import numpy as np

_FNV1A_32_OFFSET = 0x811C9DC5
_FNV1A_32_PRIME = 0x01000193
_FNV1A_64_OFFSET = 0xCBF29CE484222325
_FNV1A_64_PRIME = 0x100000001B3
_MASK32 = 0xFFFFFFFF
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_32(data: bytes, seed: int = _FNV1A_32_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * _FNV1A_32_PRIME) & _MASK32
    return h


def fnv1a_64(data: bytes, seed: int = _FNV1A_64_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * _FNV1A_64_PRIME) & _MASK64
    return h


def mix64(x: int) -> int:
    """splitmix64 finalizer: full-avalanche mix of a 64-bit value.
    fnv1a alone is a poor ring-token source for short keys that differ
    only in a trailing character — the last byte is mixed by a single
    multiply, so the low 32 bits of similar keys cluster (spacing =
    prime mod 2^32). Finalize with this before truncating to a token."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return (x ^ (x >> 31)) & _MASK64


def jump_hash(key: int, num_buckets: int) -> int:
    """Lamping-Veach jump consistent hash: minimal key movement when the
    bucket count grows/shrinks. The SHARED consistent-hash helper — the
    network-cache server selector (backend/netcache.py, the reference's
    pkg/cache jump-hash selector) and the HBM ownership map's
    block -> placement-group step (search/ownership.py) both consume
    this one implementation; do not grow another."""
    if num_buckets <= 1:
        return 0
    b, j = -1, 0
    while j < num_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


def token_for(tenant: str, trace_id: bytes) -> int:
    """Ring token for a (tenant, trace id) pair — 32-bit fnv1a over the
    tenant bytes then the trace id bytes, matching the placement role of
    the reference's util.TokenFor."""
    return fnv1a_32(trace_id, seed=fnv1a_32(tenant.encode("utf-8")))


def token_for_trace_id(trace_id: bytes) -> int:
    return fnv1a_32(trace_id)


def fnv1a_32_batch(ids: np.ndarray) -> np.ndarray:
    """Vectorized fnv1a-32 over a [N, L] uint8 array of fixed-length keys.

    Used for bulk bloom-shard assignment when building blocks: one pass per
    byte position, vectorized over N keys.
    """
    assert ids.dtype == np.uint8 and ids.ndim == 2
    h = np.full(ids.shape[0], _FNV1A_32_OFFSET, dtype=np.uint64)
    prime = np.uint64(_FNV1A_32_PRIME)
    mask = np.uint64(_MASK32)
    for col in range(ids.shape[1]):
        h ^= ids[:, col].astype(np.uint64)
        h = (h * prime) & mask
    return h.astype(np.uint32)
