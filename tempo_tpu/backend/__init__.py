from .types import (
    BlockMeta,
    CompactedBlockMeta,
    TenantIndex,
    NAME_META,
    NAME_COMPACTED_META,
    NAME_DATA,
    NAME_INDEX,
    NAME_TENANT_INDEX,
    bloom_name,
)
from .raw import RawBackend, BackendError, DoesNotExist
from .local import LocalBackend
from .mock import MockBackend
from .cache import CachedBackend, LRUCache
from .netcache import MemcachedCache, RedisCache, BackgroundCache, open_cache

__all__ = [
    "BlockMeta", "CompactedBlockMeta", "TenantIndex",
    "NAME_META", "NAME_COMPACTED_META", "NAME_DATA", "NAME_INDEX",
    "NAME_TENANT_INDEX", "bloom_name",
    "RawBackend", "BackendError", "DoesNotExist",
    "LocalBackend", "MockBackend",
]


def open_backend(cfg: dict) -> RawBackend:
    """Build a backend from config: {"backend": "local", "local": {"path": ...}}.

    Cloud backends (reference tempodb/backend/{s3,gcs,azure}) are stdlib
    HTTP clients behind the same RawBackend interface — SigV4 / bearer /
    SharedKey auth implemented directly, verified in tests against
    in-process mock object stores (the minio/fake-GCS/azurite role in the
    reference's e2e suite).
    """
    kind = cfg.get("backend", "local")
    if kind == "local":
        return LocalBackend(cfg.get("local", {}).get("path", "./tempo-blocks"))
    if kind == "memory":
        return MockBackend()
    if kind == "s3":
        from .s3 import S3Backend
        return S3Backend(**cfg.get("s3", {}))
    if kind == "gcs":
        from .gcs import GCSBackend
        return GCSBackend(**cfg.get("gcs", {}))
    if kind == "azure":
        from .azure import AzureBackend
        return AzureBackend(**cfg.get("azure", {}))
    raise ValueError(f"unknown backend {kind!r}")
