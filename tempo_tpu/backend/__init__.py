from .types import (
    BlockMeta,
    CompactedBlockMeta,
    TenantIndex,
    NAME_META,
    NAME_COMPACTED_META,
    NAME_DATA,
    NAME_INDEX,
    NAME_TENANT_INDEX,
    bloom_name,
)
from .raw import RawBackend, BackendError, DoesNotExist
from .local import LocalBackend
from .mock import MockBackend

__all__ = [
    "BlockMeta", "CompactedBlockMeta", "TenantIndex",
    "NAME_META", "NAME_COMPACTED_META", "NAME_DATA", "NAME_INDEX",
    "NAME_TENANT_INDEX", "bloom_name",
    "RawBackend", "BackendError", "DoesNotExist",
    "LocalBackend", "MockBackend",
]


def open_backend(cfg: dict) -> RawBackend:
    """Build a backend from config: {"backend": "local", "local": {"path": ...}}.

    S3/GCS/Azure are config-gated here; their client implementations land
    behind the same RawBackend interface (reference tempodb/backend/{s3,gcs,
    azure}) and raise until enabled in this environment (zero egress).
    """
    kind = cfg.get("backend", "local")
    if kind == "local":
        return LocalBackend(cfg.get("local", {}).get("path", "./tempo-blocks"))
    if kind == "memory":
        return MockBackend()
    if kind in ("s3", "gcs", "azure"):
        raise NotImplementedError(
            f"backend {kind!r} requires network egress; use 'local' here. "
            "The RawBackend interface is the extension point."
        )
    raise ValueError(f"unknown backend {kind!r}")
