"""In-memory backend for tests (reference tempodb/backend/mocks.go)."""

from __future__ import annotations

import threading

from .raw import RawBackend, DoesNotExist


class MockBackend(RawBackend):
    def __init__(self, fail_reads: bool = False):
        self._objs: dict[tuple[str, str, str], bytes] = {}
        self._lock = threading.Lock()
        self.fail_reads = fail_reads
        self.read_count = 0
        self.write_count = 0

    def _k(self, tenant, block_id, name):
        return (tenant, block_id or "", name)

    def write(self, tenant, block_id, name, data: bytes) -> None:
        with self._lock:
            self.write_count += 1
            self._objs[self._k(tenant, block_id, name)] = bytes(data)

    def read(self, tenant, block_id, name) -> bytes:
        from tempo_tpu.robustness import FAULTS

        if FAULTS.active:
            FAULTS.hit("backend_read_error")  # object-store flake
        with self._lock:
            self.read_count += 1
            if self.fail_reads:
                raise DoesNotExist("mock configured to fail")
            try:
                return self._objs[self._k(tenant, block_id, name)]
            except KeyError:
                raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None

    def read_range(self, tenant, block_id, name, offset, length) -> bytes:
        return self.read(tenant, block_id, name)[offset:offset + length]

    def delete(self, tenant, block_id, name) -> None:
        with self._lock:
            try:
                del self._objs[self._k(tenant, block_id, name)]
            except KeyError:
                raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None

    def list_tenants(self) -> list[str]:
        with self._lock:
            return sorted({t for (t, _, _) in self._objs})

    def list_blocks(self, tenant: str) -> list[str]:
        with self._lock:
            return sorted({b for (t, b, _) in self._objs if t == tenant and b})

    def _block_objects(self, tenant, block_id) -> list[str]:
        with self._lock:
            return [n for (t, b, n) in self._objs if t == tenant and b == block_id]
