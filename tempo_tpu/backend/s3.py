"""S3 object-storage backend (AWS Signature V4, stdlib-only client).

Role-equivalent to the reference's tempodb/backend/s3 (minio-go based,
s3.go). Same key layout: ``<prefix>/<tenant>/<block>/<name>`` with
tenant-level objects at ``<prefix>/<tenant>/<name>``. The reference's
"append emulation" (S3 multipart upload) is unnecessary here: every vT1
object is written whole through the streaming writers, so plain PutObject
suffices and keeps writes atomic (S3 PUT is all-or-nothing).

SigV4 is implemented directly (hmac/hashlib) rather than via an SDK; the
test suite's mock S3 server recomputes and verifies every signature, so
the signing path is covered end to end without network egress.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET

from .raw import RawBackend, BackendError, DoesNotExist
from .transport import HTTPTransport, TransportError

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def sign_v4(*, method: str, host: str, path: str, query: dict,
            headers: dict, payload_sha256: str, region: str,
            access_key: str, secret_key: str,
            now: datetime.datetime | None = None) -> dict:
    """Produce the SigV4 Authorization headers for one request.

    Returns the headers to add (Host/x-amz-date/x-amz-content-sha256/
    Authorization). Exposed as a function so the mock server can verify
    signatures by recomputation.
    """
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")

    all_headers = dict(headers)
    all_headers["host"] = host
    all_headers["x-amz-date"] = amz_date
    all_headers["x-amz-content-sha256"] = payload_sha256

    canon_headers = {k.lower().strip(): " ".join(str(v).split())
                     for k, v in all_headers.items()}
    signed_names = ";".join(sorted(canon_headers))
    canonical_headers = "".join(
        f"{k}:{canon_headers[k]}\n" for k in sorted(canon_headers))
    canonical_query = "&".join(
        f"{_uri_encode(str(k))}={_uri_encode(str(v))}"
        for k, v in sorted(query.items()))
    canonical_request = "\n".join([
        method,
        _uri_encode(path, encode_slash=False) or "/",
        canonical_query,
        canonical_headers,
        signed_names,
        payload_sha256,
    ])
    scope = f"{date_stamp}/{region}/s3/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256",
        amz_date,
        scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(b"AWS4" + secret_key.encode(), date_stamp)
    k = _hmac(k, region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()

    return {
        "Host": host,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_sha256,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_names}, Signature={signature}"
        ),
    }


class S3Backend(RawBackend):
    def __init__(self, *, bucket: str, endpoint: str, region: str = "us-east-1",
                 access_key: str = "", secret_key: str = "", prefix: str = "",
                 timeout_s: float = 30.0, retries: int = 3):
        self.bucket = bucket
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.prefix = prefix.strip("/")
        self.t = HTTPTransport(endpoint, timeout_s=timeout_s,
                               retries=retries, name=f"s3/{bucket}")

    # ---- keypath ----

    def _key(self, tenant: str, block_id: str | None, name: str = "") -> str:
        parts = [p for p in (self.prefix, tenant, block_id, name) if p]
        return "/".join(parts)

    def _sign_path(self, key: str) -> str:
        """Unencoded absolute path; sign_v4 URI-encodes it once, per spec."""
        return f"/{self.bucket}/{key}" if key else f"/{self.bucket}"

    def _wire_path(self, key: str) -> str:
        """Request-line path: the same single URI encoding the signer uses
        (segments encoded, slashes kept) so signature and wire agree for
        keys with spaces/%/# — tenant IDs are arbitrary header strings."""
        return _uri_encode(self._sign_path(key), encode_slash=False)

    # ---- signed request ----

    def _request(self, method: str, key: str, *, query: dict | None = None,
                 headers: dict | None = None, body: bytes = b"",
                 operation: str = "", ok=(200, 204, 206)):
        query = query or {}
        headers = dict(headers or {})
        payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
        headers.update(sign_v4(
            method=method, host=self.t.host_header, path=self._sign_path(key),
            query=query, headers=headers, payload_sha256=payload_hash,
            region=self.region, access_key=self.access_key,
            secret_key=self.secret_key))
        if body:
            headers["Content-Length"] = str(len(body))
        try:
            return self.t.request(method, self._wire_path(key), query=query,
                                  headers=headers, body=body,
                                  operation=operation, ok=ok)
        except TransportError as e:
            if e.status == 404:
                raise DoesNotExist(key) from None
            raise BackendError(str(e)) from e

    # ---- RawBackend ----

    def write(self, tenant, block_id, name, data: bytes) -> None:
        self._request("PUT", self._key(tenant, block_id, name),
                      body=data, operation="PUT")

    def read(self, tenant, block_id, name) -> bytes:
        _, _, data = self._request("GET", self._key(tenant, block_id, name),
                                   operation="GET")
        return data

    def read_range(self, tenant, block_id, name, offset, length) -> bytes:
        _, _, data = self._request(
            "GET", self._key(tenant, block_id, name),
            headers={"Range": f"bytes={offset}-{offset + length - 1}"},
            operation="GET_RANGE")
        return data

    def delete(self, tenant, block_id, name) -> None:
        # S3 DELETE is idempotent (204 even for missing keys); probe first so
        # the RawBackend contract (DoesNotExist) holds.
        self._request("HEAD", self._key(tenant, block_id, name), operation="HEAD")
        self._request("DELETE", self._key(tenant, block_id, name),
                      operation="DELETE", ok=(200, 204))

    # ---- streaming append via multipart upload (reference
    # tempodb/backend/s3/s3.go append emulation: CreateMultipartUpload →
    # UploadPart per Append → CompleteMultipartUpload on CloseAppend).
    # Parts under 5 MiB (except the last) are rejected by real S3, so
    # sub-minimum appends coalesce into a pending buffer.

    _MIN_PART = 5 << 20

    def append(self, tenant, block_id, name, tracker, data: bytes):
        key = self._key(tenant, block_id, name)
        if tracker is None:
            _, _, body = self._request("POST", key, query={"uploads": ""},
                                       operation="CREATE_MULTIPART")
            upload_id = next(iter(self._xml_texts(
                ET.fromstring(body), "UploadId")), "")
            if not upload_id:
                raise BackendError("multipart create returned no UploadId")
            tracker = {"upload_id": upload_id, "etags": [], "pending": b""}
        tracker["pending"] += data
        if len(tracker["pending"]) >= self._MIN_PART:
            self._upload_part(key, tracker)
        return tracker

    def _upload_part(self, key: str, tracker) -> None:
        part_num = len(tracker["etags"]) + 1
        status, headers, _ = self._request(
            "PUT", key,
            query={"partNumber": str(part_num),
                   "uploadId": tracker["upload_id"]},
            body=tracker["pending"], operation="UPLOAD_PART")
        etag = headers.get("ETag", headers.get("Etag", ""))
        tracker["etags"].append(etag)
        tracker["pending"] = b""

    def close_append(self, tenant, block_id, name, tracker) -> None:
        if tracker is None:
            return
        key = self._key(tenant, block_id, name)
        if tracker["pending"] or not tracker["etags"]:
            self._upload_part(key, tracker)  # final part may be < 5 MiB
        parts = "".join(
            f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{e}</ETag></Part>"
            for i, e in enumerate(tracker["etags"]))
        body = (f"<CompleteMultipartUpload>{parts}"
                "</CompleteMultipartUpload>").encode()
        self._request("POST", key, query={"uploadId": tracker["upload_id"]},
                      body=body, operation="COMPLETE_MULTIPART")

    def abort_append(self, tenant, block_id, name, tracker) -> None:
        """AbortMultipartUpload — a failed completion must release the
        pending upload (S3 bills its parts until aborted)."""
        if tracker is None:
            return
        self._request("DELETE", self._key(tenant, block_id, name),
                      query={"uploadId": tracker["upload_id"]},
                      operation="ABORT_MULTIPART", ok=(200, 204))

    @staticmethod
    def _xml_texts(root: ET.Element, path: str) -> list[str]:
        """findall tolerating namespaced and bare tags (minio vs AWS vs mock):
        matches on local tag names."""
        parts = path.split("/")
        nodes = [root]
        for part in parts:
            nodes = [c for n in nodes for c in n
                     if c.tag.rpartition("}")[2] == part]
        return [n.text or "" for n in nodes]

    def _list(self, prefix: str, delimiter: str | None):
        """ListObjectsV2 pagination → (keys, common-prefixes), both relative
        to `prefix`."""
        keys, prefixes, token = [], [], None
        while True:
            q = {"list-type": "2", "prefix": prefix}
            if delimiter:
                q["delimiter"] = delimiter
            if token:
                q["continuation-token"] = token
            _, _, body = self._request("GET", "", query=q, operation="LIST")
            root = ET.fromstring(body)
            keys += [k[len(prefix):]
                     for k in self._xml_texts(root, "Contents/Key")]
            prefixes += [p[len(prefix):].rstrip("/")
                         for p in self._xml_texts(root, "CommonPrefixes/Prefix")]
            trunc = next(iter(self._xml_texts(root, "IsTruncated")), "false")
            tokens = self._xml_texts(root, "NextContinuationToken")
            token = tokens[0] if tokens else None
            if trunc != "true" or not token:
                return sorted(set(keys)), sorted(set(prefixes))

    def _list_prefixes(self, prefix: str) -> list[str]:
        return self._list(prefix, "/")[1]

    def _list_keys(self, prefix: str) -> list[str]:
        return self._list(prefix, None)[0]

    def list_tenants(self) -> list[str]:
        base = f"{self.prefix}/" if self.prefix else ""
        return self._list_prefixes(base)

    def list_blocks(self, tenant: str) -> list[str]:
        return self._list_prefixes(self._key(tenant, None) + "/")

    def _block_objects(self, tenant: str, block_id: str) -> list[str]:
        return self._list_keys(self._key(tenant, block_id) + "/")
