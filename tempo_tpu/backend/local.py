"""Filesystem backend: ``<path>/<tenant>/<block>/<name>``.

Role-equivalent to the reference's tempodb/backend/local (also reused as
the ingester-local store and the WAL /blocks dir). Writes are atomic via
temp-file + rename so a crashed writer never leaves a torn meta.json.
"""

from __future__ import annotations

import os
import tempfile

from .raw import RawBackend, DoesNotExist


class LocalBackend(RawBackend):
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _p(self, tenant: str, block_id: str | None, name: str = "") -> str:
        # defense in depth behind the API-layer tenant validation: no
        # component may escape the root (tenant arrives from a request
        # header; block/name are internal but cheap to pin too). Shared
        # rule with params.validate_tenant via utils/pathsafe.
        from tempo_tpu.utils.pathsafe import check_path_component

        check_path_component(tenant, "tenant")
        if block_id:
            check_path_component(block_id, "block id")
        if name:
            check_path_component(name, "object name")
        parts = [self.path, tenant]
        if block_id:
            parts.append(block_id)
        if name:
            parts.append(name)
        return os.path.join(*parts)

    def write(self, tenant, block_id, name, data: bytes) -> None:
        self._p(tenant, block_id, name)  # validates NAME too (an
        # absolute name would win the later os.path.join outright)
        d = self._p(tenant, block_id)
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=f".{name}.")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(d, name))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def append(self, tenant, block_id, name, tracker, data: bytes):
        """Native streaming append: parts accumulate in a hidden temp file
        that becomes visible atomically at close_append (the write()
        temp+rename contract, extended to incremental writers)."""
        if tracker is None:
            self._p(tenant, block_id, name)  # validate name up front
            d = self._p(tenant, block_id)
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d, prefix=f".{name}.append.")
            os.close(fd)
            tracker = tmp
        with open(tracker, "ab") as f:
            f.write(data)
        return tracker

    def close_append(self, tenant, block_id, name, tracker) -> None:
        if tracker is None:
            return
        os.replace(tracker, self._p(tenant, block_id, name))

    def abort_append(self, tenant, block_id, name, tracker) -> None:
        if tracker is None:
            return
        try:
            os.unlink(tracker)
        except OSError:
            pass

    def read(self, tenant, block_id, name) -> bytes:
        from tempo_tpu.robustness import FAULTS

        if FAULTS.active:
            FAULTS.hit("backend_read_error")  # object-store flake
        try:
            with open(self._p(tenant, block_id, name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None

    def read_range(self, tenant, block_id, name, offset: int, length: int) -> bytes:
        try:
            with open(self._p(tenant, block_id, name), "rb") as f:
                f.seek(offset)
                return f.read(length)
        except FileNotFoundError:
            raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None

    def delete(self, tenant, block_id, name) -> None:
        try:
            os.unlink(self._p(tenant, block_id, name))
        except FileNotFoundError:
            raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None
        # opportunistically remove empty block dirs
        d = self._p(tenant, block_id)
        try:
            if block_id and not os.listdir(d):
                os.rmdir(d)
        except OSError:
            pass

    def list_tenants(self) -> list[str]:
        try:
            return sorted(
                e for e in os.listdir(self.path)
                if os.path.isdir(os.path.join(self.path, e))
            )
        except FileNotFoundError:
            return []

    def list_blocks(self, tenant: str) -> list[str]:
        try:
            base = self._p(tenant, None)
            return sorted(
                e for e in os.listdir(base)
                if os.path.isdir(os.path.join(base, e))
            )
        except FileNotFoundError:
            return []

    def _block_objects(self, tenant: str, block_id: str) -> list[str]:
        try:
            return os.listdir(self._p(tenant, block_id))
        except FileNotFoundError:
            return []
