"""Instrumented HTTP transport shared by the cloud object-store backends.

Role-equivalent to the reference's instrumented backend transports
(tempodb/backend/instrumentation/backend_transports.go:13-50): every
request is timed and counted per (operation, status); retries with
exponential backoff cover transient 5xx and connection resets. Hedging
stays one layer up (db/hedge.HedgedBackend) exactly as the reference
composes hedgedhttp around the instrumented transport.

Pure stdlib (http.client): no egress-dependent SDKs in this image, and an
object-store client needs nothing an HTTP/1.1 connection pool can't give.
"""

from __future__ import annotations

import http.client
import socket
import ssl
import threading
import time
import urllib.parse

from tempo_tpu.observability import Counter, Histogram

_request_duration = Histogram(
    "tempodb_backend_request_duration_seconds",
    "object-store request latency by operation/status",
)
_request_errors = Counter(
    "tempodb_backend_request_errors_total",
    "object-store transport errors (after retries)",
)

_RETRYABLE_STATUS = {429, 500, 502, 503, 504}


class TransportError(Exception):
    def __init__(self, msg: str, status: int = 0, body: bytes = b""):
        super().__init__(msg)
        self.status = status
        self.body = body


class HTTPTransport:
    """Connection-pooled HTTP client for one endpoint.

    One persistent connection per calling thread (the backends are driven
    by worker pools, so this is a natural pool bounded by pool size).
    """

    def __init__(self, endpoint: str, timeout_s: float = 30.0,
                 retries: int = 3, backoff_s: float = 0.25, name: str = ""):
        u = urllib.parse.urlparse(endpoint)
        if u.scheme not in ("http", "https"):
            raise ValueError(f"endpoint must be http(s), got {endpoint!r}")
        self.scheme = u.scheme
        self.host = u.hostname or "localhost"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.base_path = u.path.rstrip("/")
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_s = backoff_s
        self.name = name or self.host
        self._local = threading.local()

    # host:port as a client would send it in Host: (omit default ports)
    @property
    def host_header(self) -> str:
        default = 443 if self.scheme == "https" else 80
        return self.host if self.port == default else f"{self.host}:{self.port}"

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self.scheme == "https":
                conn = http.client.HTTPSConnection(
                    self.host, self.port, timeout=self.timeout_s,
                    context=ssl.create_default_context())
            else:
                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s)
            self._local.conn = conn
        return conn

    def _reset(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            self._local.conn = None

    def request(self, method: str, path: str, *, query: dict | None = None,
                headers: dict | None = None, body: bytes = b"",
                operation: str = "", ok: tuple = (200, 201, 204, 206),
                ) -> tuple[int, dict, bytes]:
        """One logical request with retries. Returns (status, headers, body).

        Raises TransportError when the final attempt is not in `ok` (the
        status is preserved so callers can map 404 → DoesNotExist).
        """
        target = self.base_path + path
        if query:
            # quote (not quote_plus): matches SigV4/SharedKey canonical encoding
            target += "?" + urllib.parse.urlencode(
                sorted(query.items()), quote_via=urllib.parse.quote)
        op = operation or method
        last_exc: Exception | None = None
        for attempt in range(self.retries + 1):
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            t0 = time.monotonic()
            try:
                conn = self._conn()
                conn.request(method, target, body=body or None,
                             headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
            except (OSError, http.client.HTTPException, socket.timeout) as e:
                self._reset()
                last_exc = e
                _request_duration.observe(
                    time.monotonic() - t0, operation=op, status="error")
                continue
            _request_duration.observe(
                time.monotonic() - t0, operation=op, status=str(status))
            if status in ok:
                return status, dict(resp.getheaders()), data
            if status in _RETRYABLE_STATUS and attempt < self.retries:
                continue
            _request_errors.inc(operation=op)
            raise TransportError(
                f"{self.name}: {method} {path} -> {status}",
                status=status, body=data)
        _request_errors.inc(operation=op)
        raise TransportError(f"{self.name}: {method} {path} failed: {last_exc}")
