"""Azure Blob Storage backend (SharedKey auth, stdlib-only client).

Role-equivalent to the reference's tempodb/backend/azure (azblob SDK,
block blobs). Key layout matches the other backends:
``<prefix>/<tenant>/<block>/<name>`` inside one container.

Writes are single PutBlob calls (BlockBlob) — atomic for our object sizes;
the reference's block-list append emulation exists only because its WAL
streams into Azure, which the vT1 design never does (WAL is local disk,
objects are written whole).

SharedKey signing implemented per the Azure REST spec; the mock Azurite-
style server in the test suite recomputes and verifies every signature.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate

from .raw import RawBackend, BackendError, DoesNotExist
from .transport import HTTPTransport, TransportError

API_VERSION = "2020-10-02"


def sign_shared_key(*, method: str, account: str, path: str, query: dict,
                    headers: dict, key_b64: str) -> str:
    """Compute the SharedKey Authorization header value.

    `headers` must already contain the x-ms-* headers and any standard
    headers participating in the string-to-sign. Exposed for the mock
    server's verification.
    """
    std = {k.lower(): str(v) for k, v in headers.items()}

    def h(name: str) -> str:
        return std.get(name, "")

    canonical_headers = "".join(
        f"{k}:{std[k]}\n" for k in sorted(std) if k.startswith("x-ms-"))
    canonical_resource = f"/{account}{path}"
    for k in sorted(query):
        canonical_resource += f"\n{k.lower()}:{query[k]}"
    content_length = h("content-length")
    if content_length == "0":  # 2015-02-21+ semantics: empty, not "0"
        content_length = ""
    string_to_sign = "\n".join([
        method,
        h("content-encoding"), h("content-language"), content_length,
        h("content-md5"), h("content-type"), h("date") if not h("x-ms-date") else "",
        h("if-modified-since"), h("if-match"), h("if-none-match"),
        h("if-unmodified-since"), h("range"),
    ]) + "\n" + canonical_headers + canonical_resource
    mac = hmac.new(base64.b64decode(key_b64), string_to_sign.encode("utf-8"),
                   hashlib.sha256)
    return f"SharedKey {account}:{base64.b64encode(mac.digest()).decode()}"


class AzureBackend(RawBackend):
    def __init__(self, *, container: str, account: str, key: str,
                 endpoint: str = "", prefix: str = "",
                 timeout_s: float = 30.0, retries: int = 3):
        self.container = container
        self.account = account
        self.key = key
        self.prefix = prefix.strip("/")
        endpoint = endpoint or f"https://{account}.blob.core.windows.net"
        self.t = HTTPTransport(endpoint, timeout_s=timeout_s,
                               retries=retries, name=f"azure/{container}")

    def _key(self, tenant: str, block_id: str | None, name: str = "") -> str:
        return "/".join(p for p in (self.prefix, tenant, block_id, name) if p)

    def _blob_path(self, key: str) -> str:
        return f"/{self.container}/{urllib.parse.quote(key)}" if key \
            else f"/{self.container}"

    def _request(self, method: str, key: str, *, query: dict | None = None,
                 headers: dict | None = None, body: bytes = b"",
                 operation: str = "", ok=(200, 201, 202, 206)):
        query = query or {}
        headers = dict(headers or {})
        headers["x-ms-date"] = formatdate(usegmt=True)
        headers["x-ms-version"] = API_VERSION
        headers["Content-Length"] = str(len(body))
        path = self._blob_path(key)
        # sign over the unquoted resource path, as the service does
        sign_path = f"/{self.container}/{key}" if key else f"/{self.container}"
        headers["Authorization"] = sign_shared_key(
            method=method, account=self.account, path=sign_path, query=query,
            headers=headers, key_b64=self.key)
        try:
            return self.t.request(method, path, query=query, headers=headers,
                                  body=body, operation=operation, ok=ok)
        except TransportError as e:
            if e.status == 404:
                raise DoesNotExist(key) from None
            raise BackendError(str(e)) from e

    # ---- RawBackend ----

    def write(self, tenant, block_id, name, data: bytes) -> None:
        self._request("PUT", self._key(tenant, block_id, name), body=data,
                      headers={"x-ms-blob-type": "BlockBlob",
                               "Content-Type": "application/octet-stream"},
                      operation="PUT")

    # ---- streaming append via block blobs (reference
    # tempodb/backend/azure: Put Block per part + Put Block List on close;
    # block ids are base64, fixed-length per blob).

    def append(self, tenant, block_id, name, tracker, data: bytes):
        import base64

        if tracker is None:
            tracker = {"block_ids": []}
        bid = base64.b64encode(
            f"blk-{len(tracker['block_ids']):08d}".encode()).decode()
        self._request("PUT", self._key(tenant, block_id, name),
                      query={"comp": "block", "blockid": bid},
                      body=data, operation="PUT_BLOCK", ok=(201,))
        tracker["block_ids"].append(bid)
        return tracker

    def close_append(self, tenant, block_id, name, tracker) -> None:
        if tracker is None:
            return
        blocks = "".join(f"<Latest>{b}</Latest>" for b in tracker["block_ids"])
        body = (f"<?xml version='1.0' encoding='utf-8'?>"
                f"<BlockList>{blocks}</BlockList>").encode()
        self._request("PUT", self._key(tenant, block_id, name),
                      query={"comp": "blocklist"},
                      headers={"Content-Type": "application/xml"},
                      body=body, operation="PUT_BLOCK_LIST", ok=(201,))

    def abort_append(self, tenant, block_id, name, tracker) -> None:
        """Azure garbage-collects uncommitted blocks after 7 days; there is
        no explicit abort API for block uploads — nothing to do."""

    def read(self, tenant, block_id, name) -> bytes:
        _, _, data = self._request("GET", self._key(tenant, block_id, name),
                                   operation="GET")
        return data

    def read_range(self, tenant, block_id, name, offset, length) -> bytes:
        _, _, data = self._request(
            "GET", self._key(tenant, block_id, name),
            headers={"Range": f"bytes={offset}-{offset + length - 1}"},
            operation="GET_RANGE")
        return data

    def delete(self, tenant, block_id, name) -> None:
        self._request("DELETE", self._key(tenant, block_id, name),
                      operation="DELETE", ok=(200, 202))

    def _list(self, prefix: str, delimiter: str | None):
        blobs, prefixes, marker = [], [], None
        while True:
            q = {"restype": "container", "comp": "list", "prefix": prefix}
            if delimiter:
                q["delimiter"] = delimiter
            if marker:
                q["marker"] = marker
            _, _, body = self._request("GET", "", query=q, operation="LIST")
            root = ET.fromstring(body)
            for el in root.iter("Blob"):
                blobs.append(el.findtext("Name")[len(prefix):])
            for el in root.iter("BlobPrefix"):
                prefixes.append(el.findtext("Name")[len(prefix):].rstrip("/"))
            marker = root.findtext("NextMarker")
            if not marker:
                return sorted(set(blobs)), sorted(set(prefixes))

    def list_tenants(self) -> list[str]:
        base = f"{self.prefix}/" if self.prefix else ""
        return self._list(base, "/")[1]

    def list_blocks(self, tenant: str) -> list[str]:
        return self._list(self._key(tenant, None) + "/", "/")[1]

    def _block_objects(self, tenant: str, block_id: str) -> list[str]:
        return self._list(self._key(tenant, block_id) + "/", None)[0]
