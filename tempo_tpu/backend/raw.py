"""Object-storage backend interface.

Role-equivalent to the reference's tempodb/backend/raw.go:26-45 RawReader /
RawWriter / Compactor triple, collapsed into one ABC (implementations are
local filesystem, in-memory mock; S3/GCS/Azure slot in behind the same
interface). Keypath layout: ``<tenant>/<block_id>/<name>`` with tenant-level
objects at ``<tenant>/<name>``.
"""

from __future__ import annotations

import abc
from typing import Iterable

from .types import (
    BlockMeta,
    CompactedBlockMeta,
    NAME_META,
    NAME_COMPACTED_META,
)


class BackendError(Exception):
    pass


class DoesNotExist(BackendError):
    pass


class RawBackend(abc.ABC):
    # ---- raw object ops ----

    @abc.abstractmethod
    def write(self, tenant: str, block_id: str | None, name: str, data: bytes) -> None:
        """Write an object atomically (block_id None → tenant-level object)."""

    @abc.abstractmethod
    def read(self, tenant: str, block_id: str | None, name: str) -> bytes:
        ...

    @abc.abstractmethod
    def read_range(self, tenant: str, block_id: str | None, name: str,
                   offset: int, length: int) -> bytes:
        ...

    @abc.abstractmethod
    def delete(self, tenant: str, block_id: str | None, name: str) -> None:
        ...

    # ---- append (reference raw.go Append/CloseAppend + AppendTracker):
    # large objects stream out in parts so block completion and compaction
    # never hold a whole block in memory (S3 multipart emulation etc.,
    # reference tempodb/backend/s3/s3.go). Default implementation buffers
    # parts and writes once on close — correct for any backend, bounded
    # only by the object size; real backends override with native
    # multipart/resumable/block-list uploads.

    def append(self, tenant: str, block_id: str | None, name: str,
               tracker, data: bytes):
        """Append `data` to an object under construction. `tracker` is the
        value returned by the previous append (None starts a new one).
        Returns the updated tracker. The object is not visible until
        close_append."""
        if tracker is None:
            tracker = []
        tracker.append(bytes(data))
        return tracker

    def close_append(self, tenant: str, block_id: str | None, name: str,
                     tracker) -> None:
        """Finalize an appended object (commit point for `name`)."""
        if tracker is not None:
            self.write(tenant, block_id, name, b"".join(tracker))

    def abort_append(self, tenant: str, block_id: str | None, name: str,
                     tracker) -> None:
        """Discard an in-progress append (failed completion/compaction):
        release whatever the tracker holds server-side so retries don't
        accumulate orphans (S3 pending multipart uploads bill until a
        lifecycle rule reaps them; local temp files fill the block dir).
        Default: tracker is an in-memory buffer — nothing to release."""

    @abc.abstractmethod
    def list_tenants(self) -> list[str]:
        ...

    @abc.abstractmethod
    def list_blocks(self, tenant: str) -> list[str]:
        ...

    # ---- meta helpers (reference backend.go:21-64) ----

    def write_block_meta(self, meta: BlockMeta) -> None:
        self.write(meta.tenant_id, meta.block_id, NAME_META, meta.to_json())

    def read_block_meta(self, tenant: str, block_id: str) -> BlockMeta:
        return BlockMeta.from_json(self.read(tenant, block_id, NAME_META))

    def write_compacted_meta(self, cm: CompactedBlockMeta) -> None:
        self.write(cm.meta.tenant_id, cm.meta.block_id, NAME_COMPACTED_META, cm.to_json())

    def read_compacted_meta(self, tenant: str, block_id: str) -> CompactedBlockMeta:
        return CompactedBlockMeta.from_json(
            self.read(tenant, block_id, NAME_COMPACTED_META)
        )

    # ---- compactor ops (reference backend Compactor iface) ----

    def mark_compacted(self, meta: BlockMeta) -> None:
        """Flip a block to compacted: write the compacted marker, remove the
        live meta so pollers stop listing it."""
        self.write_compacted_meta(CompactedBlockMeta.from_meta(meta))
        try:
            self.delete(meta.tenant_id, meta.block_id, NAME_META)
        except DoesNotExist:
            pass

    def clear_block(self, tenant: str, block_id: str,
                    names: Iterable[str] | None = None) -> None:
        """Hard-delete a block's objects (retention second phase)."""
        for name in list(names) if names is not None else self._block_objects(tenant, block_id):
            try:
                self.delete(tenant, block_id, name)
            except DoesNotExist:
                pass

    def _block_objects(self, tenant: str, block_id: str) -> list[str]:
        """Names of the objects in a block; backends that can list within a
        block override this. The default derives the bloom shard count from
        the block's (compacted) meta so large blocks don't leak shards."""
        from .types import NAME_DATA, NAME_INDEX, NAME_SEARCH, NAME_SEARCH_HEADER, bloom_name
        names = [NAME_META, NAME_COMPACTED_META, NAME_DATA, NAME_INDEX,
                 NAME_SEARCH, NAME_SEARCH_HEADER]
        shards = 64
        for reader in (self.read_compacted_meta, self.read_block_meta):
            try:
                meta = reader(tenant, block_id)
                meta = getattr(meta, "meta", meta)  # CompactedBlockMeta wraps
                shards = max(shards, meta.bloom_shard_count)
                break
            except BackendError:
                continue
        names += [bloom_name(i) for i in range(shards)]
        return names
