"""GCS object-storage backend (JSON API, stdlib-only client).

Role-equivalent to the reference's tempodb/backend/gcs (google
cloud-storage SDK). Same key layout as the other backends:
``<prefix>/<tenant>/<block>/<name>``.

Auth is a bearer token: either static (config/test), read from a token
file, or fetched from the GCE metadata server when running on GCP
(``metadata`` mode). Service-account JWT self-signing is deliberately not
reimplemented — on-GCP the metadata server is the idiomatic source, and
off-GCP an operator passes a token or uses workload identity; both reduce
to a bearer string at this layer.
"""

from __future__ import annotations

import json
import urllib.parse

from .raw import RawBackend, BackendError, DoesNotExist
from .transport import HTTPTransport, TransportError


class _TokenSource:
    # refresh this long before expiry so in-flight requests never race it
    _EXPIRY_SLACK_S = 120

    def __init__(self, cfg: dict):
        self.static = cfg.get("token", "")
        self.token_file = cfg.get("token_file", "")
        self.use_metadata = cfg.get("token_source", "") == "metadata"
        self.metadata_endpoint = cfg.get(
            "metadata_endpoint", "http://169.254.169.254")
        self._cached = ""
        self._expires_at = 0.0

    def invalidate(self) -> None:
        """Drop the cached token (called on 401 so the next request
        refetches instead of failing until restart)."""
        self._expires_at = 0.0

    def get(self) -> str:
        if self.static:
            return self.static
        if self.token_file:
            with open(self.token_file) as f:
                return f.read().strip()
        if self.use_metadata:
            import time
            if time.monotonic() >= self._expires_at:
                t = HTTPTransport(self.metadata_endpoint, timeout_s=5,
                                  retries=2, name="gce-metadata")
                _, _, body = t.request(
                    "GET",
                    "/computeMetadata/v1/instance/service-accounts/default/token",
                    headers={"Metadata-Flavor": "Google"}, operation="TOKEN")
                doc = json.loads(body)
                self._cached = doc["access_token"]
                self._expires_at = (time.monotonic()
                                    + float(doc.get("expires_in", 3600))
                                    - self._EXPIRY_SLACK_S)
            return self._cached
        return ""


class GCSBackend(RawBackend):
    def __init__(self, *, bucket: str, endpoint: str = "https://storage.googleapis.com",
                 prefix: str = "", timeout_s: float = 30.0, retries: int = 3,
                 **auth_cfg):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.tokens = _TokenSource(auth_cfg)
        self.t = HTTPTransport(endpoint, timeout_s=timeout_s,
                               retries=retries, name=f"gcs/{bucket}")

    def _key(self, tenant: str, block_id: str | None, name: str = "") -> str:
        return "/".join(p for p in (self.prefix, tenant, block_id, name) if p)

    def _headers(self, extra: dict | None = None) -> dict:
        h = dict(extra or {})
        tok = self.tokens.get()
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _obj_path(self, key: str) -> str:
        return (f"/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}"
                f"/o/{urllib.parse.quote(key, safe='')}")

    def _request(self, method: str, path: str, *, query=None, headers=None,
                 body=b"", operation="", ok=(200, 204, 206)):
        for attempt in (0, 1):
            try:
                return self.t.request(method, path, query=query,
                                      headers=self._headers(headers), body=body,
                                      operation=operation, ok=ok)
            except TransportError as e:
                if e.status == 404:
                    raise DoesNotExist(path) from None
                if e.status == 401 and attempt == 0:
                    # expired/revoked token: refetch once, then retry
                    self.tokens.invalidate()
                    continue
                raise BackendError(str(e)) from e

    # ---- RawBackend ----

    def write(self, tenant, block_id, name, data: bytes) -> None:
        path = (f"/upload/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}/o")
        self._request("POST", path,
                      query={"uploadType": "media",
                             "name": self._key(tenant, block_id, name)},
                      headers={"Content-Type": "application/octet-stream",
                               "Content-Length": str(len(data))},
                      body=data, operation="PUT")

    # ---- streaming append via resumable upload (the GCS counterpart of
    # the reference's streaming writer): POST uploadType=resumable opens a
    # session; each part PUTs with a Content-Range; chunks must be 256 KiB
    # multiples except the last, so sub-multiple appends coalesce.

    _CHUNK_QUANTUM = 256 << 10

    def append(self, tenant, block_id, name, tracker, data: bytes):
        if tracker is None:
            path = (f"/upload/storage/v1/b/"
                    f"{urllib.parse.quote(self.bucket, safe='')}/o")
            _, headers, _ = self._request(
                "POST", path,
                query={"uploadType": "resumable",
                       "name": self._key(tenant, block_id, name)},
                headers={"Content-Type": "application/json"},
                body=b"{}", operation="CREATE_RESUMABLE")
            session = headers.get("Location", headers.get("location", ""))
            if not session:
                raise BackendError("resumable upload returned no session URI")
            # the session URI is absolute; keep only path?query for the
            # transport (same host)
            u = urllib.parse.urlsplit(session)
            tracker = {"session": u.path, "query": dict(
                urllib.parse.parse_qsl(u.query)), "offset": 0, "pending": b""}
        tracker["pending"] += data
        n = len(tracker["pending"]) // self._CHUNK_QUANTUM * self._CHUNK_QUANTUM
        if n:
            self._put_chunk(tracker, tracker["pending"][:n], final=False)
            tracker["pending"] = tracker["pending"][n:]
        return tracker

    def _put_chunk(self, tracker, chunk: bytes, final: bool) -> None:
        start = tracker["offset"]
        end = start + len(chunk)
        total = str(end) if final else "*"
        if chunk:
            rng = f"bytes {start}-{end - 1}/{total}"
        else:
            rng = f"bytes */{total}"  # zero-byte finalize
        # 308 = Resume Incomplete (intermediate chunk ack)
        self._request("PUT", tracker["session"],
                      query=tracker["query"],
                      headers={"Content-Range": rng,
                               "Content-Length": str(len(chunk))},
                      body=chunk, operation="UPLOAD_CHUNK",
                      ok=(200, 201, 308))
        tracker["offset"] = end

    def close_append(self, tenant, block_id, name, tracker) -> None:
        if tracker is None:
            return
        self._put_chunk(tracker, tracker["pending"], final=True)
        tracker["pending"] = b""

    def abort_append(self, tenant, block_id, name, tracker) -> None:
        """Cancel the resumable session (GCS answers 499 Client Closed
        Request for a successful cancel) so failed completions don't leave
        week-long pending sessions behind."""
        if tracker is None:
            return
        self._request("DELETE", tracker["session"], query=tracker["query"],
                      operation="CANCEL_RESUMABLE", ok=(200, 204, 499))

    def read(self, tenant, block_id, name) -> bytes:
        _, _, data = self._request(
            "GET", self._obj_path(self._key(tenant, block_id, name)),
            query={"alt": "media"}, operation="GET")
        return data

    def read_range(self, tenant, block_id, name, offset, length) -> bytes:
        _, _, data = self._request(
            "GET", self._obj_path(self._key(tenant, block_id, name)),
            query={"alt": "media"},
            headers={"Range": f"bytes={offset}-{offset + length - 1}"},
            operation="GET_RANGE")
        return data

    def delete(self, tenant, block_id, name) -> None:
        self._request("DELETE", self._obj_path(self._key(tenant, block_id, name)),
                      operation="DELETE", ok=(200, 204))

    def _list(self, prefix: str, delimiter: str | None):
        items, prefixes, token = [], [], None
        path = f"/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}/o"
        while True:
            q = {"prefix": prefix}
            if delimiter:
                q["delimiter"] = delimiter
            if token:
                q["pageToken"] = token
            _, _, body = self._request("GET", path, query=q, operation="LIST")
            doc = json.loads(body)
            items += [it["name"][len(prefix):] for it in doc.get("items", [])]
            prefixes += [p[len(prefix):].rstrip("/")
                         for p in doc.get("prefixes", [])]
            token = doc.get("nextPageToken")
            if not token:
                return sorted(set(items)), sorted(set(prefixes))

    def list_tenants(self) -> list[str]:
        base = f"{self.prefix}/" if self.prefix else ""
        return self._list(base, "/")[1]

    def list_blocks(self, tenant: str) -> list[str]:
        return self._list(self._key(tenant, None) + "/", "/")[1]

    def _block_objects(self, tenant: str, block_id: str) -> list[str]:
        return self._list(self._key(tenant, block_id) + "/", None)[0]
