"""Block metadata and object naming.

Role-equivalent to the reference's tempodb/backend/block_meta.go and
tenant index (blocklist/poller writes index.json.gz). The only durable,
shared state in the whole system is object storage; meta.json written last
is the commit record for a block (SURVEY.md §1 invariant, §5 checkpoint).
"""

from __future__ import annotations

import gzip
import hashlib
import json
import time
import uuid
from dataclasses import dataclass, field, asdict

VERSION_VT1 = "vT1"

NAME_META = "meta.json"
NAME_COMPACTED_META = "meta.compacted.json"
NAME_DATA = "data"
NAME_INDEX = "index"
NAME_TENANT_INDEX = "index.json.gz"

# columnar search block objects (tempo_tpu.search)
NAME_SEARCH = "search"
NAME_SEARCH_HEADER = "search-header.json"


def bloom_name(shard: int) -> str:
    return f"bloom-{shard}"


def new_block_id() -> str:
    return str(uuid.uuid4())


@dataclass
class BlockMeta:
    version: str = VERSION_VT1
    block_id: str = ""
    tenant_id: str = ""
    start_time: int = 0  # unix seconds, min over objects
    end_time: int = 0    # unix seconds, max over objects
    total_objects: int = 0
    size: int = 0        # bytes of the data object
    compaction_level: int = 0
    encoding: str = "zstd"        # page compression
    index_page_size: int = 0      # records per index page
    total_records: int = 0
    data_encoding: str = "v2"     # trace object codec
    bloom_shard_count: int = 0
    bloom_shard_size_bytes: int = 0
    min_id: str = ""  # hex, lowest object id in block
    max_id: str = ""  # hex, highest object id in block
    # search container geometry, recorded so the frontend can compute
    # page-range jobs from the blocklist alone — no per-query header
    # fetches (cf. reference BlockMeta Size/TotalRecords feeding
    # searchsharding.go page math)
    search_pages: int = 0
    search_size: int = 0              # compressed container bytes
    search_entries_per_page: int = 0  # E of the page geometry
    search_kv_per_entry: int = 0      # C of the page geometry

    def __post_init__(self):
        if not self.block_id:
            self.block_id = new_block_id()

    def to_json(self) -> bytes:
        return json.dumps(asdict(self), sort_keys=True).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "BlockMeta":
        d = json.loads(data)
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})

    def extend_range(self, start: int, end: int) -> None:
        if start:
            self.start_time = min(self.start_time or start, start)
        if end:
            self.end_time = max(self.end_time, end)


@dataclass
class CompactedBlockMeta:
    meta: BlockMeta = field(default_factory=BlockMeta)
    compacted_time: int = 0  # unix seconds

    def to_json(self) -> bytes:
        return json.dumps(
            {"meta": asdict(self.meta), "compacted_time": self.compacted_time},
            sort_keys=True,
        ).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "CompactedBlockMeta":
        d = json.loads(data)
        return cls(meta=BlockMeta(**{
            k: v for k, v in d["meta"].items()
            if k in BlockMeta.__dataclass_fields__
        }), compacted_time=d.get("compacted_time", 0))

    @classmethod
    def from_meta(cls, meta: BlockMeta) -> "CompactedBlockMeta":
        return cls(meta=meta, compacted_time=int(time.time()))


@dataclass
class TenantIndex:
    """Gzipped per-tenant listing of block metas, written by the elected
    poller so other instances can skip the per-block meta fetches
    (reference blocklist/poller.go:134-177)."""

    created_at: int = 0
    metas: list = field(default_factory=list)            # list[BlockMeta]
    compacted: list = field(default_factory=list)        # list[CompactedBlockMeta]

    def to_bytes(self) -> bytes:
        content = json.dumps({
            "metas": [asdict(m) for m in self.metas],
            "compacted": [
                {"meta": asdict(c.meta), "compacted_time": c.compacted_time}
                for c in self.compacted
            ],
        })
        # content digest FIRST in the document: created_at changes on
        # every builder cycle (it doubles as the builder heartbeat), so
        # readers dedupe re-parses by this digest — extractable from the
        # head of the gunzipped bytes without a full json parse
        digest = hashlib.sha256(content.encode()).hexdigest()
        head = json.dumps({
            "content_digest": digest,
            "created_at": self.created_at or int(time.time()),
        })
        return gzip.compress((head[:-1] + ", " + content[1:]).encode())

    @classmethod
    def from_bytes(cls, data: bytes) -> "TenantIndex":
        import zlib

        try:
            text = gzip.decompress(data)
        except (OSError, EOFError, zlib.error) as e:
            # normalize: callers treat ValueError as "index unreadable"
            raise ValueError(f"corrupt tenant index: {e}") from e
        return cls.from_json_bytes(text)

    @classmethod
    def from_json_bytes(cls, text: bytes) -> "TenantIndex":
        try:
            return cls._from_json_bytes(text)
        except (KeyError, TypeError, AttributeError,
                json.JSONDecodeError) as e:
            # shape-corrupt JSON normalizes to the ValueError contract
            # (readers fall back to a direct block poll on it)
            raise ValueError(f"corrupt tenant index: {e}") from e

    @classmethod
    def _from_json_bytes(cls, text: bytes) -> "TenantIndex":
        d = json.loads(text)
        return cls(
            created_at=d.get("created_at", 0),
            metas=[BlockMeta(**{
                k: v for k, v in m.items() if k in BlockMeta.__dataclass_fields__
            }) for m in d.get("metas", [])],
            compacted=[CompactedBlockMeta(
                meta=BlockMeta(**{
                    k: v for k, v in c["meta"].items()
                    if k in BlockMeta.__dataclass_fields__
                }),
                compacted_time=c.get("compacted_time", 0),
            ) for c in d.get("compacted", [])],
        )
