"""Read-through cache wrap for backend reads.

Role-equivalent to the reference's tempodb/backend/cache + pkg/cache
(SURVEY.md layer 1): bloom shards and index objects are small and hot —
wrap the RawBackend so their reads hit an in-process cache. The Cache
interface {store, fetch, stop} matches the reference's (pkg/cache/
cache.go:14-18); memcached/redis client implementations slot in behind it
(network clients are gated in this environment — the LRU is the default
tier, and device HBM staging in tempo_tpu.db is the tier above).

shouldCache heuristics (reference tempodb.go:461-489): only bloom/index
reads, and only for blocks older than `min_compaction_level` / younger
than `max_block_age` knobs here reduced to a name-predicate default.
"""

from __future__ import annotations

import collections
import threading

from .raw import RawBackend
from .types import NAME_INDEX


class LRUCache:
    """The in-process Cache implementation."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = max_bytes
        self._data: collections.OrderedDict[str, bytes] = collections.OrderedDict()
        self._size = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def store(self, key: str, val: bytes) -> None:
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._size -= len(old)
            self._data[key] = val
            self._size += len(val)
            while self._size > self.max_bytes and self._data:
                _, evicted = self._data.popitem(last=False)
                self._size -= len(evicted)

    def fetch(self, key: str) -> bytes | None:
        with self._lock:
            val = self._data.get(key)
            if val is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return val

    def stop(self) -> None:
        with self._lock:
            self._data.clear()
            self._size = 0


def default_should_cache(name: str) -> bool:
    return name == NAME_INDEX or name.startswith("bloom-") or \
        name == "search-header.json"


class CachedBackend(RawBackend):
    """RawBackend wrapper: read-through on cacheable object names."""

    def __init__(self, inner: RawBackend, cache: LRUCache | None = None,
                 should_cache=default_should_cache):
        self.inner = inner
        self.cache = cache or LRUCache()
        self.should_cache = should_cache

    def _key(self, tenant, block_id, name) -> str:
        return f"{tenant}/{block_id or ''}/{name}"

    def read(self, tenant, block_id, name) -> bytes:
        if not self.should_cache(name):
            return self.inner.read(tenant, block_id, name)
        key = self._key(tenant, block_id, name)
        val = self.cache.fetch(key)
        if val is None:
            val = self.inner.read(tenant, block_id, name)
            self.cache.store(key, val)
        return val

    def write(self, tenant, block_id, name, data: bytes) -> None:
        self.inner.write(tenant, block_id, name, data)
        if self.should_cache(name):
            self.cache.store(self._key(tenant, block_id, name), data)

    def append(self, tenant, block_id, name, tracker, data: bytes):
        # forward so the inner backend's native streaming (S3 multipart,
        # GCS resumable…) is reached — the RawBackend default would
        # silently buffer the whole object in memory instead
        return self.inner.append(tenant, block_id, name, tracker, data)

    def close_append(self, tenant, block_id, name, tracker) -> None:
        self.inner.close_append(tenant, block_id, name, tracker)

    def abort_append(self, tenant, block_id, name, tracker) -> None:
        self.inner.abort_append(tenant, block_id, name, tracker)

    def read_range(self, tenant, block_id, name, offset, length) -> bytes:
        return self.inner.read_range(tenant, block_id, name, offset, length)

    def delete(self, tenant, block_id, name) -> None:
        self.inner.delete(tenant, block_id, name)

    def list_tenants(self):
        return self.inner.list_tenants()

    def list_blocks(self, tenant):
        return self.inner.list_blocks(tenant)

    def _block_objects(self, tenant, block_id):
        return self.inner._block_objects(tenant, block_id)
