"""Network cache clients: memcached and Redis, plus write-behind.

Role-equivalent to the reference's pkg/cache (memcached*.go with the
jump-hash server selector, redis*.go, background.go write-behind). Both
clients implement the same Cache interface as backend.cache.LRUCache
{store, fetch, stop} so they slot behind CachedBackend unchanged.

Protocol clients are stdlib sockets speaking the wire protocols directly
(memcached text protocol, RESP2) — no client library in this image, and
the protocols are a few dozen lines each. Cache errors NEVER propagate:
a down cache node degrades to a miss (store drops, fetch returns None),
exactly the reference's failure stance.
"""

from __future__ import annotations

import queue
import socket
import threading

from tempo_tpu.observability import Counter
# jump_hash re-exported for compatibility: the implementation moved to
# utils.hashing so the HBM ownership map (search/ownership.py) and this
# server selector share ONE consistent-hash helper
from tempo_tpu.utils.hashing import fnv1a_64, jump_hash  # noqa: F401

_cache_errors = Counter("tempo_cache_errors_total",
                        "network cache operation failures (degraded to miss)")
_cache_dropped = Counter("tempo_cache_background_dropped_total",
                         "write-behind stores dropped on queue overflow")


class _ConnPool:
    """One persistent socket per (thread, server)."""

    def __init__(self, servers: list[tuple[str, int]], timeout_s: float):
        self.servers = servers
        self.timeout_s = timeout_s
        self._local = threading.local()

    def sock(self, idx: int) -> socket.socket:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        s = conns.get(idx)
        if s is None:
            s = socket.create_connection(self.servers[idx],
                                         timeout=self.timeout_s)
            conns[idx] = s
        return s

    def drop(self, idx: int) -> None:
        conns = getattr(self._local, "conns", None)
        if conns and idx in conns:
            try:
                conns[idx].close()
            except OSError:
                pass
            del conns[idx]

    def close_all(self) -> None:
        conns = getattr(self._local, "conns", None) or {}
        for s in conns.values():
            try:
                s.close()
            except OSError:
                pass
        conns.clear()


def _parse_servers(servers: str | list) -> list[tuple[str, int]]:
    if isinstance(servers, str):
        servers = [s.strip() for s in servers.split(",") if s.strip()]
    out = []
    for s in servers:
        if isinstance(s, (tuple, list)):
            out.append((s[0], int(s[1])))
        else:
            host, _, port = s.rpartition(":")
            out.append((host or "127.0.0.1", int(port)))
    return out


class _NetCache:
    """Shared skeleton: jump-hash selection + error-to-miss degradation."""

    def __init__(self, servers, timeout_s: float = 0.5, ttl_s: int = 0):
        self.pool = _ConnPool(_parse_servers(servers), timeout_s)
        self.ttl_s = ttl_s

    def _select(self, key: str) -> int:
        return jump_hash(fnv1a_64(key.encode()), len(self.pool.servers))

    # any wire trouble — IO errors AND malformed replies (ValueError/
    # IndexError from parsing) — degrades to a miss; the socket is dropped
    # because a desynced connection would corrupt every later op on it
    _WIRE_ERRORS = (OSError, ValueError, IndexError)

    def store(self, key: str, val: bytes) -> None:
        idx = self._select(key)
        try:
            self._store(self.pool.sock(idx), key, val)
        except self._WIRE_ERRORS:
            _cache_errors.inc(op="store")
            self.pool.drop(idx)

    def fetch(self, key: str) -> bytes | None:
        idx = self._select(key)
        try:
            return self._fetch(self.pool.sock(idx), key)
        except self._WIRE_ERRORS:
            _cache_errors.inc(op="fetch")
            self.pool.drop(idx)
            return None

    def stop(self) -> None:
        self.pool.close_all()

    # subclass protocol ops raise OSError on any wire trouble
    def _store(self, s: socket.socket, key: str, val: bytes) -> None:
        raise NotImplementedError

    def _fetch(self, s: socket.socket, key: str) -> bytes | None:
        raise NotImplementedError


def _read_line(s: socket.socket, buf: bytearray) -> bytes:
    while b"\r\n" not in buf:
        chunk = s.recv(65536)
        if not chunk:
            raise OSError("connection closed")
        buf += chunk
    line, _, rest = bytes(buf).partition(b"\r\n")
    buf[:] = rest
    return line


def _read_n(s: socket.socket, buf: bytearray, n: int) -> bytes:
    while len(buf) < n:
        chunk = s.recv(65536)
        if not chunk:
            raise OSError("connection closed")
        buf += chunk
    out = bytes(buf[:n])
    buf[:] = buf[n:]
    return out


_KEY_UNSAFE = set(range(0x21)) | {0x7F}  # control chars + space


def safe_cache_key(key: str, max_len: int = 250) -> str:
    """Memcached-safe key. Keys embed tenant IDs taken verbatim from the
    X-Scope-OrgID header; whitespace/CR-LF would desync the text protocol
    (command injection → cross-tenant cache poisoning), and memcached caps
    keys at 250 bytes — any such key is replaced by its hash."""
    raw = key.encode()
    if len(raw) <= max_len and not any(b in _KEY_UNSAFE for b in raw):
        return key
    import hashlib
    return "h:" + hashlib.sha256(raw).hexdigest()


class MemcachedCache(_NetCache):
    """Memcached text protocol over a jump-hash-selected server list."""

    def _store(self, s, key, val):
        key = safe_cache_key(key)
        s.sendall(f"set {key} 0 {self.ttl_s} {len(val)}\r\n".encode()
                  + val + b"\r\n")
        buf = bytearray()
        resp = _read_line(s, buf)
        if resp not in (b"STORED", b"NOT_STORED"):
            raise OSError(f"memcached: unexpected {resp[:40]!r}")

    def _fetch(self, s, key):
        key = safe_cache_key(key)
        s.sendall(f"get {key}\r\n".encode())
        buf = bytearray()
        line = _read_line(s, buf)
        if line == b"END":
            return None
        if not line.startswith(b"VALUE "):
            raise OSError(f"memcached: unexpected {line[:40]!r}")
        nbytes = int(line.split()[3])
        # a hostile/broken server declaring a huge length must degrade
        # (counted wire error), not drive an allocation that OOMs the
        # reader — cached objects are bounded page/index blobs
        if not 0 <= nbytes <= (256 << 20):
            raise ValueError(f"memcached: implausible value length {nbytes}")
        val = _read_n(s, buf, nbytes)
        _read_n(s, buf, 2)          # \r\n after data
        end = _read_line(s, buf)
        if end != b"END":
            raise OSError(f"memcached: missing END, got {end[:40]!r}")
        return val


class RedisCache(_NetCache):
    """RESP2 client (SET [EX ttl] / GET), single server or jump-hash list."""

    @staticmethod
    def _cmd(*args: bytes) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    def _store(self, s, key, val):
        args = [b"SET", key.encode(), val]
        if self.ttl_s:
            args += [b"EX", str(self.ttl_s).encode()]
        s.sendall(self._cmd(*args))
        buf = bytearray()
        resp = _read_line(s, buf)
        if not resp.startswith(b"+OK"):
            raise OSError(f"redis: unexpected {resp[:40]!r}")

    def _fetch(self, s, key):
        s.sendall(self._cmd(b"GET", key.encode()))
        buf = bytearray()
        line = _read_line(s, buf)
        if not line.startswith(b"$"):
            raise OSError(f"redis: unexpected {line[:40]!r}")
        n = int(line[1:])
        if n == -1:
            return None
        if not 0 <= n <= (256 << 20):  # same hostile-length stance as
            raise ValueError(           # the memcached client
                f"redis: implausible bulk length {n}")
        val = _read_n(s, buf, n)
        _read_n(s, buf, 2)
        return val


class BackgroundCache:
    """Write-behind wrapper (reference pkg/cache/background.go): stores are
    queued and written by worker threads so the read path never blocks on
    cache writes; overflow drops the store (it's a cache)."""

    def __init__(self, inner, workers: int = 2, queue_size: int = 1024):
        self.inner = inner
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._run, daemon=True)
            for _ in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                key, val = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            self.inner.store(key, val)
            self._q.task_done()

    def store(self, key: str, val: bytes) -> None:
        try:
            self._q.put_nowait((key, val))
        except queue.Full:
            _cache_dropped.inc()

    def fetch(self, key: str) -> bytes | None:
        return self.inner.fetch(key)

    def flush(self, timeout_s: float = 5.0) -> None:
        """Drain pending stores (tests / shutdown). unfinished_tasks (not
        empty()) is the drain condition: a dequeued item still mid-store
        counts until its task_done."""
        import time
        deadline = time.monotonic() + timeout_s
        while self._q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1)
        self.inner.stop()


def open_cache(cfg: dict):
    """Cache factory from config (reference tempodb cache config):
    {"cache": "memcached"|"redis"|"lru"|"none", ...}."""
    from .cache import LRUCache

    kind = cfg.get("cache", "lru")
    if kind in ("none", ""):
        return None
    if kind == "lru":
        return LRUCache(cfg.get("lru", {}).get("max_bytes", 256 << 20))
    if kind == "memcached":
        c = cfg.get("memcached", {})
        inner = MemcachedCache(c.get("servers", "127.0.0.1:11211"),
                               timeout_s=c.get("timeout_s", 0.5),
                               ttl_s=c.get("ttl_s", 0))
    elif kind == "redis":
        c = cfg.get("redis", {})
        inner = RedisCache(c.get("servers", "127.0.0.1:6379"),
                           timeout_s=c.get("timeout_s", 0.5),
                           ttl_s=c.get("ttl_s", 0))
    else:
        raise ValueError(f"unknown cache {kind!r}")
    bg = c.get("background", {})
    if bg.get("enabled", True):
        return BackgroundCache(inner, workers=bg.get("workers", 2),
                               queue_size=bg.get("queue_size", 1024))
    return inner
