"""Block index: fixed 28-byte records in checksummed pages.

Record = ``| 16B max_id | u64 start | u32 len |`` — one per data page,
where max_id is the highest object id in that page (the index is
downsampled: many objects per record). Index pages carry an xxhash64
checksum so torn reads are detected (reference: record.go:13,64-84,
index_writer.go, index_reader.go:42-143 with xxhash check :134-137).

Lookup: binary search for the first record with max_id >= target, fetch
that data page, scan. Implemented over numpy so a whole index column loads
as one array.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np
import xxhash
from tempo_tpu.utils.ids import pad_trace_id

RECORD_LEN = 28
_PAGE_HDR = struct.Struct("<IQ")  # record_count, xxhash64 of records


class IndexCorruptError(Exception):
    pass


@dataclass(frozen=True)
class Record:
    max_id: bytes  # 16 bytes
    start: int     # byte offset of the data page
    length: int    # byte length of the data page

    def pack(self) -> bytes:
        mid = pad_trace_id(self.max_id)
        return mid + struct.pack("<QI", self.start, self.length)

    @classmethod
    def unpack(cls, buf: bytes, off: int = 0) -> "Record":
        mid = bytes(buf[off:off + 16])
        start, length = struct.unpack_from("<QI", buf, off + 16)
        return cls(mid, start, length)


class IndexWriter:
    """Accumulates records, emits pages of `page_size` records each,
    checksummed."""

    def __init__(self, records_per_page: int = 1024):
        self.records_per_page = max(1, records_per_page)

    def write(self, records: list[Record]) -> bytes:
        out = bytearray()
        for i in range(0, len(records), self.records_per_page):
            chunk = records[i:i + self.records_per_page]
            body = b"".join(r.pack() for r in chunk)
            out += _PAGE_HDR.pack(len(chunk), xxhash.xxh64_intdigest(body))
            out += body
        return bytes(out)


class IndexReader:
    """Parses the whole index object into columnar numpy arrays and binary
    searches them. Index objects are small (28B per data page) so eager
    parse is the right trade."""

    def __init__(self, data: bytes):
        ids = []
        starts = []
        lengths = []
        off, n = 0, len(data)
        while off < n:
            if off + _PAGE_HDR.size > n:
                raise IndexCorruptError("truncated index page header")
            count, checksum = _PAGE_HDR.unpack_from(data, off)
            off += _PAGE_HDR.size
            body = data[off:off + count * RECORD_LEN]
            if len(body) != count * RECORD_LEN:
                raise IndexCorruptError("truncated index page body")
            if xxhash.xxh64_intdigest(body) != checksum:
                raise IndexCorruptError("index page checksum mismatch")
            arr = np.frombuffer(body, dtype=np.uint8).reshape(count, RECORD_LEN)
            ids.append(arr[:, :16])
            tail = np.ascontiguousarray(arr[:, 16:])
            starts.append(tail[:, :8].copy().view("<u8").reshape(-1))
            lengths.append(tail[:, 8:12].copy().view("<u4").reshape(-1))
            off += count * RECORD_LEN
        if ids:
            self.ids = np.concatenate(ids)          # [N,16] u8
            self.starts = np.concatenate(starts)    # [N] u64
            self.lengths = np.concatenate(lengths)  # [N] u32
        else:
            self.ids = np.zeros((0, 16), dtype=np.uint8)
            self.starts = np.zeros(0, dtype=np.uint64)
            self.lengths = np.zeros(0, dtype=np.uint32)
        # big-endian-comparable packed ids for searchsorted: 16B big-endian
        # bytes compare like two u64 lexicographic keys
        self._hi = self.ids[:, :8].copy().view(">u8").reshape(-1).astype(np.uint64)
        self._lo = self.ids[:, 8:].copy().view(">u8").reshape(-1).astype(np.uint64)

    def __len__(self) -> int:
        return len(self.starts)

    def record(self, i: int) -> Record:
        return Record(bytes(self.ids[i]), int(self.starts[i]), int(self.lengths[i]))

    def find_index(self, obj_id: bytes) -> int | None:
        """Position of the first record whose max_id >= obj_id, i.e. the only
        data page that can contain obj_id."""
        if len(self) == 0:
            return None
        key = pad_trace_id(obj_id)
        hi = int.from_bytes(key[:8], "big")
        lo = int.from_bytes(key[8:], "big")
        # lexicographic (hi, lo) search over sorted max_ids
        i = int(np.searchsorted(self._hi, hi, side="left"))
        while i < len(self) and self._hi[i] == hi and self._lo[i] < lo:
            i += 1
        if i >= len(self):
            return None
        return i

    def find(self, obj_id: bytes) -> Record | None:
        i = self.find_index(obj_id)
        return None if i is None else self.record(i)
