"""Streaming block writer: objects in → pages + index + bloom + meta out.

Role-equivalent to the reference's tempodb/encoding/v2/streaming_block.go:
27-155 — AddObject in ascending id order, pages cut at a target byte size
and compressed, one downsampled index record per page, sharded bloom built
over all ids, meta.json written last as the commit record.
"""

from __future__ import annotations

from tempo_tpu.backend import (
    BlockMeta,
    NAME_DATA,
    NAME_INDEX,
    bloom_name,
)
from tempo_tpu.backend.raw import RawBackend
from .bloom import ShardedBloom
from .compression import compress
from .index import IndexWriter, Record
from .objects import marshal_object
from tempo_tpu.utils.ids import pad_trace_id

DEFAULT_PAGE_SIZE = 1 << 20          # 1 MiB uncompressed, cf. reference index downsample
DEFAULT_RECORDS_PER_INDEX_PAGE = 1024
DEFAULT_BLOOM_FP = 0.01
DEFAULT_BLOOM_SHARD_SIZE = 100 << 10  # reference: 100 KiB shards
DEFAULT_FLUSH_SIZE = 30 << 20         # reference compactor.go:17-26 FlushSizeBytes


class StreamingBlock:
    def __init__(self, meta: BlockMeta,
                 page_size: int = DEFAULT_PAGE_SIZE,
                 records_per_index_page: int = DEFAULT_RECORDS_PER_INDEX_PAGE,
                 bloom_fp: float = DEFAULT_BLOOM_FP,
                 backend: RawBackend | None = None,
                 flush_size: int = DEFAULT_FLUSH_SIZE):
        """With `backend`, buffered compressed pages stream out through
        backend.append every `flush_size` bytes (the reference's 30 MB
        flush through S3-multipart append emulation) so arbitrarily large
        blocks build in bounded memory. Without it, pages accumulate and
        write once at complete() — fine for WAL-sized blocks."""
        self.meta = meta
        self.page_size = page_size
        self.records_per_index_page = records_per_index_page
        self.bloom_fp = bloom_fp
        self.backend = backend
        self.flush_size = flush_size

        self._pages: list[bytes] = []
        self._pages_bytes = 0
        self._tracker = None
        self._appending = False
        self._records: list[Record] = []
        self._cur = bytearray()
        self._cur_max_id = b""
        self._offset = 0
        self._last_id = b""
        self._ids: list[bytes] = []
        # objects already committed to the backend (for abort cleanup:
        # meta.json is written LAST, so anything here without a meta is
        # invisible to the blocklist and retention would never reclaim it)
        self._written: list[str] = []
        self._write_backend: RawBackend | None = None
        self._meta_attempted = False

    def add_object(self, obj_id: bytes, data: bytes,
                   start: int = 0, end: int = 0) -> None:
        # normalize to the 16-byte padded key everywhere (index, bloom,
        # page framing) so short 64-bit ids sort and probe consistently
        obj_id = pad_trace_id(obj_id)
        if self._last_id and obj_id < self._last_id:
            raise ValueError("objects must be added in ascending id order")
        self._last_id = obj_id
        self._ids.append(obj_id)
        self._cur += marshal_object(obj_id, data)
        self._cur_max_id = obj_id
        self.meta.total_objects += 1
        self.meta.extend_range(start, end)
        if len(self._cur) >= self.page_size:
            self._cut_page()

    def _cut_page(self) -> None:
        if not self._cur:
            return
        page = compress(bytes(self._cur), self.meta.encoding)
        self._pages.append(page)
        self._pages_bytes += len(page)
        self._records.append(Record(self._cur_max_id, self._offset, len(page)))
        self._offset += len(page)
        self._cur = bytearray()
        if self.backend is not None and self._pages_bytes >= self.flush_size:
            self._flush_pages()

    def _flush_pages(self) -> None:
        """Stream buffered compressed pages to the backend (append part);
        memory drops back to ~one page."""
        if not self._pages:
            return
        self._tracker = self.backend.append(
            self.meta.tenant_id, self.meta.block_id, NAME_DATA,
            self._tracker, b"".join(self._pages))
        self._appending = True
        self._pages = []
        self._pages_bytes = 0

    def complete(self, backend: RawBackend | None = None) -> BlockMeta:
        """Write data, index, blooms, then meta last (commit point)."""
        backend = backend if backend is not None else self.backend
        self._write_backend = backend
        self._cut_page()
        if self._appending:
            # finish the append stream (data object commits here)
            self._flush_pages()
            backend.close_append(self.meta.tenant_id, self.meta.block_id,
                                 NAME_DATA, self._tracker)
            self._appending = False
            self._written.append(NAME_DATA)
            data = None
        else:
            data = b"".join(self._pages)

        shards = max(1, -(-len(self._ids) * 16 // DEFAULT_BLOOM_SHARD_SIZE))
        bloom = ShardedBloom(
            shard_count=shards,
            fp_rate=self.bloom_fp,
            expected_per_shard=max(1, -(-len(self._ids) // shards)),
        )
        bloom.add_many(self._ids)

        m = self.meta
        m.size = self._offset
        m.total_records = len(self._records)
        m.index_page_size = self.records_per_index_page
        m.bloom_shard_count = bloom.shard_count
        m.bloom_shard_size_bytes = bloom.shard_size_bytes()
        if self._ids:
            m.min_id = self._ids[0].hex()
            m.max_id = self._ids[-1].hex()

        if data is not None:
            backend.write(m.tenant_id, m.block_id, NAME_DATA, data)
            self._written.append(NAME_DATA)
        backend.write(
            m.tenant_id, m.block_id, NAME_INDEX,
            IndexWriter(self.records_per_index_page).write(self._records),
        )
        self._written.append(NAME_INDEX)
        for s in range(bloom.shard_count):
            backend.write(m.tenant_id, m.block_id, bloom_name(s), bloom.marshal_shard(s))
            self._written.append(bloom_name(s))
        self._meta_attempted = True
        backend.write_block_meta(m)
        return m

    def abort(self) -> None:
        """Discard the block under construction: release the in-progress
        backend append (S3 multipart / GCS session / local temp file) AND
        delete any objects complete() already committed. meta.json never
        got written, so those objects are invisible to the blocklist —
        retention would never reclaim them, and callers that mint a fresh
        block id per attempt (compaction, write_block_direct) would leak
        one metaless data object per failed try."""
        if self._appending and self.backend is not None:
            try:
                self.backend.abort_append(self.meta.tenant_id,
                                          self.meta.block_id, NAME_DATA,
                                          self._tracker)
            except Exception:  # noqa: BLE001 — abort is best-effort cleanup
                pass
        be = self._write_backend or self.backend
        if be is not None:
            safe = True
            if self._meta_attempted:
                # an ambiguous meta-write failure (client timeout after the
                # server durably stored meta.json) would otherwise leave a
                # VISIBLE meta pointing at deleted objects — worse than
                # orphaned garbage. Remove the meta first; only if that
                # delete is known-good may the rest be reclaimed.
                from tempo_tpu.backend.raw import DoesNotExist
                from tempo_tpu.backend.types import NAME_META
                try:
                    be.delete(self.meta.tenant_id, self.meta.block_id,
                              NAME_META)
                except DoesNotExist:
                    pass  # meta never committed — the common case
                except Exception:  # noqa: BLE001 — meta state unknown:
                    safe = False   # keep data/index so the block stays whole
            if safe:
                for name in self._written:
                    try:
                        be.delete(self.meta.tenant_id, self.meta.block_id,
                                  name)
                    except Exception:  # noqa: BLE001 — best-effort cleanup
                        pass
                self._written = []
        self._tracker = None
        self._appending = False
        self._pages = []
        self._pages_bytes = 0
        self._cur = bytearray()

    @property
    def current_buffer_size(self) -> int:
        return self._offset + len(self._cur)
