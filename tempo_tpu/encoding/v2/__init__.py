from .compression import compress, decompress, SUPPORTED_ENCODINGS
from .objects import marshal_object, unmarshal_objects, ObjectFramingError
from .index import Record, RECORD_LEN, IndexWriter, IndexReader
from .bloom import ShardedBloom
from .streaming_block import StreamingBlock
from .backend_block import BackendBlock

__all__ = [
    "compress", "decompress", "SUPPORTED_ENCODINGS",
    "marshal_object", "unmarshal_objects", "ObjectFramingError",
    "Record", "RECORD_LEN", "IndexWriter", "IndexReader",
    "ShardedBloom", "StreamingBlock", "BackendBlock",
]
