"""Per-page compression codecs.

Role-equivalent to the reference's tempodb/encoding/v2/pool.go:36-93
(gzip/lz4/snappy/zstd/s2/none via vendored Go asm libs). Here the fast
codecs ride the native C++ runtime (tempo_tpu.ops.native wrapping system
libzstd/liblz4/libsnappy); `zstd` also has a pure-python wheel fallback
(zstandard) and gzip/zlib/none always work, so the format is readable even
without the native build.
"""

from __future__ import annotations

import gzip as _gzip
import zlib as _zlib

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover
    _zstd = None

SUPPORTED_ENCODINGS = ("none", "gzip", "zlib", "zstd", "lz4", "snappy", "s2")

# `s2` (reference pool.go:36-93, klauspost/compress/s2) is an extended
# snappy whose value on the reference is the Go assembly encoder's
# speed; its framing is snappy-compatible in the mode the reference
# uses. This framework's block format is deliberately not byte-
# compatible with the reference's, so `s2` here is config-surface
# parity: it maps onto the native snappy codec, which fills the same
# fast-codec role on this runtime.


def _native():
    from tempo_tpu.ops import native

    return native if native.available() else None


def requires_native(encoding: str) -> bool:
    """True when this codec has no pure-python fallback here — the one
    source of truth for startup validation (a codec that passes config
    load must never fail its first compress call)."""
    if encoding in ("lz4", "snappy", "s2"):
        return True
    if encoding == "zstd":
        return _zstd is None  # zstandard wheel is the fallback
    return False


def encoding_usable(encoding: str) -> bool:
    """Can this codec actually compress in THIS process (native lib or
    pure-python fallback present)?"""
    return not requires_native(encoding) or _native() is not None


def best_available(preferred: str, fallback: str = "zlib") -> str:
    """`preferred` if its codec is usable here, else `fallback` (zlib:
    always available, closest ratio to zstd). The degrade point for
    DEFAULT configs on hosts without the native build or wheels — data
    is always labeled with the codec that actually wrote it."""
    return preferred if encoding_usable(preferred) else fallback


def compress(data: bytes, encoding: str, level: int = 3) -> bytes:
    if encoding == "none":
        return data
    if encoding == "gzip":
        return _gzip.compress(data, compresslevel=min(level + 3, 9))
    if encoding == "zlib":
        return _zlib.compress(data, level + 3)
    if encoding == "zstd":
        n = _native()
        if n is not None:
            return n.zstd_compress(data, level)
        if _zstd is None:
            raise RuntimeError("zstd unavailable: no native lib and no zstandard wheel")
        return _zstd.ZstdCompressor(level=level).compress(data)
    if encoding in ("lz4", "snappy", "s2"):
        n = _native()
        if n is None:
            raise RuntimeError(f"{encoding} requires the native runtime (make -C native)")
        return n.lz4_compress(data) if encoding == "lz4" else n.snappy_compress(data)
    raise ValueError(f"unknown encoding {encoding!r}")


def decompress(data: bytes, encoding: str) -> bytes:
    if encoding == "none":
        return data
    if encoding == "gzip":
        return _gzip.decompress(data)
    if encoding == "zlib":
        return _zlib.decompress(data)
    if encoding == "zstd":
        n = _native()
        if n is not None:
            return n.zstd_decompress(data)
        if _zstd is None:
            raise RuntimeError("zstd unavailable: no native lib and no zstandard wheel")
        return _zstd.ZstdDecompressor().decompress(data)
    if encoding in ("lz4", "snappy", "s2"):
        n = _native()
        if n is None:
            raise RuntimeError(f"{encoding} requires the native runtime (make -C native)")
        return n.lz4_decompress(data) if encoding == "lz4" else n.snappy_decompress(data)
    raise ValueError(f"unknown encoding {encoding!r}")
