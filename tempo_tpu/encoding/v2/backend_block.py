"""Backend block reader: bloom → index → page fetch → object scan.

Role-equivalent to the reference's tempodb/encoding/v2/backend_block.go:
38-231 (FindTraceByID via bloom shard test + index binary search + single
page fetch; Search via linear page iteration with proto-decode matching)
and finder_paged.go / iterator_paged.go.
"""

from __future__ import annotations

from typing import Iterator

from tempo_tpu.backend import BlockMeta, NAME_DATA, NAME_INDEX, bloom_name
from tempo_tpu.backend.raw import RawBackend
from .bloom import ShardedBloom
from .compression import decompress
from .index import IndexReader
from .objects import unmarshal_objects
from tempo_tpu.utils.ids import pad_trace_id


class BackendBlock:
    def __init__(self, backend: RawBackend, meta: BlockMeta):
        self.backend = backend
        self.meta = meta
        self._index: IndexReader | None = None

    # ---- index / pages ----

    def index(self) -> IndexReader:
        if self._index is None:
            self._index = IndexReader(
                self.backend.read(self.meta.tenant_id, self.meta.block_id, NAME_INDEX)
            )
        return self._index

    def read_page(self, record_idx: int) -> bytes:
        idx = self.index()
        raw = self.backend.read_range(
            self.meta.tenant_id, self.meta.block_id, NAME_DATA,
            int(idx.starts[record_idx]), int(idx.lengths[record_idx]),
        )
        return decompress(raw, self.meta.encoding)

    # ---- find ----

    def find_by_id(self, obj_id: bytes) -> bytes | None:
        """Bloom-gated point lookup; returns the stored object bytes or None."""
        key = pad_trace_id(obj_id)
        if self.meta.bloom_shard_count:
            shard = ShardedBloom.shard_for(key, self.meta.bloom_shard_count)
            blob = self.backend.read(self.meta.tenant_id, self.meta.block_id,
                                     bloom_name(shard))
            if not ShardedBloom.test_marshalled(blob, key):
                return None
        idx = self.index()
        i = idx.find_index(key)
        if i is None:
            return None
        page = self.read_page(i)
        for oid, data in unmarshal_objects(page):
            if pad_trace_id(oid) == key:
                return data
            if pad_trace_id(oid) > key:
                return None
        return None

    # ---- iteration (search scan / compaction) ----

    def iter_objects(self, start_page: int = 0, pages: int | None = None
                     ) -> Iterator[tuple[bytes, bytes]]:
        """Yield (id, data) over a page range — the unit of the frontend's
        search job sharding (SearchBlockRequest start_page/pages_to_search)."""
        idx = self.index()
        end = len(idx) if pages is None else min(len(idx), start_page + pages)
        for i in range(start_page, end):
            yield from unmarshal_objects(self.read_page(i))

    def bytes_in_pages(self, start_page: int, pages: int | None = None) -> int:
        idx = self.index()
        end = len(idx) if pages is None else min(len(idx), start_page + pages)
        return int(idx.lengths[start_page:end].sum())
