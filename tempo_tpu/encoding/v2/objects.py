"""Object framing inside data pages and WAL files.

``| u32 id_len | u32 data_len | id | data |`` — fixed little-endian
framing (the reference uses uvarint framing, tempodb/encoding/v2/object.go;
fixed u32s cost a few bytes but make host-side scanning branch-free and
trivially vectorizable, and pages are compressed anyway).
"""

from __future__ import annotations

import struct
from typing import Iterator

_HDR = struct.Struct("<II")
MAX_OBJECT_SIZE = 1 << 30


class ObjectFramingError(ValueError):  # callers catch ValueError (WAL find,
    # strict unmarshal consumers): corruption must land in that contract
    pass


def marshal_object(obj_id: bytes, data: bytes) -> bytes:
    return _HDR.pack(len(obj_id), len(data)) + obj_id + data


def unmarshal_objects(buf: bytes, *, tolerate_truncation: bool = False
                      ) -> Iterator[tuple[bytes, bytes]]:
    """Yield (id, data) pairs. With tolerate_truncation (WAL replay), a
    short tail is treated as end-of-stream — a crashed writer's partial
    record is discarded, matching the reference's replay semantics
    (wal/append_block.go:76-128)."""
    off, n = 0, len(buf)
    while off < n:
        if off + _HDR.size > n:
            if tolerate_truncation:
                return
            raise ObjectFramingError("truncated object header")
        id_len, data_len = _HDR.unpack_from(buf, off)
        if id_len > 128 or data_len > MAX_OBJECT_SIZE:
            if tolerate_truncation:
                return
            raise ObjectFramingError(f"implausible object lens {id_len}/{data_len}")
        end = off + _HDR.size + id_len + data_len
        if end > n:
            if tolerate_truncation:
                return
            raise ObjectFramingError("truncated object body")
        obj_id = buf[off + _HDR.size: off + _HDR.size + id_len]
        data = buf[off + _HDR.size + id_len: end]
        yield obj_id, data
        off = end
