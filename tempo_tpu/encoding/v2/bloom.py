"""Sharded bloom filters for trace-by-ID.

Role-equivalent to the reference's tempodb/encoding/common/bloom.go:20-93
(willf/bloom sharded by fnv32(traceID)): ids are distributed over
`shard_count` shards keyed by fnv1a32(id) % shards so a reader fetches one
small shard object, not the whole filter. Hashing: double hashing with two
xxhash64 seeds — h_i = h1 + i*h2 — the standard Kirsch-Mitzenmacher scheme.
Bit arrays are numpy uint64 words; batch add/test is vectorized.
"""

from __future__ import annotations

import math
import struct

import numpy as np
import xxhash

from tempo_tpu.utils.hashing import fnv1a_32

_HDR = struct.Struct("<IIQ")  # k hashes, reserved, m bits
_SEED2 = 0x9E3779B97F4A7C15


def _probe_positions(obj_id: bytes, k: int, m: int) -> np.ndarray:
    """Kirsch-Mitzenmacher double hashing: h_i = h1 + i*h2 mod m. The ONE
    definition shared by in-memory filters and marshalled-shard tests — a
    divergence here silently produces bloom false negatives."""
    h1 = xxhash.xxh64_intdigest(obj_id, seed=0)
    h2 = xxhash.xxh64_intdigest(obj_id, seed=_SEED2) | 1
    i = np.arange(k, dtype=np.uint64)
    return (np.uint64(h1) + i * np.uint64(h2)) % np.uint64(m)


def _probe_words(bits: np.ndarray, pos: np.ndarray) -> bool:
    words = bits[(pos // 64).astype(np.int64)]
    return bool(np.all(words & (np.uint64(1) << (pos % np.uint64(64)))))


class ShardedBloom:
    def __init__(self, shard_count: int, fp_rate: float = 0.01,
                 expected_per_shard: int = 1000):
        self.shard_count = max(1, shard_count)
        self.fp = fp_rate
        n = max(1, expected_per_shard)
        m = max(64, int(-n * math.log(fp_rate) / (math.log(2) ** 2)))
        m = (m + 63) // 64 * 64
        k = max(1, round(m / n * math.log(2)))
        self.m = m
        self.k = k
        self._bits = [np.zeros(m // 64, dtype=np.uint64) for _ in range(self.shard_count)]

    @staticmethod
    def shard_for(obj_id: bytes, shard_count: int) -> int:
        return fnv1a_32(obj_id) % max(1, shard_count)

    def add(self, obj_id: bytes) -> None:
        s = self.shard_for(obj_id, self.shard_count)
        pos = _probe_positions(obj_id, self.k, self.m)
        np.bitwise_or.at(self._bits[s], (pos // 64).astype(np.int64),
                         np.uint64(1) << (pos % np.uint64(64)))

    def add_many(self, obj_ids) -> None:
        """Vectorized bulk insert: the per-id cost collapses to the two
        xxhash C calls; probe positions and bit-ORs batch per shard. The
        block writer inserts every id at complete() time, so this is the
        completion/compaction hot loop, not `add` (probe math identical
        to _probe_positions — the KM scheme shared with readers)."""
        ids = list(obj_ids)
        if not ids:
            return
        n = len(ids)
        h1 = np.fromiter((xxhash.xxh64_intdigest(o, seed=0) for o in ids),
                         dtype=np.uint64, count=n)
        h2 = np.fromiter((xxhash.xxh64_intdigest(o, seed=_SEED2)
                          for o in ids), dtype=np.uint64, count=n) | np.uint64(1)
        i = np.arange(self.k, dtype=np.uint64)
        pos = (h1[:, None] + i[None, :] * h2[:, None]) % np.uint64(self.m)
        shards = np.fromiter((fnv1a_32(o) for o in ids),
                             dtype=np.int64, count=n) % self.shard_count
        for s in np.unique(shards):
            p = pos[shards == s].ravel()
            np.bitwise_or.at(self._bits[int(s)], (p // 64).astype(np.int64),
                             np.uint64(1) << (p % np.uint64(64)))

    def test(self, obj_id: bytes) -> bool:
        s = self.shard_for(obj_id, self.shard_count)
        return _probe_words(self._bits[s],
                            _probe_positions(obj_id, self.k, self.m))

    # ---- serialization: one object per shard ----

    def marshal_shard(self, shard: int) -> bytes:
        return _HDR.pack(self.k, 0, self.m) + self._bits[shard].tobytes()

    @classmethod
    def test_marshalled(cls, data: bytes, obj_id: bytes) -> bool:
        k, _, m = _HDR.unpack_from(data)
        bits = np.frombuffer(data, dtype=np.uint64, offset=_HDR.size)
        if len(bits) != m // 64:
            raise ValueError("bloom shard truncated")
        return _probe_words(bits, _probe_positions(obj_id, k, m))

    def shard_size_bytes(self) -> int:
        return _HDR.size + self.m // 8
