"""Immutable block encodings.

One versioned encoding today: vT1 (tempo_tpu.encoding.v2) — pages of
length-framed objects with per-page compression, a binary-searchable
downsampled index of 28-byte records, and sharded bloom filters; the same
page machinery also carries the columnar search data (tempo_tpu.search).

Role-equivalent to the reference's tempodb/encoding (VersionedEncoding,
versioned.go:15-27).
"""

from tempo_tpu.encoding.v2.streaming_block import StreamingBlock
from tempo_tpu.encoding.v2.backend_block import BackendBlock

SUPPORTED_VERSIONS = ("vT1",)

__all__ = ["StreamingBlock", "BackendBlock", "SUPPORTED_VERSIONS"]
