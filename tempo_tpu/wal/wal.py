"""Write-ahead log: append-only per-block files with crash replay.

Role-equivalent to the reference's tempodb/wal (wal.go:54-219,
append_block.go:25-269): every accepted trace segment is appended to the
head block's file before being acknowledged; on restart the file is
re-scanned (tolerating a truncated tail from a crashed writer), corrupt or
zero-length files are removed, and the in-memory appender state (records,
time range) is rebuilt. Filenames encode everything needed to replay:
``<block_id>+<tenant>+<version>+<encoding>+<data_encoding>``.

Record payloads are COMPRESSED per segment (the reference WAL writes
snappy v2 pages, wal.go:54-97 — at ingest volume the WAL is a real
disk-bandwidth term). The codec rides in the filename's encoding field,
so replay is self-describing and an upgrade replays old uncompressed
("none") files unchanged. Default "auto": native snappy when the C++
runtime is built, zlib otherwise — the ack path never depends on an
optional build.
"""

from __future__ import annotations

import os
import urllib.parse
from dataclasses import dataclass

from tempo_tpu.backend.types import BlockMeta, VERSION_VT1
from tempo_tpu.encoding.v2.compression import compress, decompress
from tempo_tpu.encoding.v2.objects import marshal_object, unmarshal_objects
from tempo_tpu.model.codec import segment_codec_for, CURRENT_ENCODING
from tempo_tpu.utils.ids import pad_trace_id

_SEP = "+"


def resolve_wal_encoding(encoding: str = "auto") -> str:
    """Validated at WAL CONSTRUCTION: a typo'd codec, or one whose
    native library isn't built, must fail startup — not the first
    append, after the process already reported ready."""
    from tempo_tpu.encoding.v2.compression import (
        SUPPORTED_ENCODINGS, requires_native,
    )
    from tempo_tpu.ops import native

    if encoding == "auto":
        return "snappy" if native.available() else "zlib"
    if encoding not in SUPPORTED_ENCODINGS:
        raise ValueError(f"wal_encoding {encoding!r}: supported are "
                         f"auto, {', '.join(SUPPORTED_ENCODINGS)}")
    if requires_native(encoding) and not native.available():
        raise ValueError(f"wal_encoding {encoding!r} requires the native "
                         "runtime (make -C native)")
    return encoding


def wal_filename(meta: BlockMeta) -> str:
    # tenant ids are arbitrary strings — percent-encode so the separator
    # (and '/', NUL, etc.) can never corrupt the filename round-trip
    tenant = urllib.parse.quote(meta.tenant_id, safe="")
    return _SEP.join([
        meta.block_id, tenant, meta.version, meta.encoding or "none",
        meta.data_encoding,
    ])


def parse_wal_filename(name: str) -> BlockMeta:
    parts = name.split(_SEP)
    if len(parts) != 5:
        raise ValueError(f"unparseable wal filename {name!r}")
    block_id, tenant, version, encoding, data_encoding = parts
    if not block_id or not tenant:
        raise ValueError(f"unparseable wal filename {name!r}")
    return BlockMeta(
        version=version, block_id=block_id,
        tenant_id=urllib.parse.unquote(tenant),
        encoding=encoding, data_encoding=data_encoding,
    )


@dataclass
class _Entry:
    obj_id: bytes
    offset: int
    length: int


class AppendBlock:
    """One head block's WAL file + in-memory appender records."""

    def __init__(self, wal_dir: str, meta: BlockMeta, _replay: bool = False):
        self.meta = meta
        self.path = os.path.join(wal_dir, wal_filename(meta))
        self._entries: list[_Entry] = []
        self._by_id: dict[bytes, list[int]] = {}
        self._codec = segment_codec_for(meta.data_encoding)
        self._enc = meta.encoding or "none"
        self.corrupt_records = 0  # dropped at replay (decompress failures)
        if _replay:
            self._fh = None
            self._replay_file()
            self._fh = open(self.path, "ab")
        else:
            self._fh = open(self.path, "wb")
        self._rfh = open(self.path, "rb")
        self._offset = os.path.getsize(self.path)
        self._closed = False

    # ---- write path ----

    def append(self, obj_id: bytes, segment: bytes,
               start: int = 0, end: int = 0) -> None:
        # normalize to the padded 16-byte key so WAL iteration order matches
        # block index order (StreamingBlock pads the same way)
        obj_id = pad_trace_id(obj_id)
        if self._enc != "none":
            segment = compress(segment, self._enc)
        rec = marshal_object(obj_id, segment)
        self._fh.write(rec)
        self._fh.flush()
        e = _Entry(obj_id, self._offset, len(rec))
        self._offset += len(rec)
        self._by_id.setdefault(obj_id, []).append(len(self._entries))
        self._entries.append(e)
        self.meta.extend_range(start, end)
        self.meta.total_objects += 1

    @property
    def data_length(self) -> int:
        return self._offset

    def __len__(self) -> int:
        return len(self._entries)

    # ---- read path ----

    def _read_entry(self, e: _Entry) -> bytes:
        self._rfh.seek(e.offset)
        buf = self._rfh.read(e.length)
        for _, data in unmarshal_objects(buf):
            if self._enc == "none":
                return data
            try:
                return decompress(data, self._enc)
            except Exception as exc:  # noqa: BLE001 — post-replay rot
                # normalize codec errors (zlib.error, native RuntimeError)
                # to the ValueError find() already treats as on-disk
                # corruption — surfaced, and swallowed only during a
                # racing clear()
                raise ValueError(f"corrupt wal entry: {exc}") from exc
        raise ValueError("corrupt wal entry")

    def find(self, obj_id: bytes) -> bytes | None:
        """Combined object bytes for an id, or None. Tolerates a
        concurrent clear(): completing blocks stay queryable while their
        completion streams to the backend, so a reader may hold this block
        right as the successful hand-off closes the file — by then the
        trace is served from the completed block (`recent`), and the
        correct answer HERE is 'not found', not a crash."""
        idxs = self._by_id.get(pad_trace_id(obj_id))
        if not idxs:
            return None
        try:
            segs = [self._read_entry(self._entries[i]) for i in idxs]
        except (AttributeError, ValueError, OSError):
            if self._closed:
                return None  # cleared/closed underneath us
            raise  # genuine on-disk corruption must surface, not 404
        return self._codec.to_object(segs)

    def iterator(self):
        """Yield (id, combined object bytes) in ascending id order — the
        dedupe/combine iterator feeding block completion (reference
        append_block.go Iterator + dedupe)."""
        for obj_id in sorted(self._by_id):
            yield obj_id, self.find(obj_id)

    # ---- lifecycle ----

    def close(self) -> None:
        # flag FIRST: a racing find() that hits the closing file must see
        # _closed and answer None rather than re-raise (see find())
        self._closed = True
        if self._fh:
            self._fh.close()
            self._fh = None
        if getattr(self, "_rfh", None):
            self._rfh.close()
            self._rfh = None

    def clear(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    # ---- replay ----

    def _replay_file(self) -> None:
        with open(self.path, "rb") as f:
            buf = f.read()
        off = 0
        for obj_id, data in unmarshal_objects(buf, tolerate_truncation=True):
            length = 8 + len(obj_id) + len(data)
            off += length
            if self._enc != "none":
                try:
                    data = decompress(data, self._enc)
                except Exception:  # noqa: BLE001 — corrupt record
                    # DROP it, like the reference drops corrupt WAL data
                    # at replay (wal.go:119-143): indexing it would make
                    # every later find() raise and wedge block completion
                    # in an infinite retry. Framing is per-record, so
                    # subsequent intact records still replay.
                    self.corrupt_records += 1
                    continue
            e = _Entry(obj_id, off - length, length)
            self._by_id.setdefault(obj_id, []).append(len(self._entries))
            self._entries.append(e)
            r = self._codec.fast_range(data) if len(data) >= 8 else None
            if r:
                self.meta.extend_range(r[0], r[1])
            self.meta.total_objects += 1
        if self.corrupt_records:
            from tempo_tpu.observability import get_logger

            get_logger().warning(
                "wal replay %s: dropped %d corrupt record(s)",
                os.path.basename(self.path), self.corrupt_records)
        # truncate any torn tail so future appends start clean
        if off < len(buf):
            with open(self.path, "ab") as f:
                f.truncate(off)


class WAL:
    def __init__(self, wal_dir: str, encoding: str = "auto"):
        self.dir = wal_dir
        self.encoding = resolve_wal_encoding(encoding)
        # stats of the most recent replay_all() on this WAL — a slow
        # restart must be attributable (how many bytes re-scanned, how
        # long), not a silent startup stall
        self.last_replay: dict | None = None
        os.makedirs(wal_dir, exist_ok=True)

    def new_block(self, tenant: str, block_id: str | None = None,
                  data_encoding: str = CURRENT_ENCODING) -> AppendBlock:
        meta = BlockMeta(version=VERSION_VT1, tenant_id=tenant,
                         data_encoding=data_encoding, encoding=self.encoding)
        if block_id:
            meta.block_id = block_id
        return AppendBlock(self.dir, meta)

    def replay_all(self) -> tuple[list[AppendBlock], list[str]]:
        """Rescan the WAL dir. Returns (replayed blocks, removed files).
        Zero-length and unparseable files are removed, torn tails truncated
        (reference wal.go:119-143 corrupt-file removal)."""
        import time

        t0 = time.perf_counter()
        blocks: list[AppendBlock] = []
        removed: list[str] = []
        sidecars: list[str] = []
        for name in sorted(os.listdir(self.dir)):
            path = os.path.join(self.dir, name)
            if not os.path.isfile(path):
                continue
            if name.endswith(".search"):
                # search-WAL sidecars replay with their paired trace block
                # (ingester pairs them by path), never on their own
                sidecars.append(name)
                continue
            try:
                meta = parse_wal_filename(name)
            except ValueError:
                os.unlink(path)
                removed.append(name)
                continue
            if os.path.getsize(path) == 0:
                os.unlink(path)
                removed.append(name)
                continue
            blocks.append(AppendBlock(self.dir, meta, _replay=True))
        # sidecars whose paired trace WAL is gone would otherwise leak forever
        kept = {os.path.basename(b.path) for b in blocks}
        for name in sidecars:
            if name[: -len(".search")] not in kept:
                os.unlink(os.path.join(self.dir, name))
                removed.append(name)
        self.last_replay = {
            "duration_s": time.perf_counter() - t0,
            "blocks": len(blocks),
            "bytes": sum(b.data_length for b in blocks),
            "corrupt_records": sum(b.corrupt_records for b in blocks),
            "removed_files": len(removed),
        }
        return blocks, removed
