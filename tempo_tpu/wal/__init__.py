from .wal import WAL, AppendBlock, parse_wal_filename

__all__ = ["WAL", "AppendBlock", "parse_wal_filename"]
