"""Query frontend: shard, dispatch, retry, merge.

Role-equivalent to the reference's modules/frontend (frontend.go,
tracebyidsharding.go:30-48, searchsharding.go:163-407, retry.go):
trace-by-ID requests shard into block-id-range sub-queries plus an
ingester query; search requests shard into per-block SearchBlockRequest
jobs plus one recent/ingester request; sub-requests run with bounded
concurrency, retry on failure, and merge (trace combine / result dedupe +
metrics sum).

Every sub-request routes through the per-tenant fair RequestQueue drained
by a bounded worker pool (QueueWorkerPool): tenants are served
round-robin, and a tenant with more than max_outstanding_per_tenant
queued sub-requests gets the whole request rejected with TooManyRequests
(HTTP 429) — the reference's frontend-v1 queue semantics
(v1/frontend.go:33-60) collapsed in-process.
"""

from __future__ import annotations

import time as _time
import uuid
from dataclasses import dataclass

from tempo_tpu import tempopb
from tempo_tpu.model.codec import codec_for, CURRENT_ENCODING
from tempo_tpu.model.combine import combine_trace_protos
from tempo_tpu.observability import tracing
from tempo_tpu.search import SearchResults
from tempo_tpu.search.ownership import HEDGE, OWNERSHIP

from .queue import QueueWorkerPool

# per-attempt deadline a hedged attempt runs under when the REQUEST has
# no deadline of its own: without one, expiring the losing attempt
# (d.t_end = 0) would have nothing to expire and the loser would hold
# its worker slot forever against a wedged querier
_HEDGE_CANCEL_CAP_S = 600.0


@dataclass
class FrontendConfig:
    query_shards: int = 20           # reference default, 2-256
    max_concurrent_jobs: int = 50    # reference: bounded fan-out 50
    retries: int = 2                 # reference retry ware
    tolerate_failed_blocks: int = 0
    # per-tenant cap on concurrently-outstanding REQUESTS — deliberately
    # NOT the reference's sub-request-counting semantics (its 2000,
    # v1/frontend.go:46-48, bounds queued items); whole requests need a
    # far lower cap to mean anything as admission control
    max_outstanding_per_tenant: int = 64
    # complementary memory bound on QUEUED sub-requests per tenant
    max_queued_per_tenant: int = 100_000
    # page-range job sizing (reference searchsharding.go:26-27
    # target_bytes_per_job default 10 MiB): a block whose search container
    # exceeds this splits into multiple page-range jobs
    target_bytes_per_job: int = 10 << 20
    # TPU-native batching: jobs per SearchBlocksRequest. None (default)
    # auto-sizes to one batched request per querier — on TPU the whole
    # request should cost ~one kernel dispatch + one device sync, not 40
    # (a fixed small batch re-imposes the CPU fan-out the batcher exists
    # to invert); a per-request count still caps it for CPU-style
    # deployments with many worker processes behind few querier stubs
    batch_jobs_per_request: int | None = None
    # querier shuffle-sharding on the pull dispatcher (reference
    # queue.go querier awareness): cap how many worker streams one
    # tenant's jobs spread over. 0 = off
    max_queriers_per_tenant: int = 0


def _metrics_remainder(m, parts: list[dict]) -> "tempopb.SearchMetrics":
    """The share of merged SearchMetrics NOT covered by the explain
    breakdowns — sub-responses from the ingester live leg or a
    stats-disabled querier carry plain metrics only, and the frontend's
    merged record must account them too (clamped at zero: float sums
    and partial fields never go negative)."""
    part_blocks = sum(int(p.get("blocks_inspected", 0)) for p in parts)
    part_dev_b = sum(int((p.get("bytes_inspected") or {}).get("device", 0))
                     for p in parts)
    part_host_b = sum(int((p.get("bytes_inspected") or {}).get("host", 0))
                      for p in parts)
    part_dev_s = sum(float(p.get("device_seconds", 0.0)) for p in parts)
    part_skip = sum(sum((p.get("skipped_blocks") or {}).values())
                    for p in parts)
    rem = tempopb.SearchMetrics()
    rem.inspected_blocks = max(0, m.inspected_blocks - part_blocks)
    rem.inspected_bytes_device = max(
        0, m.inspected_bytes_device - part_dev_b)
    rem.inspected_bytes = max(
        0, m.inspected_bytes - part_dev_b - part_host_b)
    rem.device_seconds = max(0.0, m.device_seconds - part_dev_s)
    rem.skipped_blocks = max(0, m.skipped_blocks - part_skip)
    return rem


def create_block_boundaries(shards: int) -> list[str]:
    """Split the 128-bit block-id (uuid) space into `shards` ranges
    (reference tracebyidsharding.go createBlockBoundaries)."""
    bounds = []
    step = (1 << 128) // max(1, shards)
    for i in range(shards + 1):
        v = min(i * step, (1 << 128) - 1)
        bounds.append(str(uuid.UUID(int=v)))
    bounds[-1] = "ffffffff-ffff-ffff-ffff-ffffffffffff"
    return bounds


class QueryFrontend:
    def __init__(self, queriers: list, cfg: FrontendConfig | None = None,
                 db=None):
        """queriers: round-robin pool of Querier-interface objects
        (in-process Queriers or gRPC QuerierClients). db: the reader
        TempoDB supplying block metas for search job sharding — the
        frontend reads the blocklist itself (reference: frontend depends
        on tempodb Reader for BlockMetas, SURVEY.md §2.2). Defaults to
        queriers[0].db for in-process single-binary wiring."""
        self.queriers = queriers
        self.cfg = cfg or FrontendConfig()
        self.db = db if db is not None else getattr(queriers[0], "db", None)
        self._rr = 0
        from tempo_tpu.utils.lru import BoundedCache
        # one live entry per (tenant, epoch, pool size); a handful of
        # tenants' worth of 10K-job templates is the working set
        self._batches_cache = BoundedCache(8)
        self.pool = QueueWorkerPool(
            workers=self.cfg.max_concurrent_jobs,
            max_outstanding_per_tenant=self.cfg.max_outstanding_per_tenant,
            max_queued_per_tenant=self.cfg.max_queued_per_tenant)

    def _querier(self):
        q = self.queriers[self._rr % len(self.queriers)]
        self._rr += 1
        return q

    def _owner_querier(self, owner: int | None, attempt: int,
                       width: int | None = None,
                       replicas: tuple[int, ...] = ()):
        """Owner-routed dispatch (docs/search-hbm-ownership.md): the
        FIRST attempt of a block batch goes to its placement group's
        owner — the one process holding the group HBM-resident, where
        concurrent tenants' dashboards coalesce into fused dispatches.
        Retries prefer the group's SURVIVING REPLICAS (heat-promoted
        groups carry ``replicas``, member indices primary-first — a
        replica holds the group device-resident, so the retry stays on
        the fast path) before falling back to the round-robin pool,
        where any non-owner answers through the byte-identical host
        route instead of failing the query.

        ``width`` is the PLAN-TIME pool width the batch's owner index
        was computed against (it rides the memoized batch plan, which
        is keyed on the ownership generation): indexing the live pool
        with ``owner % len(queriers)`` silently remapped EVERY owner
        whenever the pool resized mid-flight. A grown pool keeps the
        plan-time mapping; an index past the live pool (a shrink)
        degrades to round-robin instead of landing on an arbitrary
        wrong owner."""
        if owner is None or not self.queriers:
            return self._querier()
        n = len(self.queriers)
        w = width or n
        if 0 < attempt < len(replicas):
            idx = replicas[attempt] % w
            if idx < n:
                return self.queriers[idx]
            return self._querier()
        if attempt == 0:
            idx = owner % w
            if idx < n:
                return self.queriers[idx]
        return self._querier()

    def _retrying(self, fn, job):
        from tempo_tpu.robustness import DeadlineExceeded, deadline

        last = None
        for _ in range(self.cfg.retries + 1):
            try:
                return fn(job)
            except DeadlineExceeded:
                raise  # the budget is gone; a retry cannot help
            except Exception as e:  # noqa: BLE001 — retried, then surfaced
                last = e
                if deadline.expired():
                    break  # don't burn retries against a dead deadline
        raise last

    def _dispatch_batch(self, breq, owner: int | None,
                        width: int | None, anchor: str, job=None):
        """Send one batched SearchBlocksRequest with owner routing,
        replica-preferring retries, and — for a heat-PROMOTED group —
        hedged dispatch: the first attempt races the primary against
        its next replica after the hedge delay, first answer wins.
        Un-promoted groups (``replica_indices`` returns empty, one
        attribute read when replication is off) keep the exact rf=1
        dispatch: attempt 0 to the owner, retries round-robin."""
        replicas: tuple[int, ...] = ()
        if OWNERSHIP.enabled:
            replicas = OWNERSHIP.replica_indices(anchor)
        attempts = [0]

        def _send(_j):
            a = attempts[0]
            attempts[0] += 1
            q = self._owner_querier(owner, a, width, replicas)
            if a == 0 and len(replicas) > 1 and HEDGE.armed:
                hq = self._owner_querier(owner, 1, width, replicas)
                if hq is not q:
                    return self._hedged_send(breq, q, hq)
            if HEDGE.armed:
                # un-hedged walls feed the hedge-delay estimator too —
                # they are exactly the "healthy answer" distribution
                # the p99 bound is derived from
                t0 = _time.monotonic()
                r = q.search_blocks(breq)
                HEDGE.observe(_time.monotonic() - t0)
                return r
            return q.search_blocks(breq)

        return self._retrying(_send, job)

    def _hedged_send(self, breq, primary, hedge):
        """Race ``primary`` against ``hedge`` for one batch: dispatch
        to the primary, wait out the hedge delay, fire the identical
        request at the replica if the primary hasn't answered, return
        the FIRST success and cancel the loser by force-expiring its
        per-attempt deadline (the batcher checks the deadline between
        groups, so the loser stops at the next group boundary instead
        of burning device time on an answer nobody wants).

        Both attempts run on daemon threads under
        ``contextvars.copy_context()`` — the tenant/query-stats
        ``fronted()`` mark and the caller's deadline must reach the
        in-process querier exactly as an un-hedged call's would, and
        the per-attempt ``deadline.start`` override scopes to the copy.
        A primary FAILURE inside the hedge delay raises immediately so
        ``_retrying`` moves straight to the surviving replica."""
        import contextvars
        import queue as _qmod
        import threading

        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.robustness import DeadlineExceeded, deadline as _dl

        delay = _HEDGE_CANCEL_CAP_S
        if HEDGE.armed:
            delay = HEDGE.delay_s()
        budget = _dl.remaining()
        cap = budget if budget is not None else _HEDGE_CANCEL_CAP_S
        results: "_qmod.Queue" = _qmod.Queue()
        dls: dict = {}

        def _attempt(q, tag):
            try:
                with _dl.start(cap) as d:
                    dls[tag] = d
                    t0 = _time.monotonic()
                    r = q.search_blocks(breq)
                    results.put((tag, True, r, _time.monotonic() - t0))
            except BaseException as e:  # noqa: BLE001 — raced, loser surfaced
                results.put((tag, False, e, 0.0))

        def _launch(q, tag):
            ctx = contextvars.copy_context()
            threading.Thread(target=ctx.run, args=(_attempt, q, tag),
                             name="hedge-%s" % tag, daemon=True).start()

        def _win(tag, val, wall, pending):
            obs.hedged_dispatches.inc(
                result="primary" if tag == "primary" else "hedge_won")
            if HEDGE.armed:
                HEDGE.observe(wall)
            for loser in pending:
                d = dls.get(loser)
                if d is not None:
                    # force-expire the loser's per-attempt deadline:
                    # deadline.expired() answers True from here on, so
                    # the in-flight attempt stops at its next check
                    d.t_end = 0.0
                obs.hedged_dispatches.inc(result="cancelled")
            return val

        _launch(primary, "primary")
        try:
            tag, ok, val, wall = results.get(timeout=delay)
        except _qmod.Empty:
            tag = None
        if tag is not None:
            if ok:
                return _win(tag, val, wall, ())
            raise val  # fast primary failure: retry goes to the replica
        _launch(hedge, "hedge")
        pending = {"primary", "hedge"}
        failures = []
        while pending:
            rem = _dl.remaining()
            try:
                tag, ok, val, wall = results.get(
                    timeout=_HEDGE_CANCEL_CAP_S if rem is None
                    else max(0.0, rem))
            except _qmod.Empty:
                raise DeadlineExceeded(
                    "hedged dispatch exhausted the request deadline")
            pending.discard(tag)
            if ok:
                return _win(tag, val, wall, pending)
            failures.append(val)
        raise failures[0]

    # ---- trace by id (reference frontend.go:91-176) ----

    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> tempopb.TraceByIDResponse:
        with tracing.start_span("frontend.TraceByID", kind=tracing.KIND_SERVER,
                                tenant=tenant) as span:
            resp = self._find_trace_by_id(tenant, trace_id)
            span.set_attributes(failed_blocks=resp.metrics.failed_blocks,
                                found=bool(len(resp.trace.batches)))
            return resp

    def _find_trace_by_id(self, tenant: str, trace_id: bytes) -> tempopb.TraceByIDResponse:
        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.robustness import DeadlineExceeded, deadline

        bounds = create_block_boundaries(self.cfg.query_shards - 1)
        jobs = [("ingesters", "", "")] + [
            ("blocks", bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)
        ]

        def run(job):
            if deadline.expired():
                # budget spent: fail the remaining shard lookups fast —
                # they count failed below, so the 206/failed_blocks
                # contract tells the client how much went unsearched
                raise DeadlineExceeded("request deadline expired before "
                                       "trace-by-id sub-query")
            mode, start, end = job
            return self._retrying(
                lambda j: self._querier().find_trace_by_id(
                    tenant, trace_id, block_start=j[1], block_end=j[2], mode=j[0]
                ),
                job,
            )

        responses, errors = self.pool.run_jobs(tenant, jobs, run)
        # deadline-expired shards are degraded-by-design, never a request
        # failure: they count failed (the client sees a partial lookup)
        # but must not trip the tolerance raise
        dl_errors = [e for e in errors if isinstance(e, DeadlineExceeded)]
        errors = [e for e in errors if not isinstance(e, DeadlineExceeded)]
        if dl_errors:
            obs.partial_results.inc(len(dl_errors), reason="deadline")
        failed = (sum(r.metrics.failed_blocks for r in responses)
                  + len(errors) + len(dl_errors))
        if errors and failed > self.cfg.tolerate_failed_blocks:
            raise errors[0]

        out = tempopb.TraceByIDResponse()
        out.metrics.failed_blocks = failed
        partials = [r.trace for r in responses if len(r.trace.batches)]
        if partials:
            out.trace.CopyFrom(combine_trace_protos(partials))
        return out

    # ---- search (reference searchsharding.go:163-306) ----

    def search(self, tenant: str, req: tempopb.SearchRequest,
               on_progress=None) -> tempopb.SearchResponse:
        """Shard + dispatch one search. Concurrent search() calls are the
        query-coalescer's feedstock: every batched sub-request runs on a
        shared worker-pool thread (never serialized per tenant beyond
        queue fairness), so two dashboards firing together reach the
        querier's BlockBatcher concurrently and their same-batch
        dispatches fuse into one multi-query kernel launch. The frontend
        deliberately keeps sub-request ORDER deterministic (plan-cached
        batches, stable group sort) — peers that iterate groups in the
        same order meet in every coalescing window instead of just the
        first."""
        with tracing.start_span("frontend.Search", kind=tracing.KIND_SERVER,
                                tenant=tenant) as span:
            resp, n_batches = self._search(tenant, req,
                                           on_progress=on_progress)
            span.set_attributes(
                inspected_blocks=resp.metrics.inspected_blocks,
                inspected_traces=resp.metrics.inspected_traces,
                results=len(resp.traces),
                block_batches=n_batches)
            return resp

    def _block_jobs(self, metas) -> list[tuple]:
        """Page-range jobs per block (reference searchsharding.go:323-367
        backendRequests): pages_per_job from target_bytes_per_job and the
        block's recorded container geometry; blocks without geometry info
        (old metas, search-less blocks) become one whole-block job.

        Jobs order by (page geometry, block id) so the fixed-size batch
        slicing downstream yields geometry-PURE SearchBlocksRequests:
        the querier's batcher can only stack same-(E, C) pages into one
        kernel, so a mixed batch fragments into several dispatches."""
        jobs = []
        geo = lambda m: (m.search_entries_per_page, m.search_kv_per_entry)  # noqa: E731
        for m in sorted(metas, key=lambda m: (geo(m), m.block_id)):
            if m.search_pages and m.search_size:
                per_page = max(1, m.search_size // m.search_pages)
                pages_per_job = max(1, self.cfg.target_bytes_per_job // per_page)
                for sp in range(0, m.search_pages, pages_per_job):
                    jobs.append((m, sp, min(pages_per_job,
                                            m.search_pages - sp)))
            else:
                jobs.append((m, 0, 0))  # 0 = all pages / fallback scan
        return jobs

    def _search_batches(self, tenant: str) -> list[tuple]:
        """Page-range jobs grouped into batched requests — each querier
        stacks its share into few kernel dispatches; batches break at
        geometry (and, under ownership, owner) boundaries so every
        batch is geometry-pure and owner-pure. Returns
        [(payload, breq_template, owner, width)] where payload is the
        [(meta, start, n_pages)] job list (failure accounting),
        breq_template a read-only SearchBlocksRequest with the jobs
        pre-built, owner the batch's member index for owner routing
        (None = no preference), and width the querier-pool width the
        owner index was computed against (_owner_querier keys its
        member->querier mapping on it so a pool resize mid-flight
        cannot silently remap every owner). Memoized per (tenant,
        blocklist epoch, ownership generation): re-sorting a 10K-block
        meta list and rebuilding its job list is O(blocks) host work
        per query otherwise (VERDICT r3 #1).

        Deliberately NOT filtered by the request's time window (the
        reference sharder excludes out-of-range metas,
        searchsharding.go:309-321): a now-relative dashboard window
        changes every query, so a window-keyed memo would never hit and
        each miss would pin a fresh 10K-job template set. Window pruning
        happens in the batcher's memoized header prune instead — the
        same contract the direct TempoDB.search path uses; an
        out-of-window block costs a cached skip, not a scan."""
        db = self.db
        # width: stable querier-process count, NOT the live stream count
        # a pull pool reports via len() — that flaps per connect and
        # would churn this cache through every rollout
        width = (self.queriers.stable_len()
                 if hasattr(self.queriers, "stable_len")
                 else len(self.queriers))
        # the ownership generation keys the memo when owner routing is
        # on: a rebalance regroups the batches, and serving a stale
        # template would route groups to their PREVIOUS owner
        own_gen = OWNERSHIP.generation if OWNERSHIP.enabled else -1
        key = (tenant, db.blocklist.epoch(), width, own_gen)
        hit = self._batches_cache.get(key)
        if hit is not None:
            return hit
        metas = list(db.blocklist.metas(tenant))
        block_jobs = self._block_jobs(metas)
        owner_of: dict = {}
        if OWNERSHIP.enabled:
            # owner-routed sharding (docs/search-hbm-ownership.md):
            # jobs regroup by placement-group owner so every batched
            # request lands WHOLE on one owner — the process already
            # holding those blocks device-resident. The stable sort
            # keeps the geometry order within each owner, so batches
            # stay geometry-pure exactly as before.
            for j in block_jobs:
                bid = j[0].block_id
                if bid not in owner_of:
                    owner_of[bid] = OWNERSHIP.owner_index(bid)
            block_jobs = sorted(
                block_jobs,
                key=lambda j: (-1 if owner_of[j[0].block_id] is None
                               else owner_of[j[0].block_id]))
        # auto: spread the whole job list over the querier pool — each
        # querier's share scans in ~one batched dispatch
        B = self.cfg.batch_jobs_per_request or max(
            1, -(-len(block_jobs) // max(1, width)))
        batches = []
        run_start = 0
        for i in range(1, len(block_jobs) + 1):
            # batches break at geometry AND owner boundaries: a mixed
            # batch would fragment into several dispatches (geometry) or
            # split one request across owners (routing)
            sig = lambda j: (owner_of.get(j[0].block_id),   # noqa: E731
                             j[0].search_entries_per_page,
                             j[0].search_kv_per_entry)
            if i == len(block_jobs) or sig(block_jobs[i]) != sig(block_jobs[run_start]):
                run = block_jobs[run_start:i]
                batches.extend(run[k:k + B] for k in range(0, len(run), B))
                run_start = i
        # pre-build each batch's job-list proto once: the python loop
        # over (at 10K blocks) 10K jobs costs ~15 ms PER QUERY, while
        # CopyFrom of a template is a C-level message copy. Templates
        # are read-only after this point (queries CopyFrom, never
        # mutate) and die with the cache entry.
        out = []
        for b in batches:
            t = tempopb.SearchBlocksRequest()
            for m, sp, n in b:
                j = t.jobs.add()
                j.block_id = m.block_id
                j.start_page = sp
                j.pages_to_search = n
                j.encoding = m.encoding
                j.version = m.version
                j.data_encoding = m.data_encoding
                # meta window travels with the job so the executor can
                # window-prune container-less blocks pre-proto-scan
                j.start_time = m.start_time or 0
                j.end_time = m.end_time or 0
            # the batch's routing preference: its (single, by the run
            # break above) owner's member index; None = round-robin
            out.append((b, t, owner_of.get(b[0][0].block_id), width))
        self._batches_cache.put(key, out)
        return out

    def _search(self, tenant: str, req: tempopb.SearchRequest,
                on_progress=None) -> tuple[tempopb.SearchResponse, int]:
        """on_progress: optional callable(SearchResponse) invoked after
        each sub-response merges that GREW the result set — the
        progressive-streaming seam (docs/search-live-tail.md). The job
        list leads with the ingester/hot-tier leg, so the first
        increment a streaming client sees is the freshest data. Called
        under the merge lock: it must enqueue, not block."""
        import threading

        from tempo_tpu.search import query_stats

        batches = self._search_batches(tenant)
        jobs = [("recent", None)] + [("blocks", b) for b in batches]

        # request-scope stats: one record for the WHOLE external
        # request, merged from its sub-responses' metrics (and their
        # full breakdowns under explain). Feeds the ring + slow-query
        # log only — the per-tenant counters are booked at the
        # execution layer (the queriers), where the kernels ran;
        # re-booking here would double count in single-binary mode.
        qstats = query_stats.begin(tenant, req, scope="request")
        merged = SearchResults.for_request(req)
        merge_lock = threading.Lock()
        quit_event = threading.Event()
        failed_block_ids: set = set()  # BLOCK identity, not batch count —
                                       # a block whose page-range jobs span
                                       # several failed batches counts once

        def merge(r):
            """Incremental merge so the limit can cancel remaining jobs
            (reference results.go quit channel + searchsharding.go:219-274
            stop-dispatch)."""
            with merge_lock:
                before = merged.n_results
                merged.merge_response(r)
                if merged.complete:
                    quit_event.set()
                if on_progress is not None and merged.n_results > before:
                    on_progress(merged.response())

        recent_failed = [False]

        def run(job):
            # in-process sub-requests run under the fronted() mark so
            # their exec-scope slow-log lines defer to THIS request's
            # line (remote queriers never see the mark and log theirs)
            with query_stats.fronted():
                return _run(job)

        def _run(job):
            from tempo_tpu.robustness import DeadlineExceeded, deadline

            kind, payload = job
            if deadline.expired():
                # the request's budget is spent: fail the remaining
                # sub-queries FAST instead of queueing them behind
                # whatever already ate it (a dead device, a cold
                # backend) — the merge goes out partial, and a never-
                # started batch's blocks still count FAILED so
                # metrics.failed_blocks tells the client how much of
                # the corpus went unsearched
                if kind != "recent":
                    pl = payload[0]
                    with merge_lock:
                        failed_block_ids.update(m.block_id
                                                for m, _, _ in pl)
                raise DeadlineExceeded("request deadline expired before "
                                       "sub-query dispatch")
            if kind == "recent":
                try:
                    r = self._retrying(
                        lambda _: self._querier().search_recent(tenant, req),
                        job,
                    )
                except Exception:
                    recent_failed[0] = True  # ingester leg is not a block
                    raise
            else:
                payload, template, owner, width = payload
                breq = tempopb.SearchBlocksRequest()
                breq.CopyFrom(template)  # C-level copy of the job list
                breq.search_req.CopyFrom(req)
                breq.tenant_id = tenant
                # attempt 0 targets the group's owner (owner-routed
                # HBM; a heat-promoted group hedges against its next
                # replica); retries prefer surviving replicas, then
                # round-robin — owner death degrades to any non-owner's
                # byte-identical host route
                try:
                    r = self._dispatch_batch(
                        breq, owner, width,
                        payload[0][0].block_id, job=job)
                except Exception:
                    # one failed batch = every distinct block it carried
                    with merge_lock:
                        failed_block_ids.update(m.block_id
                                                for m, _, _ in payload)
                    raise
            merge(r)
            return r

        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.robustness import DeadlineExceeded

        _, errors = self.pool.run_jobs(tenant, jobs, run,
                                       stop_event=quit_event)
        # deadline-expired sub-queries are PARTIAL by design, never a
        # request failure: whatever merged before the budget ran out
        # goes out marked partial (their blocks still count failed —
        # 206, not silence)
        dl_errors = [e for e in errors if isinstance(e, DeadlineExceeded)]
        errors = [e for e in errors if not isinstance(e, DeadlineExceeded)]
        if dl_errors:
            merged.metrics.partial = True
            obs.partial_results.inc(len(dl_errors), reason="deadline")
        # partial failures past the tolerance are an error, not a silently
        # smaller answer (reference tolerate_failed_blocks → HTTP 206/5xx)
        if not quit_event.is_set() and errors and (
            recent_failed[0]
            or len(failed_block_ids) > self.cfg.tolerate_failed_blocks
        ):
            raise errors[0]
        # tolerated failures stay FAILED in the metrics — folding them
        # into skipped_blocks would make "broken" indistinguishable from
        # "pruned" (reference frontend.go:144-146; HTTP layer maps
        # failed_blocks > 0 to 206). They also mark the answer partial:
        # a degraded response must never read as a complete one.
        merged.metrics.failed_blocks += len(failed_block_ids)
        if failed_block_ids or recent_failed[0]:
            merged.metrics.partial = True
        if qstats is not None:
            import json

            if merged.explain_parts:
                for part in merged.explain_parts:
                    qstats.merge_child(part)
                # sub-responses WITHOUT a breakdown (the ingester live
                # leg, a querier running stats-disabled) still
                # contributed plain metrics — absorb the remainder so
                # the explain never contradicts the metrics beside it
                qstats.absorb_metrics(
                    _metrics_remainder(merged.metrics,
                                       merged.explain_parts))
            else:
                qstats.absorb_metrics(merged.metrics)
            d = qstats.finish()
            if req.explain:
                # the response carries ONE merged breakdown, replacing
                # the per-sub-request parts the executors attached
                merged.metrics.query_stats_json = json.dumps(
                    d, separators=(",", ":"), sort_keys=True)
        if not req.explain:
            # measured wall time varies run to run; the EXTERNAL
            # response stays deterministic (cacheable, diffable —
            # repeated identical queries must compare equal) unless the
            # caller opted into the breakdown. The field still rode the
            # querier→frontend sub-responses, so the request-scope
            # accounting above saw the real total; the deterministic
            # byte split stays either way.
            merged.metrics.device_seconds = 0.0
        return merged.response(), len(batches)
