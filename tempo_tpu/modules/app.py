"""Single-binary app wiring.

Role-equivalent to the reference's cmd/tempo/app (modules.go dependency
DAG, target selection): builds the full pipeline in one process —
distributor → ring → N ingesters → shared TempoDB ← queriers ←
frontend — plus the maintenance loops (flush sweep, blocklist poll,
compaction, retention) exposed as explicit tick methods so tests and
operators drive them deterministically; `run_maintenance` starts the
background threads for real deployments.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from tempo_tpu.backend import open_backend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.observability.log import get_logger
from .distributor import Distributor
from .frontend import QueryFrontend, FrontendConfig
from .generator import MetricsGenerator
from .ingester import FlushIncompleteError, Ingester
from .overrides import Overrides, Limits
from .querier import Querier
from .ring import Ring


log = get_logger("tempo_tpu.app")


@dataclass
class AppConfig:
    backend: dict = field(default_factory=lambda: {"backend": "memory"})
    cache: dict = field(default_factory=dict)  # {"cache": "lru|memcached|redis|none", ...}
    wal_dir: str = "./wal"
    n_ingesters: int = 1
    n_queriers: int = 1
    replication_factor: int = 1
    db: TempoDBConfig = field(default_factory=TempoDBConfig)
    frontend: FrontendConfig = field(default_factory=FrontendConfig)
    limits: Limits = field(default_factory=Limits)
    per_tenant_overrides: dict = field(default_factory=dict)
    write_quorum: str = "majority"  # or "one" (RF=2 eventual consistency)
    external_endpoints: list = field(default_factory=list)  # serverless workers
    flush_tick_s: float = 10.0
    poll_tick_s: float = 30.0
    compaction_tick_s: float = 30.0
    # self_tracing: {enabled, exporter: self|otlp, endpoint, tenant,
    # sample_ratio} — the framework traces itself (observability/tracing)
    self_tracing: dict = field(default_factory=dict)
    # metrics_generator: {remote_write: {url, headers, interval_s,
    # external_labels}, spool_dir} — prometheus remote-write shipping
    metrics_generator: dict = field(default_factory=dict)
    # receivers: {kafka: {brokers, topic, group_id, encoding, ...},
    # pubsub_lite: {topic, subscription, ...}} — pull-based ingest
    # (push receivers — OTLP gRPC/HTTP, Zipkin, Jaeger — live on the
    # server ports and need no config here)
    receivers: dict = field(default_factory=dict)
    # streams each querier opens per discovered query-frontend for pull
    # dispatch (reference querier.frontend_worker parallelism)
    frontend_worker_parallelism: int = 2
    # write-path telemetry (observability/ingest_telemetry.py): stage
    # histograms push->searchable, freshness/backlog gauges, slow-flush
    # log, /debug/ingest. False is a true noop on the ingest path —
    # record sites branch out on one attribute read, ingest output is
    # byte-identical (asserted by bench.py's freshness phase)
    ingest_telemetry_enabled: bool = True
    # slow-flush JSON log threshold (seconds): a successful block
    # completion slower than this emits ONE structured line on
    # tempo_tpu.slowflush (token-bucket rate-limited per tenant under a
    # global ceiling, the slow-query log's idiom); <= 0 disables the
    # line — tempo_ingester_slow_flushes_total still counts every one
    ingest_slow_flush_log_s: float = 30.0
    # synthetic freshness canary: every interval, push one tagged trace
    # and poll BACKEND search until it is visible, exporting measured
    # push->searchable as tempo_ingest_canary_freshness_seconds (+ a
    # failure counter past the deadline). The black-box complement to
    # the white-box stage metrics — a wedged flush/poll loop looks
    # "idle" to each stage individually but times the canary out. Off
    # by default: it writes real (tiny) blocks into its tenant.
    ingest_canary_enabled: bool = False
    ingest_canary_interval_s: float = 30.0
    ingest_canary_tenant: str = "canary"
    # gRPC executor threads on the query-frontend: every pull stream
    # PARKS one thread for its lifetime, so size this above queriers ×
    # parallelism + unary headroom — a starved stream is silent
    frontend_grpc_max_workers: int = 256


class App:
    def __init__(self, cfg: AppConfig | None = None):
        self.cfg = cfg or AppConfig()
        self.backend = open_backend(self.cfg.backend)
        if self.cfg.cache:
            from tempo_tpu.backend.cache import CachedBackend
            from tempo_tpu.backend.netcache import open_cache
            cache = open_cache(self.cfg.cache)
            if cache is not None:
                self.backend = CachedBackend(self.backend, cache=cache)
        self.overrides = Overrides(self.cfg.limits,
                                   self.cfg.per_tenant_overrides)
        self.ring = Ring(replication_factor=self.cfg.replication_factor)

        self.ingesters: dict[str, Ingester] = {}
        self.dbs: list[TempoDB] = []
        for i in range(self.cfg.n_ingesters):
            iid = f"ingester-{i}"
            db = TempoDB(self.backend, f"{self.cfg.wal_dir}/{iid}", self.cfg.db)
            self.dbs.append(db)
            self.ingesters[iid] = Ingester(db, self.overrides, instance_id=iid)
            self.ring.register(iid)

        # queriers share one reader db (blocklist + staged-block cache)
        self.reader_db = TempoDB(self.backend, f"{self.cfg.wal_dir}/querier",
                                 self.cfg.db)
        self.generator = MetricsGenerator()
        self.remote_write = None
        gen_cfg = self.cfg.metrics_generator or {}
        rw = gen_cfg.get("remote_write") or {}
        if rw.get("url"):
            from .remote_write import RemoteWriteShipper
            self.remote_write = RemoteWriteShipper(
                self.generator, rw["url"],
                spool_dir=gen_cfg.get("spool_dir",
                                      f"{self.cfg.wal_dir}/remote-write"),
                interval_s=float(rw.get("interval_s", 15.0)),
                external_labels=rw.get("external_labels", {}),
                headers=rw.get("headers", {}),
            )
        self.distributor = Distributor(self.ring, self.ingesters, self.overrides,
                                       forwarder=self.generator.forward,
                                       write_quorum=self.cfg.write_quorum)
        self.queriers = [
            Querier(self.reader_db, self.ring, self.ingesters, self.overrides,
                    external_endpoints=self.cfg.external_endpoints)
            for _ in range(self.cfg.n_queriers)
        ]
        self.frontend = QueryFrontend(self.queriers, self.cfg.frontend)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._receivers: list = []
        # self-tracing ("tempo traces tempo"): export into our own
        # distributor by default, or OTLP/HTTP out to a collector
        from tempo_tpu.observability import tracing
        self.tracer = tracing.init_tracing(self.cfg.self_tracing,
                                           push=self.push)
        # build identity: the constant-1 gauge whose labels say WHAT is
        # running (set once here; /status re-evaluates live)
        from tempo_tpu.observability import metrics as obs
        from tempo_tpu.observability import profile
        obs.build_info.set(1, **profile.build_info())
        # write-path telemetry + freshness canary (process-wide sink,
        # the profiler idiom: the most recent App's config wins)
        from tempo_tpu.observability import ingest_telemetry
        ingest_telemetry.configure(
            enabled=self.cfg.ingest_telemetry_enabled,
            slow_flush_log_s=self.cfg.ingest_slow_flush_log_s)
        self.canary = None
        if self.cfg.ingest_canary_enabled:
            # the canary searches the READER db, not the frontend: the
            # frontend's ingester leg would see the live trace instantly
            # and mask the very flush/poll wedge the probe exists for
            self.canary = ingest_telemetry.IngestCanary(
                push_fn=self.push,
                search_fn=self.reader_db.search,
                tenant=self.cfg.ingest_canary_tenant,
                interval_s=self.cfg.ingest_canary_interval_s)
        ingest_telemetry.TELEMETRY.canary = self.canary

    # ---- public API surface (what api/http.py routes onto) ----

    def push(self, tenant: str, batches) -> None:
        self.distributor.push_batches(tenant, batches)

    def find_trace(self, tenant: str, trace_id: bytes):
        return self.frontend.find_trace_by_id(tenant, trace_id)

    def search(self, tenant: str, req, on_progress=None):
        return self.frontend.search(tenant, req, on_progress=on_progress)

    def tail_subscribe(self, tenant: str, req):
        """Register a standing tail query (docs/search-live-tail.md).
        None = hot tier disabled, or the tenant's subscription cap is
        reached — the HTTP layer maps the two to 400/429."""
        from tempo_tpu.search.live_tier import LIVE_TIER

        if not LIVE_TIER.enabled:
            return None
        return LIVE_TIER.subscribe(tenant, req)

    def tail_unsubscribe(self, sub) -> None:
        from tempo_tpu.search.live_tier import LIVE_TIER

        if LIVE_TIER.enabled:
            LIVE_TIER.unsubscribe(sub)

    # ---- maintenance ticks ----

    def flush_tick(self, force: bool = False) -> list:
        completed = []
        for ing in self.ingesters.values():
            completed.extend(ing.sweep(force=force))
        return completed

    def poll_tick(self) -> None:
        self.reader_db.poll()

    def compaction_tick(self) -> None:
        for tenant in self.reader_db.blocklist.tenants():
            self.reader_db.compact_tenant_once(tenant)
            self.reader_db.retain_tenant(tenant)

    def heartbeat_tick(self) -> None:
        for iid in self.ingesters:
            self.ring.heartbeat(iid)
        self.ring.forget_unhealthy()

    # ---- lifecycle ----

    def run_maintenance(self) -> None:
        def loop(tick_s, fn, immediate=False):
            def body():
                if immediate:  # restart must not serve an empty
                    try:       # blocklist for a full poll interval
                        fn()
                    except Exception:  # noqa: BLE001 — keep loops alive,
                        # but a backend broken at boot must not be silent
                        # (microservices.py logs the same failure)
                        log.exception("startup maintenance tick")
                while not self._stop.wait(tick_s):
                    try:
                        fn()
                    except Exception:  # noqa: BLE001 — keep loops alive
                        pass
            t = threading.Thread(target=body, daemon=True)
            t.start()
            self._threads.append(t)

        loop(self.cfg.flush_tick_s, self.flush_tick)
        loop(self.cfg.poll_tick_s, self.poll_tick, immediate=True)
        loop(self.cfg.compaction_tick_s, self.compaction_tick)
        loop(5.0, self.heartbeat_tick)
        if self.remote_write is not None:
            self.remote_write.start()
        if self.canary is not None:
            self.canary.start()
        self.start_receivers()

    def start_receivers(self) -> None:
        """Pull-based ingest receivers (kafka / pubsub-lite)."""
        if self._receivers:
            return
        kcfg = self.cfg.receivers.get("kafka")
        if kcfg:
            from tempo_tpu.api.kafka import KafkaReceiver, KafkaReceiverConfig

            rx = KafkaReceiver(KafkaReceiverConfig(**kcfg), self.push)
            rx.start()
            self._receivers.append(rx)
        pcfg = self.cfg.receivers.get("pubsub_lite")
        if pcfg:
            from tempo_tpu.api.kafka import pubsub_lite_receiver

            rx = pubsub_lite_receiver(pcfg, self.push)
            rx.start()
            self._receivers.append(rx)

    def shutdown(self) -> None:
        """Graceful: flush everything, stop loops (reference /shutdown)."""
        self._stop.set()
        if self.canary is not None:
            self.canary.stop()
        for rx in self._receivers:
            rx.stop()
        self._receivers.clear()
        if self.tracer is not None:
            from tempo_tpu.observability import tracing
            self.tracer.shutdown()
            if tracing.get_tracer() is self.tracer:
                tracing.set_tracer(None)
        flush_left = 0
        for ing in self.ingesters.values():
            try:
                ing.flush_all()
            except FlushIncompleteError as e:
                # keep draining the rest of the process — but the WAL on
                # disk still holds data; a scale-down must not remove it
                log.error("shutdown flush incomplete: %s", e)
                flush_left += e.left_behind
        if self.remote_write is not None:
            self.remote_write.stop(final_ship=True)
        self.poll_tick()
        if flush_left:
            # re-raised AFTER the full drain so an orchestrator driving
            # shutdown() programmatically cannot mistake a partial flush
            # for success and delete the node's WAL volume
            raise FlushIncompleteError(left_behind=flush_left, completed=[])

    def ready(self) -> bool:
        return self.ring.healthy_count() >= self.cfg.replication_factor
