"""Consistent-hash ring for write placement and job ownership.

Role-equivalent to the reference's dskit ring + lifecycler (SURVEY.md §2.5
write replication row): instances register token sets; a key's token walks
the ring clockwise collecting the first RF distinct healthy instances
(replication set). Also provides `owns` for compactor-style job-ownership
sharding (modules/compactor/compactor.go:186-221).

This is the in-process implementation; the interface (register/heartbeat/
get/owns) is what a memberlist-gossip backend would implement for
multi-process deployments.
"""

from __future__ import annotations

import bisect
import random
import threading
import time
from dataclasses import dataclass, field

DEFAULT_TOKENS = 128
HEARTBEAT_TIMEOUT_S = 60


@dataclass
class RingInstance:
    id: str
    tokens: list = field(default_factory=list)
    last_heartbeat: float = 0.0
    state: str = "ACTIVE"  # ACTIVE | LEAVING

    def healthy(self, now: float, timeout: float = HEARTBEAT_TIMEOUT_S) -> bool:
        return self.state == "ACTIVE" and now - self.last_heartbeat < timeout


class Ring:
    def __init__(self, replication_factor: int = 3):
        self.rf = replication_factor
        self._lock = threading.Lock()
        self._instances: dict[str, RingInstance] = {}
        self._tokens: list[tuple[int, str]] = []  # sorted (token, instance id)

    # ---- membership (lifecycler role) ----

    def register(self, instance_id: str, n_tokens: int = DEFAULT_TOKENS,
                 seed: int | None = None) -> RingInstance:
        rng = random.Random(seed if seed is not None else instance_id)
        inst = RingInstance(
            id=instance_id,
            tokens=sorted(rng.randrange(2**32) for _ in range(n_tokens)),
            last_heartbeat=time.monotonic(),
        )
        with self._lock:
            self._instances[instance_id] = inst
            self._rebuild()
        return inst

    def __contains__(self, instance_id: str) -> bool:
        with self._lock:
            return instance_id in self._instances

    def heartbeat(self, instance_id: str) -> None:
        with self._lock:
            if instance_id in self._instances:
                self._instances[instance_id].last_heartbeat = time.monotonic()

    def leave(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)
            self._rebuild()

    def forget_unhealthy(self) -> list[str]:
        """Auto-forget (reference: compactor/generator rings)."""
        now = time.monotonic()
        with self._lock:
            dead = [i for i, inst in self._instances.items()
                    if not inst.healthy(now)]
            for i in dead:
                del self._instances[i]
            if dead:
                self._rebuild()
        return dead

    def _rebuild(self) -> None:
        self._tokens = sorted(
            (t, i) for i, inst in self._instances.items() for t in inst.tokens
        )

    # ---- placement ----

    def get(self, token: int, rf: int | None = None) -> list[str]:
        """Replication set: first `rf` distinct healthy instances clockwise
        from token. Unhealthy instances are skipped (write extension,
        reference distributor.go:359-362)."""
        rf = rf or self.rf
        now = time.monotonic()
        with self._lock:
            if not self._tokens:
                return []
            out: list[str] = []
            start = bisect.bisect_left(self._tokens, (token & 0xFFFFFFFF, ""))
            n = len(self._tokens)
            for k in range(n):
                _, iid = self._tokens[(start + k) % n]
                if iid in out:
                    continue
                if not self._instances[iid].healthy(now):
                    continue
                out.append(iid)
                if len(out) >= rf:
                    break
            return out

    def owns(self, instance_id: str, token: int) -> bool:
        """Job-ownership: does this instance lead the replica set for the
        token?"""
        got = self.get(token, rf=1)
        return bool(got) and got[0] == instance_id

    def shuffle_shard(self, tenant: str, size: int) -> "Ring":
        """Deterministic, scale-stable per-tenant sub-ring (reference dskit
        ShuffleShard, used for generator placement and frontend querier
        limits — SURVEY.md §2.5): instance k of the shard is the first
        distinct owner clockwise of hash(tenant, k) on the token ring, so
        a join/leave only remaps the tenants whose walk crosses the
        changed tokens — not every tenant at once."""
        import hashlib

        sub = Ring(replication_factor=min(self.rf, max(1, size)))
        with self._lock:
            if size <= 0 or size >= len(self._instances) or not self._tokens:
                return self
            chosen: list[str] = []
            k = 0
            while len(chosen) < size and k < size * 8:
                h = hashlib.sha256(f"{tenant}/{k}".encode()).digest()
                token = int.from_bytes(h[:4], "big")
                start = bisect.bisect_left(self._tokens, (token, ""))
                n = len(self._tokens)
                for j in range(n):
                    _, iid = self._tokens[(start + j) % n]
                    if iid not in chosen:
                        chosen.append(iid)
                        break
                k += 1
            for iid in chosen:
                # shared instance objects: heartbeats flow through
                sub._instances[iid] = self._instances[iid]
            sub._rebuild()
        return sub

    def healthy_count(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for i in self._instances.values() if i.healthy(now))

    def instance_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._instances)
