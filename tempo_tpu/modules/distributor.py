"""Distributor: validate, rate-limit, regroup, replicate.

Role-equivalent to the reference's modules/distributor
(distributor.go:272-516, search_data.go): incoming OTLP batches are
regrouped by trace id (one trace's spans can arrive scattered across
batches), validated against per-tenant limits, search data is extracted
once, segments are marshalled once, and the ring routes each trace to RF
ingesters (write extension past unhealthy ones happens inside Ring.get).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field

from tempo_tpu import tempopb
from tempo_tpu.model.codec import segment_codec_for, CURRENT_ENCODING
from tempo_tpu.model.matches import trace_range_ns
from tempo_tpu.search.data import extract_search_data, encode_search_data
from tempo_tpu.utils.hashing import token_for
from tempo_tpu.utils.ids import pad_trace_id, validate_trace_id
from tempo_tpu.observability import metrics as obs
from .overrides import Overrides
from .ring import Ring


class IngestError(Exception):
    pass


class RateLimited(IngestError):
    pass


@dataclass
class DistributorMetrics:
    spans_received: int = 0
    traces_pushed: int = 0
    push_failures: int = 0
    bytes_received: int = 0
    forwarder_dropped: int = 0


class Distributor:
    def __init__(self, ring: Ring, pushers: dict, overrides: Overrides | None = None,
                 forwarder=None, forward_queue_size: int = 1000,
                 write_quorum: str = "majority"):
        """pushers: instance id → object with push_bytes(tenant, PushBytesRequest)
        (in-process Ingester or a gRPC client stub). forwarder: optional
        fn(tenant, batches) feeding the metrics-generator off the hot path
        via a bounded queue + worker thread (reference distributor
        forwarder.go); overflow drops batches rather than blocking ingest."""
        self.ring = ring
        self.pushers = pushers
        self.overrides = overrides or Overrides()
        self.codec = segment_codec_for(CURRENT_ENCODING)
        self.metrics = DistributorMetrics()
        # "majority" (default) or "one" — the reference's RF=2
        # EventuallyConsistentStrategy writes with quorum 1
        # (pkg/ring/ring.go:16-98)
        if write_quorum not in ("majority", "one"):
            raise ValueError(
                f"write_quorum must be 'majority' or 'one', got {write_quorum!r}")
        self.write_quorum = write_quorum
        self.forwarder = forwarder
        self._forward_queue = None
        if forwarder is not None:
            self._forward_queue = queue.Queue(maxsize=forward_queue_size)
            t = threading.Thread(target=self._forward_loop, daemon=True)
            t.start()

    def _forward_loop(self) -> None:
        while True:
            tenant, batches = self._forward_queue.get()
            try:
                self.forwarder(tenant, batches)
            except Exception:  # noqa: BLE001 — derivation failures never propagate
                pass
            finally:
                self._forward_queue.task_done()

    def forward_flush(self) -> None:
        """Block until queued forwarder work has drained (tests/shutdown)."""
        if self._forward_queue is not None:
            self._forward_queue.join()

    def push_batches(self, tenant: str, batches: list) -> None:
        """The write hot path (reference PushBatches → requestsByTraceID →
        sendToIngestersViaBytes, SURVEY.md §3.1)."""
        if not tenant:
            raise IngestError("missing tenant")
        size = sum(b.ByteSize() for b in batches)
        if not self.overrides.allow_ingestion(tenant, size):
            self.metrics.push_failures += 1
            obs.push_failures.inc(tenant=tenant, reason="rate_limited")
            raise RateLimited(f"tenant {tenant} over ingestion rate")
        self.metrics.bytes_received += size
        obs.ingest_bytes.inc(size, tenant=tenant)

        by_trace, n_spans = self._requests_by_trace_id(batches)
        obs.ingest_spans.inc(n_spans, tenant=tenant)

        if self._forward_queue is not None:
            try:
                self._forward_queue.put_nowait((tenant, batches))
            except queue.Full:  # metrics derivation never blocks ingest
                self.metrics.forwarder_dropped += 1

        lim = self.overrides.limits(tenant)
        req_per_ingester: dict[str, tempopb.PushBytesRequest] = {}
        trace_replicas: dict[bytes, list[str]] = {}
        for tid, trace in by_trace.items():
            start_ns, end_ns = trace_range_ns(trace)
            sd = extract_search_data(
                tid, trace, max_bytes=lim.max_search_bytes_per_trace
            )
            seg = self.codec.prepare_for_write(
                trace, start_ns // 1_000_000_000, end_ns // 1_000_000_000
            )
            if len(seg) > lim.max_bytes_per_trace:
                self.metrics.push_failures += 1
                obs.push_failures.inc(tenant=tenant, reason="trace_too_large")
                raise IngestError(
                    f"trace {tid.hex()} exceeds max_bytes_per_trace"
                )
            replicas = self.ring.get(token_for(tenant, tid))
            if not replicas:
                raise IngestError("no healthy ingesters in ring")
            trace_replicas[tid] = replicas
            for iid in replicas:
                r = req_per_ingester.setdefault(iid, tempopb.PushBytesRequest())
                r.ids.append(tid)
                r.traces.append(seg)
                r.search_data.append(encode_search_data(sd))
            self.metrics.traces_pushed += 1

        errs: dict[str, Exception] = {}
        for iid, r in req_per_ingester.items():
            try:
                self.pushers[iid].push_bytes(tenant, r)
            except Exception as e:  # noqa: BLE001 — quorum semantics below
                errs[iid] = e
        if errs:
            # per-trace quorum over its OWN replica set (reference
            # ring.DoBatch tracks success per item, not per batch): a trace
            # is durable iff a majority of its replicas took the write
            for tid, replicas in trace_replicas.items():
                ok = sum(1 for iid in replicas if iid not in errs)
                need = 1 if self.write_quorum == "one" else len(replicas) // 2 + 1
                if ok < need:
                    self.metrics.push_failures += 1
                    obs.push_failures.inc(tenant=tenant, reason="quorum")
                    raise IngestError(
                        f"push quorum failed for trace {tid.hex()}: "
                        f"{list(errs.items())[:2]}"
                    )

    def _requests_by_trace_id(self, batches: list) -> tuple[dict, int]:
        """Regroup + count spans_received (the ingest ack path). Callers
        that only need the grouping (the generator forwarder re-routes
        the same batches later, off the ack path) use regroup_by_trace —
        counting here twice would double spans_received per push."""
        out, n_spans = self.regroup_by_trace(batches)
        self.metrics.spans_received += n_spans
        return out, n_spans

    @staticmethod
    def regroup_by_trace(batches: list) -> tuple[dict, int]:
        """Regroup spans by trace id (reference distributor.go:442-516 —
        the hot loop: one trace's spans arrive scattered over resource
        batches; rebuild one Trace per id preserving resource/scope).
        Returns (traces by id, span count); no metric side effects."""
        out: dict[bytes, tempopb.Trace] = {}
        n_spans = 0
        for batch in batches:
            for ss in batch.scope_spans:
                for span in ss.spans:
                    validate_trace_id(span.trace_id)
                    tid = pad_trace_id(span.trace_id)
                    n_spans += 1
                    trace = out.get(tid)
                    if trace is None:
                        trace = out[tid] = tempopb.Trace()
                    dest = None
                    for rb in trace.batches:
                        if rb.resource == batch.resource:
                            dest = rb
                            break
                    if dest is None:
                        dest = trace.batches.add()
                        dest.resource.CopyFrom(batch.resource)
                        dest.schema_url = batch.schema_url
                    dss = None
                    for cand in dest.scope_spans:
                        if cand.scope == ss.scope:
                            dss = cand
                            break
                    if dss is None:
                        dss = dest.scope_spans.add()
                        dss.scope.CopyFrom(ss.scope)
                        dss.schema_url = ss.schema_url
                    dss.spans.append(span)
        return out, n_spans
