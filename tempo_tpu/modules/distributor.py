"""Distributor: validate, rate-limit, regroup, replicate.

Role-equivalent to the reference's modules/distributor
(distributor.go:272-516, search_data.go): incoming OTLP batches are
regrouped by trace id (one trace's spans can arrive scattered across
batches), validated against per-tenant limits, search data is extracted
once, segments are marshalled once, and the ring routes each trace to RF
ingesters (write extension past unhealthy ones happens inside Ring.get).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field

from tempo_tpu import tempopb
from tempo_tpu.model.codec import segment_codec_for, CURRENT_ENCODING
from tempo_tpu.search.data import encode_search_data
from tempo_tpu.utils.hashing import token_for
from tempo_tpu.utils.ids import pad_trace_id, validate_trace_id
from tempo_tpu.observability import metrics as obs
from .overrides import Overrides
from .ring import Ring


class IngestError(Exception):
    pass


class RateLimited(IngestError):
    pass


@dataclass
class DistributorMetrics:
    spans_received: int = 0
    traces_pushed: int = 0
    push_failures: int = 0
    bytes_received: int = 0
    forwarder_dropped: int = 0


class Distributor:
    def __init__(self, ring: Ring, pushers: dict, overrides: Overrides | None = None,
                 forwarder=None, forward_queue_size: int = 1000,
                 write_quorum: str = "majority"):
        """pushers: instance id → object with push_bytes(tenant, PushBytesRequest)
        (in-process Ingester or a gRPC client stub). forwarder: optional
        fn(tenant, batches) feeding the metrics-generator off the hot path
        via a bounded queue + worker thread (reference distributor
        forwarder.go); overflow drops batches rather than blocking ingest."""
        self.ring = ring
        self.pushers = pushers
        self.overrides = overrides or Overrides()
        self.codec = segment_codec_for(CURRENT_ENCODING)
        self.metrics = DistributorMetrics()
        # native single-pass ingest walker (VERDICT r4 #4): probe once —
        # an empty input exercises symbol presence without real work
        from tempo_tpu.ops import native as _native

        self._native = _native
        try:
            self._use_native = (CURRENT_ENCODING == "v2"
                                and _native.ingest_regroup([], 0) is not None)
        except Exception:  # noqa: BLE001 — fall back to the Python walk
            self._use_native = False
        # "majority" (default) or "one" — the reference's RF=2
        # EventuallyConsistentStrategy writes with quorum 1
        # (pkg/ring/ring.go:16-98)
        if write_quorum not in ("majority", "one"):
            raise ValueError(
                f"write_quorum must be 'majority' or 'one', got {write_quorum!r}")
        self.write_quorum = write_quorum
        self.forwarder = forwarder
        self._forward_queue = None
        if forwarder is not None:
            self._forward_queue = queue.Queue(maxsize=forward_queue_size)
            t = threading.Thread(target=self._forward_loop, daemon=True)
            t.start()

    def _forward_loop(self) -> None:
        while True:
            tenant, batches = self._forward_queue.get()
            try:
                self.forwarder(tenant, batches)
            except Exception:  # noqa: BLE001 — derivation failures never propagate
                pass
            finally:
                self._forward_queue.task_done()

    def forward_flush(self) -> None:
        """Block until queued forwarder work has drained (tests/shutdown)."""
        if self._forward_queue is not None:
            self._forward_queue.join()

    def push_batches(self, tenant: str, batches: list) -> None:
        """The write hot path (reference PushBatches → requestsByTraceID →
        sendToIngestersViaBytes, SURVEY.md §3.1). The push_ack stage
        observation wraps the whole method — it is the latency a client
        experiences before its spans are durable on RF ingesters' WALs
        (telemetry-off pays one attribute read, no clock)."""
        from tempo_tpu.observability import tracing
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        # a push becomes a trace of its own (or a child of the HTTP
        # receive span) — with the dogfood pipeline on, the write path
        # is queryable in _selftrace like the read path. Self-ingest
        # pushes arrive with tracing suppressed, so the loop never
        # traces its own exporter (start_span returns the noop span).
        with tracing.start_span("distributor.PushBatches",
                                tenant=tenant) as span:
            if span.recording:
                span.set_attribute("batches", len(batches))
            if not TELEMETRY.enabled:
                return self._push_batches(tenant, batches)
            t0 = time.perf_counter()
            self._push_batches(tenant, batches)
            TELEMETRY.record_push_ack(time.perf_counter() - t0)

    def _push_batches(self, tenant: str, batches: list) -> None:
        if not tenant:
            raise IngestError("missing tenant")
        blobs = None
        if self._use_native:
            blobs = [b.SerializeToString() for b in batches]
            size = sum(map(len, blobs))
        else:
            size = sum(b.ByteSize() for b in batches)
        if not self.overrides.allow_ingestion(tenant, size):
            self.metrics.push_failures += 1
            obs.push_failures.inc(tenant=tenant, reason="rate_limited")
            raise RateLimited(f"tenant {tenant} over ingestion rate")
        self.metrics.bytes_received += size
        obs.ingest_bytes.inc(size, tenant=tenant)

        lim = self.overrides.limits(tenant)
        items = None  # [(tid, start_s, end_s, segment, sd_bytes)]
        summaries = None
        from tempo_tpu.search.structural import STRUCTURAL

        if blobs is not None:
            try:
                if STRUCTURAL.enabled:
                    # structural gate on: the native walker emits the
                    # span section too (tt_ingest_regroup2, byte-
                    # identical to the Python walk) — a stale .so
                    # without the symbol returns None and the Python
                    # walk below keeps every flushed block span-bearing
                    native_out = self._native.ingest_regroup(
                        blobs, lim.max_search_bytes_per_trace,
                        spans=True, max_spans=STRUCTURAL.max_spans,
                        max_span_kvs=STRUCTURAL.max_span_kvs)
                else:
                    native_out = self._native.ingest_regroup(
                        blobs, lim.max_search_bytes_per_trace)
            except self._native.InvalidTraceId:
                native_out = None  # python path raises canonical error
            if native_out is not None:
                n_spans, items, summaries = native_out
        if items is None:
            by_trace, n_spans, sd_by_trace = self._regroup_extract(
                batches, lim.max_search_bytes_per_trace)
            if STRUCTURAL.enabled:
                # structural engine: per-span summary rows ride the
                # search-data payload (a second walk over the regrouped
                # trace, paid ONLY behind the gate — gate off keeps the
                # fused single walk and the byte-identical wire form)
                from tempo_tpu.search.data import collect_span_rows

                for tid, trace in by_trace.items():
                    sd_by_trace[tid].spans = collect_span_rows(
                        trace, max_spans=STRUCTURAL.max_spans,
                        max_kvs=STRUCTURAL.max_span_kvs)
            items = []
            for tid, trace in by_trace.items():
                sd = sd_by_trace[tid]
                items.append((tid, sd.start_s, sd.end_s,
                              self.codec.prepare_for_write(
                                  trace, sd.start_s, sd.end_s),
                              encode_search_data(sd)))
        self.metrics.spans_received += n_spans
        obs.ingest_spans.inc(n_spans, tenant=tenant)

        if self._forward_queue is not None:
            # in-process generators take the native span summaries (no
            # second proto walk, far less GIL steal); forwarders that
            # must ship real batches (the gRPC PushSpans route to a
            # standalone generator) keep receiving them
            if summaries is not None and getattr(
                    self.forwarder, "accepts_summaries", False):
                payload = ("summaries", summaries,
                           [it[0] for it in items])
            else:
                payload = batches
            try:
                self._forward_queue.put_nowait((tenant, payload))
            except queue.Full:  # metrics derivation never blocks ingest
                self.metrics.forwarder_dropped += 1

        req_per_ingester: dict[str, tempopb.PushBytesRequest] = {}
        trace_replicas: dict[bytes, list[str]] = {}
        for tid, _start_s, _end_s, seg, sd_bytes in items:
            if len(seg) > lim.max_bytes_per_trace:
                self.metrics.push_failures += 1
                obs.push_failures.inc(tenant=tenant, reason="trace_too_large")
                raise IngestError(
                    f"trace {tid.hex()} exceeds max_bytes_per_trace"
                )
            replicas = self.ring.get(token_for(tenant, tid))
            if not replicas:
                raise IngestError("no healthy ingesters in ring")
            trace_replicas[tid] = replicas
            for iid in replicas:
                r = req_per_ingester.setdefault(iid, tempopb.PushBytesRequest())
                r.ids.append(tid)
                r.traces.append(seg)
                r.search_data.append(sd_bytes)
            self.metrics.traces_pushed += 1

        errs: dict[str, Exception] = {}
        for iid, r in req_per_ingester.items():
            try:
                self.pushers[iid].push_bytes(tenant, r)
            except Exception as e:  # noqa: BLE001 — quorum semantics below
                errs[iid] = e
        if errs:
            # per-trace quorum over its OWN replica set (reference
            # ring.DoBatch tracks success per item, not per batch): a trace
            # is durable iff a majority of its replicas took the write
            from tempo_tpu.modules.ingester import LimitError

            for tid, replicas in trace_replicas.items():
                ok = sum(1 for iid in replicas if iid not in errs)
                need = 1 if self.write_quorum == "one" else len(replicas) // 2 + 1
                if ok < need:
                    self.metrics.push_failures += 1
                    # classify over THIS trace's own replica errors only:
                    # an unrelated ingester's network fault elsewhere in
                    # the batch must not turn limit pushback into a 500
                    own = [errs[iid] for iid in replicas if iid in errs]
                    if own and all(isinstance(e, LimitError) for e in own):
                        # tenant limit (max live traces / trace bytes) is
                        # a RETRYABLE pushback, not a server fault — the
                        # reference answers FailedPrecondition and the
                        # write path surfaces 429, never 500
                        # (modules/ingester/instance.go:185,
                        # distributor.go:525-527)
                        reason = ("trace_too_large"
                                  if "bytes per trace" in str(own[0])
                                  else "live_traces_exceeded")
                        obs.push_failures.inc(tenant=tenant, reason=reason)
                        raise RateLimited(
                            f"tenant {tenant} over ingest limits: {own[0]}")
                    obs.push_failures.inc(tenant=tenant, reason="quorum")
                    raise IngestError(
                        f"push quorum failed for trace {tid.hex()}: "
                        f"{[(iid, e) for iid, e in errs.items() if iid in replicas][:2]}"
                    )

    @staticmethod
    def _regroup_extract(batches: list, max_search_bytes: int
                         ) -> tuple[dict, int, dict]:
        """regroup_by_trace + extract_search_data + trace time range in
        ONE walk over the incoming spans — the ack path walked every
        span (and every attribute) three times before (profiled r5).
        Returns (traces by id, span count, SearchData by id with
        start_s/end_s/dur_ms filled). Resource attributes parse once per
        incoming BATCH object and fan out to every trace that references
        it. Budget truncation is first-seen in arrival order (the old
        per-trace walk truncated in regrouped order — same contract:
        best-effort tag retention under the byte cap)."""
        from tempo_tpu.search.data import SearchData, _any_value_str

        out: dict[bytes, tempopb.Trace] = {}
        sds: dict[bytes, SearchData] = {}
        budget: dict[bytes, int] = {}
        rng: dict[bytes, list] = {}      # tid → [start_ns, end_ns]
        root: dict[bytes, tuple] = {}    # tid → (start, svc, name)
        first: dict[bytes, tuple] = {}   # earliest span fallback
        dest_by: dict[tuple, object] = {}
        dss_by: dict[tuple, object] = {}
        pad_cache: dict[bytes, bytes] = {}
        n_spans = 0
        ERROR = tempopb.Status.STATUS_CODE_ERROR
        for bi, batch in enumerate(batches):
            res_kvs = [(kv.key, _any_value_str(kv.value))
                       for kv in batch.resource.attributes]
            svc = ""
            for k, v in res_kvs:
                if k == "service.name":
                    svc = v  # last occurrence wins (extractor parity)
            for si, ss in enumerate(batch.scope_spans):
                for span in ss.spans:
                    raw = span.trace_id
                    tid = pad_cache.get(raw)
                    if tid is None:
                        validate_trace_id(raw)
                        tid = pad_cache[raw] = pad_trace_id(raw)
                    n_spans += 1
                    sd = sds.get(tid)
                    if sd is None:
                        sd = sds[tid] = SearchData(trace_id=tid)
                        budget[tid] = max_search_bytes
                        rng[tid] = [2**63, 0]
                    kvs = sd.kvs
                    b = budget[tid]
                    dss = dss_by.get((tid, bi, si))
                    if dss is None:
                        trace = out.get(tid)
                        if trace is None:
                            trace = out[tid] = tempopb.Trace()
                        dest = dest_by.get((tid, bi))
                        if dest is None:
                            dest = trace.batches.add()
                            dest.resource.CopyFrom(batch.resource)
                            dest.schema_url = batch.schema_url
                            dest_by[(tid, bi)] = dest
                            for k, v in res_kvs:  # once per (trace, batch)
                                if v:
                                    cost = len(k) + len(v)
                                    if b >= cost:
                                        s = kvs.get(k)
                                        if s is None:
                                            s = kvs[k] = set()
                                        if v not in s:
                                            s.add(v)
                                            b -= cost
                        dss = dest.scope_spans.add()
                        dss.scope.CopyFrom(ss.scope)
                        dss.schema_url = ss.schema_url
                        dss_by[(tid, bi, si)] = dss
                    dss.spans.append(span)

                    st = span.start_time_unix_nano
                    en = span.end_time_unix_nano
                    r = rng[tid]
                    if st < r[0]:
                        r[0] = st
                    if en > r[1]:
                        r[1] = en

                    v = span.name
                    if v:
                        cost = 4 + len(v)
                        if b >= cost:
                            s = kvs.get("name")
                            if s is None:
                                s = kvs["name"] = set()
                            if v not in s:
                                s.add(v)
                                b -= cost
                    if span.status.code == ERROR and b >= 9:
                        s = kvs.get("error")
                        if s is None:
                            s = kvs["error"] = set()
                        if "true" not in s:
                            s.add("true")
                            b -= 9
                    for kv in span.attributes:
                        v = _any_value_str(kv.value)
                        if v:
                            k = kv.key
                            cost = len(k) + len(v)
                            if b >= cost:
                                s = kvs.get(k)
                                if s is None:
                                    s = kvs[k] = set()
                                if v not in s:
                                    s.add(v)
                                    b -= cost
                    budget[tid] = b

                    if not span.parent_span_id:
                        prev = root.get(tid)
                        if prev is None or st < prev[0]:
                            root[tid] = (st, svc, span.name)
                    else:
                        prev = first.get(tid)
                        if prev is None or st < prev[0]:
                            first[tid] = (st, svc, span.name)

        for tid, sd in sds.items():
            start_ns, end_ns = rng[tid]
            if end_ns == 0:
                start_ns = 0  # trace_range_ns contract: no ended span
            sd.start_s = start_ns // 1_000_000_000
            sd.end_s = end_ns // 1_000_000_000
            # max(0, end - start): clock skew can put end before start,
            # and a negative duration must clamp (not raise in _U32.pack)
            # identically to extract_search_data and the native walker
            sd.dur_ms = (min(max(0, end_ns - start_ns) // 1_000_000,
                             0xFFFFFFFF)
                         if end_ns else 0)
            r = root.get(tid) or first.get(tid)
            if r is not None:
                sd.root_service, sd.root_name = r[1], r[2]
        return out, n_spans, sds

    @staticmethod
    def regroup_by_trace(batches: list) -> tuple[dict, int]:
        """Regroup spans by trace id (reference distributor.go:442-516 —
        the hot loop: one trace's spans arrive scattered over resource
        batches; rebuild one Trace per id preserving resource/scope).
        Returns (traces by id, span count); no metric side effects.

        Destination lookups key on SOURCE POSITION (batch/scope index),
        not proto equality: recursive proto == per span was the single
        hottest ingest cost (profiled r5), and object id() is unusable —
        upb repeated-field iteration hands out transient wrappers whose
        addresses get reused, silently crossing destinations (caught by
        the r5 differential fuzz). A duplicated-but-equal resource in
        the input yields two batches in the output — the combiner and
        every reader treat that identically."""
        out: dict[bytes, tempopb.Trace] = {}
        dest_by: dict[tuple, object] = {}   # (tid, batch idx) → ResourceSpans
        dss_by: dict[tuple, object] = {}    # (tid, batch, scope) → ScopeSpans
        pad_cache: dict[bytes, bytes] = {}  # raw tid → validated padded tid
        n_spans = 0
        for bi, batch in enumerate(batches):
            for si, ss in enumerate(batch.scope_spans):
                for span in ss.spans:
                    raw = span.trace_id
                    tid = pad_cache.get(raw)
                    if tid is None:
                        validate_trace_id(raw)
                        tid = pad_cache[raw] = pad_trace_id(raw)
                    n_spans += 1
                    dss = dss_by.get((tid, bi, si))
                    if dss is None:
                        trace = out.get(tid)
                        if trace is None:
                            trace = out[tid] = tempopb.Trace()
                        dest = dest_by.get((tid, bi))
                        if dest is None:
                            dest = trace.batches.add()
                            dest.resource.CopyFrom(batch.resource)
                            dest.schema_url = batch.schema_url
                            dest_by[(tid, bi)] = dest
                        dss = dest.scope_spans.add()
                        dss.scope.CopyFrom(ss.scope)
                        dss.schema_url = ss.schema_url
                        dss_by[(tid, bi, si)] = dss
                    dss.spans.append(span)
        return out, n_spans
