"""Prometheus remote-write shipper for the metrics-generator.

Role-equivalent to the reference's generator storage
(modules/generator/storage/instance.go:22-70): the reference runs a
Prometheus agent-mode TSDB whose WAL buffers samples until remote-write
succeeds. Here the same durability contract is kept with a simpler
mechanism suited to the collection-tick model: each tick snapshots the
per-tenant registry into a WriteRequest (prompb wire format, snappy
block compression via the native runtime), POSTs it, and on failure
spools the encoded+compressed payload to disk; spooled payloads are
re-shipped oldest-first with exponential backoff before new data, and
survive process restarts (the WAL role).

Wire contract (any Prometheus/Mimir/Thanos receiver):
  POST <url>  Content-Encoding: snappy
              Content-Type: application/x-protobuf
              X-Prometheus-Remote-Write-Version: 0.1.0
              X-Scope-OrgID: <tenant>   (multi-tenant receivers)
"""

from __future__ import annotations

import os
import threading
import time
import urllib.parse
import urllib.request

from tempo_tpu.observability.log import get_logger
from tempo_tpu.tempopb import remote_write_pb2 as prompb


def encode_write_request(samples: list, timestamp_ms: int,
                         extra_labels: dict | None = None) -> bytes:
    """[(name, ((label, value), ...), float)] → serialized WriteRequest.
    Receivers (Mimir/Thanos) reject out-of-order label sets, so the FULL
    label set including __name__ is sorted lexicographically — a label
    like "Env" legitimately sorts before "__name__"."""
    req = prompb.WriteRequest()
    for name, labels, value in sorted(samples, key=lambda s: (s[0], s[1])):
        ts = req.timeseries.add()
        # prometheus external-label semantics: the series label wins on
        # collision, external labels only fill gaps
        merged = dict(extra_labels or {})
        merged.update(labels)
        merged["__name__"] = name
        for k, v in sorted(merged.items()):
            ts.labels.add(name=k, value=str(v))
        ts.samples.add(value=float(value), timestamp=timestamp_ms)
    return req.SerializeToString()


class RemoteWriteClient:
    """One POST = one WriteRequest. Raises urllib errors on failure."""

    def __init__(self, url: str, tenant: str | None = None,
                 headers: dict | None = None, timeout_s: float = 10.0):
        self.url = url
        self.tenant = tenant
        self.headers = dict(headers or {})
        self.timeout_s = timeout_s

    def send(self, payload: bytes) -> None:
        """payload = already-snappy-compressed WriteRequest bytes."""
        req = urllib.request.Request(self.url, data=payload, method="POST")
        req.add_header("Content-Encoding", "snappy")
        req.add_header("Content-Type", "application/x-protobuf")
        req.add_header("X-Prometheus-Remote-Write-Version", "0.1.0")
        if self.tenant:
            req.add_header("X-Scope-OrgID", self.tenant)
        for k, v in self.headers.items():
            req.add_header(k, v)
        # urlopen raises HTTPError for >=400 and follows redirects itself
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()


class RemoteWriteShipper:
    """Ships a MetricsGenerator's registries; spools on failure.

    Drive with tick() (the generator collection ticker) or start()/stop()
    for a background loop.
    """

    def __init__(self, generator, url: str, spool_dir: str,
                 interval_s: float = 15.0, external_labels: dict | None = None,
                 headers: dict | None = None, timeout_s: float = 10.0,
                 max_spool_bytes: int = 64 << 20,
                 backoff_min_s: float = 1.0, backoff_max_s: float = 120.0):
        self.generator = generator
        self.url = url
        self.spool_dir = spool_dir
        self.interval_s = interval_s
        self.external_labels = dict(external_labels or {})
        self.headers = dict(headers or {})
        self.timeout_s = timeout_s
        self.max_spool_bytes = max_spool_bytes
        self.backoff_min_s = backoff_min_s
        self.backoff_max_s = backoff_max_s
        self._backoff_s = 0.0
        self._next_retry = 0.0
        self._seq = 0
        self._usage: int | None = None  # lazy-scanned, then maintained
        self.sent = 0
        self.failed = 0
        self.spooled = 0
        self.dropped_spool = 0
        self._log = get_logger("tempo_tpu.remote_write")
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(spool_dir, exist_ok=True)
        self._sweep_tmp_orphans()

    # ---- spool (the WAL role) ----

    def _spool_files(self) -> list[str]:
        try:
            names = [n for n in os.listdir(self.spool_dir)
                     if n.endswith(".rw")]
        except FileNotFoundError:
            return []
        return sorted(names)

    def _spool_usage(self) -> int:
        """Running counter (O(1) on the spool path); rescans only once
        at first use after construction."""
        if self._usage is None:
            self._usage = sum(
                os.path.getsize(os.path.join(self.spool_dir, n))
                for n in self._spool_files()
            )
        return self._usage

    def _spool(self, tenant: str, payload: bytes) -> None:
        usage = self._spool_usage()
        if usage + len(payload) > self.max_spool_bytes:
            # drop OLDEST first: newest samples matter most for alerting
            for n in self._spool_files():
                if usage + len(payload) <= self.max_spool_bytes:
                    break
                p = os.path.join(self.spool_dir, n)
                try:
                    size = os.path.getsize(p)
                    os.unlink(p)
                except OSError:
                    continue
                usage -= size
                self._usage = usage
                self.dropped_spool += 1
        self._seq += 1
        # tenant comes from the client-controlled X-Scope-OrgID header —
        # percent-encode so it can't traverse paths, and round-trips
        quoted = urllib.parse.quote(tenant, safe="")
        name = f"{time.time_ns():020d}-{self._seq:06d}-{quoted}.rw"
        path = os.path.join(self.spool_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        self._usage = usage + len(payload)
        self.spooled += 1

    @staticmethod
    def _tenant_of(name: str) -> str:
        return urllib.parse.unquote(name[:-3].split("-", 2)[2])

    def _sweep_tmp_orphans(self) -> None:
        """A crash between open(tmp) and os.replace leaves .tmp files no
        drain pass will ever ship — clear them on startup."""
        try:
            for n in os.listdir(self.spool_dir):
                if n.endswith(".tmp"):
                    try:
                        os.unlink(os.path.join(self.spool_dir, n))
                    except OSError:
                        pass
        except FileNotFoundError:
            pass

    # ---- shipping ----

    def _compress(self, data: bytes) -> bytes:
        from tempo_tpu.ops import native

        return native.snappy_compress(data)

    def _send(self, tenant: str, payload: bytes) -> bool:
        client = RemoteWriteClient(self.url, tenant=tenant,
                                   headers=self.headers,
                                   timeout_s=self.timeout_s)
        try:
            client.send(payload)
            self.sent += 1
            self._backoff_s = 0.0
            return True
        except Exception as e:  # noqa: BLE001 — network errors expected
            self.failed += 1
            self._backoff_s = min(self.backoff_max_s,
                                  (self._backoff_s * 2) or self.backoff_min_s)
            self._next_retry = time.monotonic() + self._backoff_s
            self._log.warning("remote write to %s failed (backoff %.0fs): %s",
                              self.url, self._backoff_s, e)
            return False

    def _drain_spool(self) -> bool:
        """Ship spooled payloads oldest-first. Returns False on failure
        (stop trying this tick)."""
        for name in self._spool_files():
            path = os.path.join(self.spool_dir, name)
            with open(path, "rb") as f:
                payload = f.read()
            if not self._send(self._tenant_of(name), payload):
                return False
            os.unlink(path)
            if self._usage is not None:
                self._usage = max(0, self._usage - len(payload))
        return True

    def tick(self, now_ms: int | None = None) -> None:
        """One collection cycle: snapshot registries → ship (spool first,
        then fresh samples)."""
        with self._lock:
            if time.monotonic() < self._next_retry:
                # in backoff: snapshot to spool, don't hit the receiver
                self._snapshot_to_spool(now_ms)
                return
            healthy = self._drain_spool()
            now_ms = now_ms or time.time_ns() // 1_000_000
            for tenant, payload in self._snapshots(now_ms):
                if healthy and self._send(tenant, payload):
                    continue
                healthy = False
                self._spool(tenant, payload)

    def _snapshots(self, now_ms: int):
        for tenant in self.generator.tenants():
            samples = self.generator.registry(tenant).samples()
            if not samples:
                continue
            raw = encode_write_request(samples, now_ms, self.external_labels)
            yield tenant, self._compress(raw)

    def _snapshot_to_spool(self, now_ms: int | None) -> None:
        now_ms = now_ms or time.time_ns() // 1_000_000
        for tenant, payload in self._snapshots(now_ms):
            self._spool(tenant, payload)

    # ---- lifecycle ----

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — keep shipping
                    self._log.exception("remote-write tick")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="remote-write-shipper")
        self._thread.start()

    def stop(self, final_ship: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if final_ship:
            try:
                self.tick()
            except Exception:  # noqa: BLE001
                pass
