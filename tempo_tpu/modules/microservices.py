"""Per-target process wiring for microservices deployments.

Role-equivalent to the reference's module registry + target selection
(cmd/tempo/app/modules.go:35-50, dependency DAG :325-347): one process
runs one module (or `all`), discovers its peers via gossip membership
(modules/membership.py), and speaks the gRPC surfaces in api/grpc_service:

  distributor     OTLP/HTTP+gRPC receivers → ring writes to ingester Pushers
  ingester        Pusher + IngesterQuerier gRPC; WAL/flush/complete loops
  querier         Querier gRPC (frontend jobs); replica reads via
                  IngesterQuerier clients; backend reads via its TempoDB
  query-frontend  external HTTP API; shards jobs over querier clients
  compactor       ring-ownership-gated compaction + retention loops
  all             the single-binary App (modules/app.py), unchanged

Job dispatch frontend→querier runs in one of two modes:

  pull (default when the query-frontend has a grpc_port): querier workers
  dial the frontend and pull jobs over the tempopb.Frontend/Process
  duplex stream (modules/worker.py) — the reference's httpgrpc pull
  dispatch (frontend v1 + querier/worker). A killed querier's in-flight
  jobs requeue to the survivors.

  push (fallback, and the mode when the frontend serves no gRPC): the
  frontend pushes jobs to Querier-service clients with bounded
  concurrency. The queue/fairness layer (modules/queue.py) and the job
  protocol (SearchBlocksRequest) are identical either way (SURVEY.md
  §2.6 note).
"""

from __future__ import annotations

import threading
import time

from tempo_tpu.backend import open_backend
from tempo_tpu.db import TempoDB
from tempo_tpu.observability import get_logger

from .app import AppConfig
from .distributor import Distributor
from .frontend import QueryFrontend
from .ingester import FlushIncompleteError, Ingester
from .membership import Memberlist
from .overrides import Overrides
from .querier import Querier

TARGETS = ("all", "distributor", "ingester", "querier", "query-frontend",
           "compactor", "metrics-generator")


class ClientDict:
    """Mapping instance-id → gRPC client, refreshed from gossip membership.

    Duck-types the dict the in-process wiring passes (pushers/ingesters):
    supports [] / .get / .values / iteration. Clients are cached per
    address; members that left are dropped."""

    def __init__(self, memberlist: Memberlist, role: str, factory):
        self.ml = memberlist
        self.role = role
        self.factory = factory
        self._clients: dict[str, object] = {}
        self._lock = threading.Lock()

    def _refresh(self) -> dict:
        members = {m.id: m for m in self.ml.members(self.role)}
        with self._lock:
            for mid in list(self._clients):
                if mid not in members:
                    gone = self._clients.pop(mid)
                    ch = getattr(gone, "channel", None)
                    if ch is not None:  # don't leak fds on membership churn
                        ch.close()
            for mid, m in members.items():
                if mid not in self._clients and m.grpc_addr:
                    self._clients[mid] = self.factory(m.grpc_addr)
            return dict(self._clients)

    def __getitem__(self, key):
        c = self._refresh().get(key)
        if c is None:
            raise KeyError(key)
        return c

    def get(self, key, default=None):
        return self._refresh().get(key, default)

    def values(self):
        return self._refresh().values()

    def items(self):
        return self._refresh().items()

    def keys(self):
        return self._refresh().keys()

    def __iter__(self):
        return iter(self._refresh())

    def __len__(self):
        return len(self._refresh())


class ClientList:
    """List-ish round-robin view over a ClientDict (frontend queriers)."""

    def __init__(self, clients: ClientDict):
        self.clients = clients

    def _list(self):
        vals = list(self.clients.values())
        if not vals:
            raise RuntimeError(f"no {self.clients.role} instances in the ring")
        return vals

    def __getitem__(self, i):
        vals = self._list()
        return vals[i % len(vals)]

    def __len__(self):
        return len(self.clients)


class ModuleProcess:
    """One microservice process: membership + the target's modules."""

    def __init__(self, cfg: AppConfig, target: str, *, instance_id: str,
                 grpc_port: int = 0, http_port: int = 0,
                 memberlist_cfg: dict | None = None):
        from tempo_tpu.api.grpc_service import (
            IngesterClient, PusherClient, QuerierClient,
            make_module_grpc_server,
        )

        if target not in TARGETS or target == "all":
            raise ValueError(f"ModuleProcess target must be one of "
                             f"{TARGETS[1:]}, got {target!r}")
        self.cfg = cfg
        self.target = target
        self.id = instance_id
        self.log = get_logger()
        self._stop = threading.Event()

        self.backend = open_backend(cfg.backend)
        if cfg.cache:
            from tempo_tpu.backend.cache import CachedBackend
            from tempo_tpu.backend.netcache import open_cache
            cache = open_cache(cfg.cache)
            if cache is not None:
                self.backend = CachedBackend(self.backend, cache=cache)
        self.overrides = Overrides(cfg.limits, cfg.per_tenant_overrides)

        ml_cfg = dict(memberlist_cfg or {})
        adv_host = ml_cfg.get("advertise_host", "127.0.0.1")
        needs_grpc = target in ("ingester", "querier", "distributor",
                                "metrics-generator")
        # a query-frontend WITH a grpc_port serves the Frontend/Process
        # pull stream; without one it falls back to push dispatch.
        # grpc-serving targets accept grpc_port=0 = EPHEMERAL: the
        # server binds port 0, reads the assigned port, and gossip
        # advertises it — picking a "free" port up front and binding it
        # later is a race (the observed test_microservices flake).
        serves_grpc = needs_grpc or (target == "query-frontend"
                                     and bool(grpc_port))
        self.grpc_addr = (f"{adv_host}:{grpc_port}"
                          if serves_grpc and grpc_port else "")
        self.http_addr = f"{adv_host}:{http_port}" if http_port else ""

        self.ingester = None
        self.querier = None
        self.distributor = None
        self.frontend = None
        self.generator = None        # metrics-generator target
        self.remote_write = None
        self.db = None
        self.grpc_server = None
        self.dispatcher = None       # query-frontend pull dispatch
        self.worker_manager = None   # querier-side pull workers

        if target in ("ingester", "querier", "query-frontend", "compactor"):
            self.db = TempoDB(self.backend, f"{cfg.wal_dir}/{self.id}",
                              cfg.db)
        if target == "ingester":
            self.ingester = Ingester(self.db, self.overrides,
                                     instance_id=self.id)

        self.ml = Memberlist(
            instance_id=self.id, role=target,
            bind=ml_cfg.get("bind", "127.0.0.1:0"),
            advertise_host=ml_cfg.get("advertise_host", ""),
            join=ml_cfg.get("join", []),
            grpc_addr=self.grpc_addr, http_addr=self.http_addr,
            gossip_interval_s=ml_cfg.get("gossip_interval_s", 1.0),
            suspect_timeout_s=ml_cfg.get("suspect_timeout_s", 15.0),
            replication_factor=cfg.replication_factor,
        )

        if target == "distributor":
            from tempo_tpu.api.grpc_service import MetricsGeneratorClient

            pushers = ClientDict(self.ml, "ingester",
                                 lambda a: PusherClient(a))
            self._generator_clients = ClientDict(
                self.ml, "metrics-generator",
                lambda a: MetricsGeneratorClient(a))
            self.distributor = Distributor(
                self.ml.ring("ingester"), pushers, self.overrides,
                forwarder=self._forward_to_generators,
                write_quorum=cfg.write_quorum)
        elif target == "metrics-generator":
            from .generator import MetricsGenerator

            gen_cfg = cfg.metrics_generator or {}
            self.generator = MetricsGenerator(
                max_active_series=gen_cfg.get("max_active_series", 100_000))
            rw = gen_cfg.get("remote_write") or {}
            if rw.get("url"):
                from .remote_write import RemoteWriteShipper

                self.remote_write = RemoteWriteShipper(
                    self.generator, rw["url"],
                    spool_dir=gen_cfg.get(
                        "spool_dir", f"{cfg.wal_dir}/{self.id}/remote-write"),
                    interval_s=float(rw.get("interval_s", 15.0)),
                    external_labels=rw.get("external_labels", {}),
                    headers=rw.get("headers", {}),
                )
                self.remote_write.start()
        elif target == "querier":
            ingesters = ClientDict(self.ml, "ingester",
                                   lambda a: IngesterClient(a))
            self.querier = Querier(self.db, self.ml.ring("ingester"),
                                   ingesters, self.overrides,
                                   external_endpoints=cfg.external_endpoints)
        elif target == "query-frontend":
            push_clients = ClientList(ClientDict(self.ml, "querier",
                                                 lambda a: QuerierClient(a)))
            if serves_grpc:
                from .worker import PullDispatcher, PullQuerierPool
                self.dispatcher = PullDispatcher(
                    instance=self.id,
                    max_queriers_per_tenant=cfg.frontend.max_queriers_per_tenant)
                queriers = PullQuerierPool(self.dispatcher,
                                           fallback=push_clients)
            else:
                queriers = push_clients
            self.frontend = QueryFrontend(queriers, cfg.frontend, db=self.db)

        if serves_grpc:
            self.grpc_server = make_module_grpc_server(
                f"0.0.0.0:{grpc_port}",
                pusher=self.ingester,
                ingester=self.ingester,
                querier=self.querier,
                otlp_push=self.push if self.distributor is not None else None,
                frontend_dispatcher=self.dispatcher,
                generator=self.generator,
                max_workers=(cfg.frontend_grpc_max_workers
                             if self.dispatcher is not None else 16),
            )
            bound = getattr(self.grpc_server, "bound_port", grpc_port)
            if not bound:
                raise RuntimeError(
                    f"gRPC bind failed on port {grpc_port} "
                    f"(target {target}, instance {instance_id})")
            if not grpc_port:
                # ephemeral bind: advertise the ASSIGNED port — peers
                # that merged the address-less record update on the
                # next gossip exchange, before any client could have
                # cached an address for this member
                self.grpc_addr = f"{adv_host}:{bound}"
                self.ml.set_grpc_addr(self.grpc_addr)
            self.grpc_server.start()

        if target == "querier":
            from .worker import PullWorkerManager
            self.worker_manager = PullWorkerManager(
                self.querier, self.ml,
                parallelism=cfg.frontend_worker_parallelism)

        # self-tracing: in-process self-ingest only works where a
        # distributor lives; other targets must export OTLP to a
        # collector (usually the distributor's /v1/traces)
        from tempo_tpu.observability import tracing
        tr_cfg = dict(cfg.self_tracing or {})
        tr_push = self.push if self.distributor is not None else None
        wants_self = (tr_cfg.get("exporter",
                                 "self" if tr_push else "otlp") == "self")
        if tr_cfg.get("enabled") and wants_self and tr_push is None:
            if tr_cfg.get("endpoint"):
                tr_cfg["exporter"] = "otlp"
            else:
                self.log.warning(
                    "self_tracing: target %s has no in-process push; set "
                    "exporter: otlp and an endpoint — tracing disabled",
                    target)
                tr_cfg = {}
        self.tracer = tracing.init_tracing(tr_cfg, push=tr_push)

        self._threads: list[threading.Thread] = []
        self._start_loops()

    def _forward_to_generators(self, tenant: str, batches) -> None:
        """Distributor → metrics-generator shipping (reference
        distributor.go metrics_generator forwarder): route per TRACE over
        the generator ring so a trace's client+server spans land on one
        instance — service-graph pairing is instance-local state. Runs on
        the forwarder's background thread, never the ack path; with no
        generator in the ring the batches drop (the reference counts a
        failure metric and moves on)."""
        from tempo_tpu.modules.distributor import Distributor
        from tempo_tpu.utils.hashing import token_for

        ring = self.ml.ring("metrics-generator")
        by_trace, _ = Distributor.regroup_by_trace(batches)
        per_gen: dict[str, list] = {}
        for tid, trace in by_trace.items():
            owners = ring.get(token_for(tenant, tid), rf=1)
            if not owners:
                continue  # THIS trace unroutable; ship the rest
            per_gen.setdefault(owners[0], []).extend(trace.batches)
        for gid, gbatches in per_gen.items():
            client = self._generator_clients.get(gid)
            if client is None:
                continue
            client.push_spans(tenant, gbatches)

    # ---- the HTTPApi app-interface (api/http.py routes onto this) ----

    def push(self, tenant: str, batches) -> None:
        if self.distributor is None:
            raise ValueError(f"target {self.target} does not accept pushes")
        self.distributor.push_batches(tenant, batches)

    def find_trace(self, tenant: str, trace_id: bytes):
        if self.frontend is None:
            raise ValueError(f"target {self.target} does not serve queries")
        return self.frontend.find_trace_by_id(tenant, trace_id)

    def search(self, tenant: str, req):
        if self.frontend is None:
            raise ValueError(f"target {self.target} does not serve queries")
        return self.frontend.search(tenant, req)

    @property
    def queriers(self):
        if self.frontend is None:
            raise ValueError(f"target {self.target} does not serve queries")
        return self.frontend.queriers

    @property
    def ring(self):
        return self.ml.ring("ingester")

    @property
    def reader_db(self):
        return self.db  # None for targets without a storage reader

    def ready(self) -> bool:
        if self.target in ("distributor", "querier", "query-frontend"):
            need = {"distributor": "ingester", "querier": "ingester",
                    "query-frontend": "querier"}[self.target]
            return len(self.ml.members(need)) > 0
        return True

    def flush_tick(self, force: bool = False) -> list:
        if self.ingester is None:
            return []
        return self.ingester.sweep(force=force)

    def shutdown(self) -> None:
        self._stop.set()
        if self.worker_manager is not None:
            self.worker_manager.stop()
        if self.dispatcher is not None:
            self.dispatcher.stop()
        if self.remote_write is not None:
            self.remote_write.stop(final_ship=True)
        if self.tracer is not None:
            from tempo_tpu.observability import tracing
            self.tracer.shutdown()
            if tracing.get_tracer() is self.tracer:
                tracing.set_tracer(None)
        flush_err = None
        if self.ingester is not None:
            try:
                self.ingester.flush_all()
            except FlushIncompleteError as e:
                self.log.error("shutdown flush incomplete: %s", e)
                flush_err = e
        self.ml.leave()
        if self.grpc_server is not None:
            self.grpc_server.stop(grace=1)
        if flush_err is not None:
            # after the full drain: the caller must see that WAL data
            # remains on disk (do not tear down the volume)
            raise flush_err

    # ---- maintenance ----

    def _start_loops(self) -> None:
        def loop(tick_s, fn, immediate=False):
            def body():
                if immediate:  # a restarted reader must not serve an
                    try:       # empty blocklist for a full interval
                        fn()
                    except Exception:  # noqa: BLE001
                        self.log.exception("%s maintenance", self.target)
                while not self._stop.wait(tick_s):
                    try:
                        fn()
                    except Exception:  # noqa: BLE001 — keep loops alive
                        self.log.exception("%s maintenance", self.target)
            t = threading.Thread(target=body, daemon=True)
            t.start()
            self._threads.append(t)

        if self.target == "ingester":
            loop(self.cfg.flush_tick_s, self.flush_tick)
        if self.target in ("querier", "query-frontend", "compactor"):
            loop(self.cfg.poll_tick_s, self.db.poll, immediate=True)
        if self.target == "compactor":
            loop(self.cfg.compaction_tick_s, self._compaction_tick)

    def _compaction_tick(self) -> None:
        """Ring-ownership-gated compaction (reference modules/compactor
        Owns: hash the job, own it if we lead its replica set)."""
        from tempo_tpu.utils.hashing import fnv1a_32

        ring = self.ml.ring("compactor")
        for tenant in self.db.blocklist.tenants():
            if not ring.owns(self.id, fnv1a_32(tenant.encode())):
                continue
            self.db.compact_tenant_once(tenant)
            self.db.retain_tenant(tenant)
