"""Pull-model query dispatch: frontend fairness queue → querier workers.

Role-equivalent to the reference's frontend v1 httpgrpc dispatch
(/root/reference/modules/frontend/v1/frontend.go:33-60 Process loop,
/root/reference/modules/querier/worker/frontend_processor.go:1-80):
querier WORKERS dial the frontend and pull jobs over a duplex gRPC
stream (tempopb.Frontend/Process). The querier is the gRPC CLIENT, so
job requests are the stream's responses and results its requests; one
job is in flight per stream; a querier opens `parallelism` streams.

Why pull: work distribution becomes demand-driven. A slow or loaded
querier simply pulls less; a dead one stops pulling and its in-flight
jobs are requeued to the survivors — the redistribution-on-kill the
bounded-concurrency push model (modules/microservices.py, the fallback)
can only approximate with health probes and retries.
"""

from __future__ import annotations

import itertools
import os
import queue as _queue
import socket
import threading
import time

from tempo_tpu import tempopb
from tempo_tpu.observability import get_logger
from tempo_tpu.observability.metrics import Counter, Gauge
from tempo_tpu.utils.hashing import fnv1a_32

from .queue import RequestQueue

_jobs_delivered = Counter("tempo_frontend_pull_jobs_delivered_total",
                          "results returned to waiting requests")
_jobs_requeued = Counter("tempo_frontend_pull_jobs_requeued_total",
                         "jobs redistributed off dead worker streams")
_worker_streams = Gauge("tempo_frontend_pull_worker_streams",
                        "connected querier worker streams")

SERVICE_FRONTEND = "tempopb.Frontend"
PROCESS_METHOD = f"/{SERVICE_FRONTEND}/Process"

_querier_id_seq = itertools.count(1)  # default PullWorker identities


class JobFailed(Exception):
    """A pulled job exhausted its redeliveries or the worker reported an
    execution error; the frontend's retry ware decides what happens next."""


class _Entry:
    __slots__ = ("job", "future", "tenant", "deliveries", "cancelled")

    def __init__(self, job, future, tenant):
        self.job = job
        self.future = future
        self.tenant = tenant
        self.deliveries = 0
        self.cancelled = False


class PullDispatcher:
    """Frontend side: a tenant-fair queue of ProcessJobs that connected
    worker streams drain. Jobs whose stream dies mid-flight are requeued
    (bounded redeliveries) so a killed querier's work redistributes to
    the survivors — reference frontend.go Process: a failed send/recv
    re-enqueues the request for the next worker."""

    def __init__(self, max_redeliveries: int = 3,
                 max_queued_per_tenant: int = 100_000,
                 instance: str = "default",
                 max_queriers_per_tenant: int = 0):
        # metric label: two dispatchers in one process (in-process test
        # topologies, embedded frontends) must not clobber each other's
        # gauge with last-writer-wins
        self.instance = instance
        # querier shuffle-sharding (reference queue.go cortex lineage):
        # cap how many worker streams one tenant's jobs spread over, so
        # a tenant's pathological query can't heat every querier's HBM
        # cache. 0 = off. Eligibility is rendezvous-hashed over the LIVE
        # stream set, so worker death self-heals the shard
        self.max_queriers_per_tenant = max_queriers_per_tenant
        # (epoch, distinct querier ids, stream-id → querier-id snapshot):
        # replaced wholesale under _lock on membership change, read
        # WITHOUT the lock by the accept path — which runs under the
        # queue's condition variable, where a dispatcher-lock acquire
        # would serialize all dispatch traffic. Eligibility ranks over
        # QUERIER ids (one per querier process, sent as stream metadata),
        # not stream ids, so parallelism>1 doesn't shrink a tenant's
        # shard below max_queriers_per_tenant distinct queriers — the
        # reference's per-querier shuffle-shard semantics
        # (modules/frontend/v1/frontend.go getOrCreateQueue).
        self._shard_view: tuple[int, tuple[str, ...], dict[int, str]] = (
            0, (), {})
        # tenant → (epoch, eligible frozenset); bounded
        from collections import OrderedDict
        self._shard_cache: OrderedDict[str, tuple] = OrderedDict()
        # seed the gauge at 0: the workers-missing alert matches on the
        # series EXISTING with value 0 — a never-written gauge is an
        # empty vector and the primary outage (no worker ever connected)
        # would never fire it
        _worker_streams.set(0, instance=instance)
        self._queue = RequestQueue(
            max_queued_per_tenant=max_queued_per_tenant,
            filtered_consumers=max_queriers_per_tenant > 0)
        self._pending: dict[int, _Entry] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._workers = 0
        self._worker_seq = itertools.count(1)
        self._worker_qids: dict[int, str] = {}  # stream id → querier id
        self.max_redeliveries = max_redeliveries
        self.stopped = False
        self.delivered = 0   # results handed back to waiters
        self.requeued = 0    # jobs redistributed off dead streams
        self.log = get_logger()

    # ---- frontend-facing ----

    def submit(self, tenant: str, job: tempopb.ProcessJob):
        """Enqueue one job; returns a concurrent.futures.Future resolving
        to the worker's ProcessResult (or raising JobFailed)."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()
        job.job_id = next(self._ids)
        job.tenant_id = tenant
        entry = _Entry(job, fut, tenant)
        with self._lock:
            self._pending[job.job_id] = entry
        try:
            self._queue.enqueue(tenant, entry)
        except Exception:
            with self._lock:
                self._pending.pop(job.job_id, None)
            raise
        return fut

    def abandon(self, job_id: int) -> None:
        """Caller gave up waiting (timeout): drop the pending entry and
        mark it cancelled so a queued copy is skipped, not executed."""
        with self._lock:
            entry = self._pending.pop(job_id, None)
            if entry is not None:
                entry.cancelled = True

    def workers(self) -> int:
        with self._lock:
            return self._workers

    def queued(self) -> int:
        return sum(self._queue.lengths().values())

    def stop(self) -> None:
        self.stopped = True
        self._queue.stop()

    # ---- stream-servicer-facing ----

    def register_worker(self, querier_id: str | None = None) -> int:
        """querier_id identifies the querier PROCESS (stream metadata);
        all of its streams shard as one unit. Absent (old clients), each
        stream counts as its own querier — the pre-metadata behavior."""
        with self._lock:
            self._workers += 1
            wid = next(self._worker_seq)
            self._worker_qids[wid] = querier_id or f"stream-{wid}"
            self._update_shard_view()
            _worker_streams.set(self._workers, instance=self.instance)
            return wid

    def unregister_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers -= 1
            self._worker_qids.pop(worker_id, None)
            self._update_shard_view()
            _worker_streams.set(self._workers, instance=self.instance)
        if self.max_queriers_per_tenant > 0:
            # survivors inherit the dead worker's tenants NOW: blocked
            # consumers must re-evaluate eligibility, not wait out their
            # poll timeout on already-queued jobs
            self._queue.kick()

    def _update_shard_view(self) -> None:  # callers hold self._lock
        self._shard_view = (self._shard_view[0] + 1,
                            tuple(sorted(set(self._worker_qids.values()))),
                            dict(self._worker_qids))

    def eligible(self, tenant: str, worker_id: int) -> bool:
        """Querier shuffle-shard: is this stream's QUERIER in the
        tenant's top-S rendezvous set over the live querier processes?
        With sharding off, fewer queriers than S, or an unknown id,
        everyone is eligible. Cached per tenant against the membership
        epoch, and lock-free on the hot path (this runs inside the
        queue's condition variable)."""
        s = self.max_queriers_per_tenant
        if s <= 0:
            return True
        epoch, qids, wid_map = self._shard_view  # atomic tuple read
        qid = wid_map.get(worker_id)
        if qid is None or len(qids) <= s:
            return True
        hit = self._shard_cache.get(tenant)
        if hit is not None and hit[0] == epoch:
            return qid in hit[1]
        ranked = sorted(qids, key=lambda q: fnv1a_32(f"{tenant}/{q}".encode()))
        shard = frozenset(ranked[:s])
        self._shard_cache[tenant] = (epoch, shard)
        while len(self._shard_cache) > 4096:
            self._shard_cache.popitem(last=False)
        return qid in shard

    def next_job(self, timeout: float | None = None,
                 worker_id: int | None = None):
        """Next live entry, tenant-fair; None on timeout/stop. Cancelled
        entries (abandoned by their waiter) are skipped silently; with
        shuffle-sharding on, a worker only drains eligible tenants."""
        accept = None
        if self.max_queriers_per_tenant > 0 and worker_id is not None:
            accept = lambda t: self.eligible(t, worker_id)  # noqa: E731
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            left = None if deadline is None else max(
                0.0, deadline - time.monotonic())
            item = self._queue.get(timeout=left, accept=accept)
            if item is None:
                return None
            _tenant, entry = item
            if entry.cancelled:
                continue
            entry.deliveries += 1
            return entry

    def requeue(self, entry: _Entry) -> None:
        """Stream died holding this job: hand it to the next worker, or
        fail it once the redelivery budget is spent."""
        if entry.cancelled:
            return
        if entry.deliveries > self.max_redeliveries:
            self._fail(entry, JobFailed(
                f"job {entry.job.job_id} ({entry.job.kind}) failed after "
                f"{entry.deliveries} deliveries"))
            return
        try:
            self._queue.enqueue(entry.tenant, entry)
            self.requeued += 1
            _jobs_requeued.inc(instance=self.instance)
        except Exception as e:  # noqa: BLE001 — queue stopped/full
            self._fail(entry, e)

    def deliver(self, result: tempopb.ProcessResult) -> None:
        with self._lock:
            entry = self._pending.pop(result.job_id, None)
        if entry is None:
            return  # abandoned by its waiter, or duplicate delivery
        self.delivered += 1
        _jobs_delivered.inc(instance=self.instance)
        if result.error:
            entry.future.set_exception(JobFailed(result.error))
        else:
            entry.future.set_result(result)

    def _fail(self, entry: _Entry, exc: BaseException) -> None:
        with self._lock:
            self._pending.pop(entry.job.job_id, None)
        if not entry.future.done():
            entry.future.set_exception(exc)


def make_frontend_pull_handler(dispatcher: PullDispatcher):
    """Generic gRPC handler for tempopb.Frontend/Process. The servicer is
    the frontend.go:33-60 loop inverted into a response generator: pop a
    job from the fair queue, yield it down the stream, block on the
    worker's result, deliver. Any stream death between yield and recv —
    GeneratorExit on client disconnect, StopIteration on half-close —
    requeues the in-flight job."""
    import grpc

    def process(request_iterator, context):
        md = dict(context.invocation_metadata() or ())
        wid = dispatcher.register_worker(md.get("querier-id"))
        entry = None
        try:
            while True:
                entry = dispatcher.next_job(timeout=0.5, worker_id=wid)
                if entry is None:
                    if dispatcher.stopped or not context.is_active():
                        return
                    continue
                yield entry.job
                try:
                    result = next(request_iterator)
                except StopIteration:
                    return  # client half-closed; finally requeues
                except Exception:  # noqa: BLE001 — stream torn down
                    return
                dispatcher.deliver(result)
                entry = None
        finally:
            if entry is not None:
                dispatcher.requeue(entry)
            dispatcher.unregister_worker(wid)

    handler = grpc.stream_stream_rpc_method_handler(
        process,
        request_deserializer=tempopb.ProcessResult.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )
    return grpc.method_handlers_generic_handler(
        SERVICE_FRONTEND, {"Process": handler})


# ---------------------------------------------------------------------------
# frontend-side querier facade


class PullQuerierStub:
    """Duck-types the Querier interface the frontend dispatches to
    (api/grpc_service.QuerierClient): each call becomes a ProcessJob in
    the dispatcher's queue, answered by whichever worker pulls it."""

    def __init__(self, dispatcher: PullDispatcher,
                 job_timeout_s: float | None = 120.0):
        """job_timeout_s guards against a hung worker holding a LIVE
        stream (a dead stream requeues instantly). It must comfortably
        exceed a cold query's staging + XLA-compile cost (~30s+), or the
        retry ware duplicates exactly the slow jobs."""
        self.dispatcher = dispatcher
        self.job_timeout_s = job_timeout_s

    def _dispatch(self, tenant: str, job: tempopb.ProcessJob):
        import concurrent.futures

        fut = self.dispatcher.submit(tenant, job)
        try:
            return fut.result(timeout=self.job_timeout_s)
        except (TimeoutError, concurrent.futures.TimeoutError):
            self.dispatcher.abandon(job.job_id)
            raise

    def find_trace_by_id(self, tenant, trace_id, block_start="", block_end="",
                         mode="all") -> tempopb.TraceByIDResponse:
        job = tempopb.ProcessJob(kind="trace_by_id")
        job.trace_by_id.trace_id = trace_id
        job.trace_by_id.block_start = block_start
        job.trace_by_id.block_end = block_end
        job.trace_by_id.query_mode = mode
        return self._dispatch(tenant, job).trace

    def search_recent(self, tenant, req) -> tempopb.SearchResponse:
        job = tempopb.ProcessJob(kind="search_recent")
        job.search_recent.CopyFrom(req)
        return self._dispatch(tenant, job).search

    def search_blocks(self, req: tempopb.SearchBlocksRequest) -> tempopb.SearchResponse:
        job = tempopb.ProcessJob(kind="search_blocks")
        job.search_blocks.CopyFrom(req)
        return self._dispatch(req.tenant_id, job).search

    def search_block(self, req: tempopb.SearchBlockRequest) -> tempopb.SearchResponse:
        # singular job rides the batched kind with one entry
        breq = tempopb.SearchBlocksRequest()
        breq.search_req.CopyFrom(req.search_req)
        breq.tenant_id = req.tenant_id
        j = breq.jobs.add()
        j.block_id = req.block_id
        j.start_page = req.start_page
        j.pages_to_search = req.pages_to_search
        j.encoding = req.encoding
        j.version = req.version
        j.data_encoding = req.data_encoding
        j.start_time = req.start_time
        j.end_time = req.end_time
        return self.search_blocks(breq)

    def search_tags(self, tenant) -> tempopb.SearchTagsResponse:
        job = tempopb.ProcessJob(kind="search_tags")
        return self._dispatch(tenant, job).tags

    def search_tag_values(self, tenant, tag) -> tempopb.SearchTagValuesResponse:
        job = tempopb.ProcessJob(kind="search_tag_values")
        job.search_tag_values.tag_name = tag
        return self._dispatch(tenant, job).tag_values


class PullQuerierPool:
    """List-ish pool the frontend indexes round-robin. With worker streams
    connected every index resolves to the pull stub (demand-driven — the
    index is meaningless on purpose); with none it falls back to the
    direct push clients so a frontend that lost all its workers degrades
    instead of queueing into the void."""

    def __init__(self, dispatcher: PullDispatcher, fallback=None,
                 job_timeout_s: float | None = 120.0):
        self.dispatcher = dispatcher
        self.fallback = fallback
        self._stub = PullQuerierStub(dispatcher, job_timeout_s=job_timeout_s)

    def _pull_mode(self) -> bool:
        if self.dispatcher.workers() > 0:
            return True
        return self.fallback is None or len(self.fallback) == 0

    def __getitem__(self, i):
        if not self._pull_mode():
            try:
                # ClientList mods internally; a raw list needs the mod
                # here because the worker count backing len() can change
                # between the caller's len() and this index (TOCTOU on
                # querier restart must degrade, not IndexError a search)
                fb = self.fallback
                n = len(fb)
                if n:
                    return fb[i % n]
            except Exception:  # noqa: BLE001 — fallback shrank to empty
                pass
        return self._stub

    def __len__(self):
        """Never 0: the frontend round-robins with `rr % len(pool)`, and
        in pull-degraded mode (no workers, no push clients) indexing must
        still resolve to the stub — whose queued jobs time out — rather
        than crash the query with a modulo-by-zero."""
        w = self.dispatcher.workers()
        if w > 0:
            return w
        if self.fallback is not None and len(self.fallback) > 0:
            return len(self.fallback)
        return 1

    def stable_len(self) -> int:
        """Dispatch width for job-batch sizing and its memo key. The live
        stream count (len) flaps on every worker connect/disconnect —
        keying a 10K-job template cache on it would churn the cache
        through every rollout — so batch geometry uses the QUERIER
        process count from membership (the push-client list), which only
        moves on actual scale events."""
        if self.fallback is not None and len(self.fallback) > 0:
            return len(self.fallback)
        w = self.dispatcher.workers()
        return w if w > 0 else 1


# ---------------------------------------------------------------------------
# querier-side worker


class PullWorker:
    """Querier side: `parallelism` client streams against one frontend.
    Each stream receives ProcessJobs, executes them against the local
    Querier, and sends ProcessResults back — frontend_processor.go's
    processQueries/runOneRequest loop. Streams reconnect with backoff so
    a restarted frontend gets its workers back without operator action."""

    def __init__(self, querier, frontend_address: str, parallelism: int = 2,
                 reconnect_backoff_s: float = 1.0,
                 querier_id: str | None = None):
        self.querier = querier
        self.address = frontend_address
        self.backoff_s = reconnect_backoff_s
        # one id per querier PROCESS (shared by all this worker's streams)
        # so the frontend's shuffle-shard counts queriers, not streams;
        # standalone PullWorkers (no manager) default to a unique id each
        self.querier_id = querier_id or (
            f"{socket.gethostname()}-{os.getpid()}-{next(_querier_id_seq)}")
        self._stop = threading.Event()
        self._threads = []
        self._calls_lock = threading.Lock()
        self._calls: set = set()
        self.log = get_logger()
        for i in range(max(1, parallelism)):
            t = threading.Thread(target=self._stream_loop, daemon=True,
                                 name=f"pull-worker-{frontend_address}-{i}")
            t.start()
            self._threads.append(t)

    def _stream_loop(self) -> None:
        import grpc

        warned = False  # one warning per outage, not one per second
        while not self._stop.is_set():
            send_q: _queue.SimpleQueue = _queue.SimpleQueue()
            channel = grpc.insecure_channel(self.address)
            call = None
            try:
                rpc = channel.stream_stream(
                    PROCESS_METHOD,
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=tempopb.ProcessJob.FromString,
                )

                def req_iter():
                    while True:
                        item = send_q.get()
                        if item is None:
                            return
                        yield item

                call = rpc(req_iter(),
                           metadata=(("querier-id", self.querier_id),))
                with self._calls_lock:
                    if self._stop.is_set():
                        call.cancel()
                        return
                    self._calls.add(call)
                for job in call:
                    warned = False  # stream is live again
                    if self._stop.is_set():
                        # orderly stop mid-stream: drop the job WITHOUT
                        # replying so the frontend requeues it elsewhere
                        call.cancel()
                        break
                    send_q.put(self._execute(job))
            except Exception as e:  # noqa: BLE001 — reconnect with backoff
                if not warned and not self._stop.is_set():
                    self.log.warning(
                        "pull worker: frontend %s stream failed (%s); "
                        "reconnecting every %.1fs", self.address,
                        getattr(e, "details", lambda: e)(), self.backoff_s)
                    warned = True
            finally:
                send_q.put(None)
                if call is not None:
                    call.cancel()
                    with self._calls_lock:
                        self._calls.discard(call)
                channel.close()
            if not self._stop.is_set():
                self._stop.wait(self.backoff_s)

    def _execute(self, job: tempopb.ProcessJob) -> tempopb.ProcessResult:
        res = tempopb.ProcessResult(job_id=job.job_id)
        q = self.querier
        try:
            if job.kind == "trace_by_id":
                r = q.find_trace_by_id(
                    job.tenant_id, job.trace_by_id.trace_id,
                    block_start=job.trace_by_id.block_start,
                    block_end=job.trace_by_id.block_end,
                    mode=job.trace_by_id.query_mode or "all")
                res.trace.CopyFrom(r)
            elif job.kind == "search_blocks":
                res.search.CopyFrom(q.search_blocks(job.search_blocks))
            elif job.kind == "search_recent":
                res.search.CopyFrom(
                    q.search_recent(job.tenant_id, job.search_recent))
            elif job.kind == "search_tags":
                res.tags.CopyFrom(q.search_tags(job.tenant_id))
            elif job.kind == "search_tag_values":
                res.tag_values.CopyFrom(q.search_tag_values(
                    job.tenant_id, job.search_tag_values.tag_name))
            else:
                res.error = f"unknown job kind {job.kind!r}"
        except Exception as e:  # noqa: BLE001 — travels as result.error
            res.error = f"{type(e).__name__}: {e}"
        return res

    def stop(self) -> None:
        """Cancel the streams; jobs in flight on them are requeued by the
        frontend servicer (the kill path the redistribution test kills)."""
        self._stop.set()
        with self._calls_lock:
            for call in list(self._calls):
                call.cancel()


class PullWorkerManager:
    """Maintains one PullWorker per discovered query-frontend: watches
    gossip membership (role `query-frontend`) and dials/retires workers
    as frontends come and go — the reference's worker DNS watcher
    (querier/worker/worker.go AddressAdded/AddressRemoved) on top of our
    membership layer instead of DNS."""

    def __init__(self, querier, memberlist, parallelism: int = 2,
                 refresh_s: float = 1.0):
        self.querier = querier
        self.ml = memberlist
        self.parallelism = parallelism
        # one identity for this querier process, shared across every
        # frontend's PullWorker — the unit the shuffle-shard counts
        self.querier_id = (f"{socket.gethostname()}-{os.getpid()}-"
                           f"{next(_querier_id_seq)}")
        self._workers: dict[str, PullWorker] = {}
        self._stop = threading.Event()
        # serializes refresh() against stop() so a refresh racing the
        # shutdown can't insert a worker after stop()'s sweep — that
        # worker would reconnect forever against a torn-down querier
        self._lock = threading.Lock()
        self._refresh_s = refresh_s
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pull-worker-manager")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._refresh_s):
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 — keep watching
                pass

    def refresh(self) -> None:
        want = {m.grpc_addr for m in self.ml.members("query-frontend")
                if m.grpc_addr}
        with self._lock:
            if self._stop.is_set():
                return
            for addr in list(self._workers):
                if addr not in want:
                    self._workers.pop(addr).stop()
            for addr in want:
                if addr not in self._workers:
                    self._workers[addr] = PullWorker(
                        self.querier, addr, parallelism=self.parallelism,
                        querier_id=self.querier_id)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            for w in self._workers.values():
                w.stop()
            self._workers.clear()
