"""Gossip membership: ring state replication for multi-process deployments.

Role-equivalent to the reference's memberlist gossip KV (SURVEY.md §2.6:
ring state replicated by gossip; cmd/tempo/app/app.go:99-111) — a
memberlist-lite push-pull protocol over TCP:

  - each member owns its record {id, role, addresses, heartbeat counter,
    state} and increments the counter every gossip tick;
  - every tick it exchanges full state with a few random peers (push-pull
    anti-entropy): send my map, receive theirs, both merge;
  - merge keeps the record with the higher heartbeat counter; LEFT beats
    ACTIVE at the same-or-higher counter (deregistration wins);
  - receive time is stamped locally, so each node judges liveness from its
    own clock — no cross-host clock sync needed (the same reason
    memberlist gossips counters, not timestamps).

Per-role consistent-hash `Ring`s are derived views of the member map:
ingester writes, compactor job ownership, querier discovery all read the
same gossip state, like the reference's single memberlist KV shared by
all rings. Token sets are deterministic from the instance id (Ring.
register seeds its RNG with the id), so tokens never travel the wire.
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import threading
import time
from dataclasses import dataclass, asdict, field

from tempo_tpu.observability import Counter, get_logger
from .ring import Ring

STATE_ACTIVE = "ACTIVE"
STATE_LEFT = "LEFT"

_gossip_rounds = Counter("tempo_memberlist_gossip_rounds_total",
                         "push-pull exchanges initiated")
_gossip_errors = Counter("tempo_memberlist_gossip_errors_total",
                         "failed exchanges (peer treated as suspect)")


@dataclass
class Member:
    id: str
    role: str            # ingester | distributor | querier | query-frontend | compactor | ...
    gossip_addr: str     # host:port of the member's gossip listener
    grpc_addr: str = ""  # host:port of its gRPC server ("" if none)
    http_addr: str = ""
    heartbeat: int = 0   # owner-incremented incarnation counter
    state: str = STATE_ACTIVE
    # local-only: when this node last saw the counter advance (monotonic)
    local_seen: float = field(default=0.0, compare=False)

    def wire(self) -> dict:
        d = asdict(self)
        d.pop("local_seen")
        return d


class Memberlist:
    """One gossip node. Thread-safe; all background threads are daemons."""

    def __init__(self, instance_id: str, role: str, *,
                 bind: str = "127.0.0.1:0", advertise_host: str = "",
                 join: list[str] | None = None,
                 grpc_addr: str = "", http_addr: str = "",
                 gossip_interval_s: float = 1.0, fanout: int = 3,
                 suspect_timeout_s: float = 15.0,
                 replication_factor: int = 3,
                 resolver=None):
        self.id = instance_id
        self.role = role
        # join entries may be plain host:port or thanos-style dns+ /
        # dnssrv+ specs (utils/dns.py), re-resolved every gossip round;
        # malformed specs fail here, not silently per-tick
        from tempo_tpu.utils.dns import validate_spec

        self.join_addrs = list(join or [])
        for spec in self.join_addrs:
            validate_spec(spec)
        self._resolver = resolver
        self._seed_warn_at = 0.0
        self.gossip_interval_s = gossip_interval_s
        self.fanout = fanout
        self.suspect_timeout_s = suspect_timeout_s
        self.rf = replication_factor
        self.log = get_logger()

        self._lock = threading.Lock()
        self._rings: dict[str, Ring] = {}
        self._stop = threading.Event()

        host, _, port = bind.rpartition(":")
        self._server = socketserver.ThreadingTCPServer(
            (host or "127.0.0.1", int(port or 0)), _Handler)
        self._server.daemon_threads = True
        self._server.allow_reuse_address = True
        self._server.memberlist = self
        bound = self._server.server_address
        self.gossip_addr = f"{advertise_host or bound[0]}:{bound[1]}"

        me = Member(id=self.id, role=role, gossip_addr=self.gossip_addr,
                    grpc_addr=grpc_addr, http_addr=http_addr,
                    heartbeat=1, state=STATE_ACTIVE,
                    local_seen=time.monotonic())
        self._members: dict[str, Member] = {self.id: me}
        self._ring_for(role).register(self.id)

        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def set_grpc_addr(self, grpc_addr: str) -> None:
        """Update this member's advertised gRPC address after the fact —
        the ephemeral-port flow (grpc_port=0) only knows the real port
        once the server has bound. Heartbeat bumps so peers that already
        merged the address-less record take the update on the next
        exchange (merge keeps the higher counter)."""
        with self._lock:
            me = self._members.get(self.id)
            if me is not None:
                me.grpc_addr = grpc_addr
                me.heartbeat += 1

    # ---- views ----

    def ring(self, role: str) -> Ring:
        with self._lock:
            return self._ring_for(role)

    def _ring_for(self, role: str) -> Ring:
        ring = self._rings.get(role)
        if ring is None:
            ring = self._rings[role] = Ring(replication_factor=self.rf)
        return ring

    def members(self, role: str | None = None,
                alive_only: bool = True) -> list[Member]:
        now = time.monotonic()
        with self._lock:
            out = []
            for m in self._members.values():
                if role is not None and m.role != role:
                    continue
                if alive_only and not self._alive(m, now):
                    continue
                out.append(m)
            return sorted(out, key=lambda m: m.id)

    def _alive(self, m: Member, now: float) -> bool:
        if m.state != STATE_ACTIVE:
            return False
        if m.id == self.id:
            return True
        return now - m.local_seen < self.suspect_timeout_s

    # ---- state exchange ----

    def _snapshot(self) -> dict:
        with self._lock:
            return {"from": self.id,
                    "members": {m.id: m.wire() for m in self._members.values()}}

    def merge(self, remote: dict) -> None:
        # defensive against a hostile/broken peer: a malformed snapshot
        # must be IGNORED, not raise — an escaped TypeError here would
        # kill the gossip tick thread and silently mute this node
        if not isinstance(remote, dict):
            return
        members = remote.get("members")
        if not isinstance(members, dict):
            return
        now = time.monotonic()
        with self._lock:
            for mid, rec in members.items():
                if mid == self.id:
                    # someone else's view of me: only LEFT at a higher
                    # counter matters (refute by outliving it — we bump our
                    # own counter every tick)
                    continue
                if not isinstance(mid, str) or not isinstance(rec, dict):
                    continue
                known = self._members.get(mid)
                try:
                    rm = Member(**{k: v for k, v in rec.items()
                                   if k in Member.__dataclass_fields__})
                    # id must be a str, MATCH its map key, and not forge
                    # our own identity under a different key — snapshots
                    # re-key by m.id, so a forged id would overwrite our
                    # self-record in every outgoing snapshot (e.g. a
                    # hostile LEFT@999 evicting us cluster-wide)
                    if rm.id != mid or rm.id == self.id:
                        continue
                    rm.heartbeat = int(rm.heartbeat)
                    if rm.state not in (STATE_ACTIVE, STATE_LEFT):
                        continue  # unknown states could never expire
                    if not all(isinstance(v, str) for v in (
                            rm.role, rm.gossip_addr, rm.grpc_addr,
                            rm.http_addr)):
                        continue  # poisoned addrs reach client factories
                except (TypeError, ValueError, OverflowError):
                    # OverflowError: json Infinity → int(float('inf'))
                    continue  # type-poisoned record: skip it, keep the rest
                if known is None:
                    rm.local_seen = now
                    self._members[mid] = rm
                    if rm.state == STATE_ACTIVE:
                        ring = self._ring_for(rm.role)
                        ring.register(mid)
                        ring.heartbeat(mid)
                    continue
                if rm.heartbeat > known.heartbeat or (
                        rm.state == STATE_LEFT
                        and rm.heartbeat >= known.heartbeat
                        and known.state != STATE_LEFT):
                    was = known.state
                    known.heartbeat = rm.heartbeat
                    known.state = rm.state
                    known.grpc_addr = rm.grpc_addr
                    known.http_addr = rm.http_addr
                    known.gossip_addr = rm.gossip_addr
                    known.local_seen = now
                    ring = self._ring_for(known.role)
                    if known.state == STATE_LEFT and was == STATE_ACTIVE:
                        ring.leave(mid)
                    elif known.state == STATE_ACTIVE:
                        # re-register revived members too: tick()'s suspect
                        # expiry removes them from the ring while their
                        # gossip state stays ACTIVE, so `was` alone can't
                        # tell a revival from a steady heartbeat
                        if was != STATE_ACTIVE or mid not in ring:
                            ring.register(mid)
                        ring.heartbeat(mid)

    def _exchange(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        payload = (json.dumps(self._snapshot()) + "\n").encode()
        with socket.create_connection((host, int(port)), timeout=3) as s:
            s.sendall(payload)
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(1 << 20)
                if not chunk:
                    break
                buf += chunk
        if buf:
            self.merge(json.loads(buf))

    # ---- loops ----

    def _loop(self) -> None:
        while not self._stop.wait(self.gossip_interval_s):
            self.tick()

    def tick(self) -> None:
        """One gossip round (public for deterministic tests)."""
        with self._lock:
            me = self._members[self.id]
            me.heartbeat += 1
            me.local_seen = time.monotonic()
            self._ring_for(self.role).heartbeat(self.id)
            # expire suspects from the rings (they stay in the member map
            # so a revived node re-merges cleanly)
            now = time.monotonic()
            for m in self._members.values():
                if m.id != self.id and m.state == STATE_ACTIVE \
                        and now - m.local_seen >= self.suspect_timeout_s:
                    self._ring_for(m.role).leave(m.id)
            peers = [m.gossip_addr for m in self._members.values()
                     if m.id != self.id and m.state == STATE_ACTIVE]
        targets = random.sample(peers, min(self.fanout, len(peers)))
        # seeds we haven't absorbed yet (bootstrap)
        with self._lock:
            known_addrs = {m.gossip_addr for m in self._members.values()}
        targets += [a for a in self._resolved_seeds()
                    if a not in known_addrs and a != self.gossip_addr][:2]
        for addr in targets:
            _gossip_rounds.inc()
            try:
                self._exchange(addr)
            except (OSError, ValueError):
                # ValueError covers JSONDecodeError + UnicodeDecodeError
                _gossip_errors.inc()

    def _resolved_seeds(self) -> list[str]:
        """join_addrs with dns+/dnssrv+ specs expanded (cached per-TTL in
        the resolver; plain host:port entries pass through untouched)."""
        if not any(a.startswith(("dns+", "dnssrv+")) for a in self.join_addrs):
            return self.join_addrs
        if self._resolver is None:
            from tempo_tpu.utils.dns import default_resolver

            self._resolver = default_resolver()
        resolved = self._resolver.resolve_all(self.join_addrs)
        if not resolved:
            now = time.monotonic()
            if now - self._seed_warn_at > 60:
                self._seed_warn_at = now
                self.log.warning(
                    "memberlist: no join seeds resolved from %s (DNS down "
                    "or empty records) — gossiping to known peers only",
                    self.join_addrs,
                )
        return resolved

    # ---- lifecycle ----

    def leave(self) -> None:
        """Graceful deregistration: mark LEFT and gossip it out."""
        with self._lock:
            me = self._members[self.id]
            me.state = STATE_LEFT
            me.heartbeat += 1
            self._ring_for(self.role).leave(self.id)
            peers = [m.gossip_addr for m in self._members.values()
                     if m.id != self.id and m.state == STATE_ACTIVE]
        for addr in peers[:self.fanout]:
            try:
                self._exchange(addr)
            except (OSError, ValueError):
                pass
        self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline(16 << 20)
            if not line:
                return
            remote = json.loads(line)
            ml: Memberlist = self.server.memberlist
            ml.merge(remote)
            self.wfile.write((json.dumps(ml._snapshot()) + "\n").encode())
        except (OSError, json.JSONDecodeError, ValueError):
            pass
