"""Per-tenant fair request queue + keyed-exclusive flush queues.

Role-equivalent to the reference's pkg/scheduler/queue (frontend v1
per-tenant FIFO fairness with max-outstanding 429s, user_queues.go) and
pkg/flushqueues (priority queues that dedupe in-flight ops,
exclusivequeues.go:10-83).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from collections import OrderedDict, deque


class TooManyRequests(Exception):
    """Queue full for tenant (reference: HTTP 429)."""


class RequestQueue:
    """Round-robin across tenants, FIFO within a tenant. `get` blocks until
    a request is available or the queue stops."""

    def __init__(self, max_outstanding_per_tenant: int = 2000):
        self.max_outstanding = max_outstanding_per_tenant
        self._queues: OrderedDict[str, deque] = OrderedDict()
        self._cv = threading.Condition()
        self._stopped = False

    def enqueue(self, tenant: str, request) -> None:
        with self._cv:
            if self._stopped:
                raise RuntimeError("queue stopped")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
            if len(q) >= self.max_outstanding:
                raise TooManyRequests(tenant)
            q.append(request)
            self._cv.notify()

    def get(self, timeout: float | None = None):
        """(tenant, request) or None on stop/timeout. Tenants are served
        round-robin: the tenant we serve moves to the back."""
        with self._cv:
            while True:
                for tenant in list(self._queues):
                    q = self._queues[tenant]
                    if q:
                        req = q.popleft()
                        self._queues.move_to_end(tenant)
                        if not q:
                            del self._queues[tenant]
                        return tenant, req
                if self._stopped:
                    return None
                if not self._cv.wait(timeout):
                    return None

    def lengths(self) -> dict[str, int]:
        with self._cv:
            return {t: len(q) for t, q in self._queues.items()}

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


class ExclusiveQueue:
    """Priority queue that refuses duplicate keys while an op is queued or
    in flight — the ingester flush-op dedupe (reference flushqueues)."""

    def __init__(self):
        self._heap: list = []
        self._keys: set = set()
        self._lock = threading.Lock()
        self._counter = itertools.count()

    def enqueue(self, key, priority: float, item) -> bool:
        """False if the key is already queued/in-flight."""
        with self._lock:
            if key in self._keys:
                return False
            self._keys.add(key)
            heapq.heappush(self._heap, (priority, next(self._counter), key, item))
            return True

    def dequeue(self):
        """(key, item) or None. The key stays claimed until done(key)."""
        with self._lock:
            if not self._heap:
                return None
            _, _, key, item = heapq.heappop(self._heap)
            return key, item

    def done(self, key) -> None:
        """Release the key so it can be re-enqueued (e.g. retry after
        backoff)."""
        with self._lock:
            self._keys.discard(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)
