"""Per-tenant fair request queue + keyed-exclusive flush queues.

Role-equivalent to the reference's pkg/scheduler/queue (frontend v1
per-tenant FIFO fairness with max-outstanding 429s, user_queues.go) and
pkg/flushqueues (priority queues that dedupe in-flight ops,
exclusivequeues.go:10-83).
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import heapq
import itertools
import threading
import time
from collections import OrderedDict, deque


class TooManyRequests(Exception):
    """Queue full for tenant (reference: HTTP 429)."""


class RequestQueue:
    """Round-robin across tenants, FIFO within a tenant. `get` blocks until
    a request is available or the queue stops.

    The outstanding cap counts top-level REQUESTS (begin_request /
    end_request brackets). This is a DELIBERATE divergence from the
    reference v1 queue, whose MaxOutstandingPerTenant bounds queued
    queue ITEMS — each sharded sub-request individually, which is why
    Tempo's default is as high as 2000 (v1/frontend.go:46-48). Counting
    sub-requests here would make any single search whose own fan-out
    exceeds the cap deterministically 429 itself even on an idle
    system; counting whole requests keeps admission meaningful, so the
    default is 64 concurrent requests per tenant (each fanning out to
    hundreds of sub-requests), with max_queued_per_tenant as the
    complementary memory bound on total queued sub-requests."""

    def __init__(self, max_outstanding_per_tenant: int = 64,
                 max_queued_per_tenant: int = 100_000,
                 filtered_consumers: bool = False):
        self.max_outstanding = max_outstanding_per_tenant
        # memory backpressure, complementary to the request cap: many
        # outstanding requests × many sub-requests each must not grow the
        # queue without bound
        self.max_queued = max_queued_per_tenant
        # filtered_consumers: consumers pass accept predicates (querier
        # shuffle-shard) — a single notify could land on an ineligible
        # consumer and strand the item, so enqueue must wake everyone.
        # Without filters, single notify keeps the hot path O(1)
        self._filtered = filtered_consumers
        self._queues: OrderedDict[str, deque] = OrderedDict()
        self._outstanding: dict[str, int] = {}
        self._cv = threading.Condition()
        self._stopped = False

    def begin_request(self, tenant: str) -> None:
        """Claim an outstanding-request slot; raises TooManyRequests when
        the tenant is at its cap."""
        with self._cv:
            if self._outstanding.get(tenant, 0) >= self.max_outstanding:
                raise TooManyRequests(tenant)
            self._outstanding[tenant] = self._outstanding.get(tenant, 0) + 1

    def end_request(self, tenant: str) -> None:
        with self._cv:
            n = self._outstanding.get(tenant, 1) - 1
            if n > 0:
                self._outstanding[tenant] = n
            else:
                self._outstanding.pop(tenant, None)

    def outstanding(self, tenant: str) -> int:
        with self._cv:
            return self._outstanding.get(tenant, 0)

    def enqueue(self, tenant: str, request) -> None:
        with self._cv:
            if self._stopped:
                raise RuntimeError("queue stopped")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
            if len(q) >= self.max_queued:
                raise TooManyRequests(f"{tenant}: sub-request queue full")
            q.append(request)
            if self._filtered:
                self._cv.notify_all()
            else:
                self._cv.notify()

    def get(self, timeout: float | None = None, accept=None):
        """(tenant, request) or None on stop/timeout. Tenants are served
        round-robin: the tenant we serve moves to the back. `accept` is
        an optional tenant predicate — the pull dispatcher's querier
        shuffle-sharding (a worker only drains tenants it is eligible
        for); ineligible tenants stay queued for an eligible consumer."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                for tenant in list(self._queues):
                    if accept is not None and not accept(tenant):
                        continue
                    q = self._queues[tenant]
                    if q:
                        req = q.popleft()
                        self._queues.move_to_end(tenant)
                        if not q:
                            del self._queues[tenant]
                        return tenant, req
                if self._stopped:
                    return None
                # absolute deadline, not a fresh window per wakeup: with
                # filtered consumers every enqueue wakes everyone, and a
                # per-wait timeout would never elapse under steady
                # traffic — the caller's poll loop (and its
                # is-stream-alive check) must run on schedule
                if deadline is None:
                    self._cv.wait()
                    continue
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                self._cv.wait(left)

    def lengths(self) -> dict[str, int]:
        with self._cv:
            return {t: len(q) for t, q in self._queues.items()}

    def kick(self) -> None:
        """Wake every blocked consumer so accept predicates re-evaluate —
        called when ELIGIBILITY changed without an enqueue (a worker
        died and survivors inherited its tenants)."""
        with self._cv:
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


class QueueWorkerPool:
    """N workers draining a RequestQueue — the in-process collapse of the
    reference's frontend-v1 fair queue + querier worker fleet
    (v1/frontend.go:33-60, querier/worker): every frontend sub-request
    enqueues under its tenant, workers serve tenants round-robin so a
    noisy tenant cannot starve the rest, and a tenant at its
    outstanding-REQUEST cap (or the sub-request memory bound) is
    rejected with TooManyRequests (HTTP 429)."""

    def __init__(self, workers: int = 50,
                 max_outstanding_per_tenant: int = 64,
                 max_queued_per_tenant: int = 100_000):
        self.queue = RequestQueue(max_outstanding_per_tenant,
                                  max_queued_per_tenant)
        self._n = max(1, workers)
        self._threads: list[threading.Thread] = []
        self._start_lock = threading.Lock()

    def _ensure_started(self) -> None:
        with self._start_lock:
            if self._threads:
                return
            for i in range(self._n):
                t = threading.Thread(target=self._worker, daemon=True,
                                     name=f"query-worker-{i}")
                t.start()
                self._threads.append(t)

    def _worker(self) -> None:
        while True:
            item = self.queue.get()
            if item is None:
                return  # stopped
            _tenant, (fut, fn, ctx, stop_event) = item
            if not fut.set_running_or_notify_cancel():
                continue
            if stop_event is not None and stop_event.is_set():
                fut.set_result(None)  # request already satisfied (early quit)
                continue
            try:
                fut.set_result(ctx.copy().run(fn))
            except BaseException as e:  # noqa: BLE001 — delivered via future
                fut.set_exception(e)

    def submit(self, tenant: str, fn, stop_event=None,
               ctx: contextvars.Context | None = None) -> concurrent.futures.Future:
        """Enqueue one sub-request. Admission control is request-level
        (begin_request, used by run_jobs); this only rejects —
        TooManyRequests — at the sub-request memory bound."""
        self._ensure_started()
        fut: concurrent.futures.Future = concurrent.futures.Future()
        ctx = ctx if ctx is not None else contextvars.copy_context()
        self.queue.enqueue(tenant, (fut, fn, ctx, stop_event))
        return fut

    def run_jobs(self, tenant: str, jobs, fn, stop_event=None):
        """Fan `jobs` through the fair queue as ONE outstanding request
        and gather like db.pool run_jobs: (non-None results, errors). A
        tenant at max_outstanding REQUESTS fails whole with
        TooManyRequests (HTTP 429), before any sub-request enqueues.
        Jobs run under a copy of the caller's contextvars context so the
        active tracing span parents the per-job spans."""
        self.queue.begin_request(tenant)  # raises TooManyRequests at cap
        try:
            ctx = contextvars.copy_context()
            futs = []
            try:
                for j in jobs:
                    futs.append(self.submit(
                        tenant, (lambda j=j: fn(j)),
                        stop_event=stop_event, ctx=ctx))
            except TooManyRequests:
                # sub-request memory bound mid-request: withdraw and fail
                # whole (cancelled corpses drain fast; the bound already
                # capped their memory)
                for f in futs:
                    f.cancel()
                raise
            results, errors = [], []
            for f in futs:
                try:
                    r = f.result()
                except concurrent.futures.CancelledError:
                    continue
                except Exception as e:  # noqa: BLE001 — partial results
                    # every swallowed sub-request is a visibly degraded
                    # answer, not a silent one (the caller decides
                    # whether tolerance lets the response go out).
                    # DeadlineExceeded is booked ONCE by the frontend
                    # under reason=deadline — counting it here too
                    # would double-bill the same event.
                    from tempo_tpu.observability import metrics as obs
                    from tempo_tpu.robustness import DeadlineExceeded

                    if not isinstance(e, DeadlineExceeded):
                        obs.partial_results.inc(reason="subrequest")
                    errors.append(e)
                    continue
                if r is not None:
                    results.append(r)
            return results, errors
        finally:
            self.queue.end_request(tenant)

    def lengths(self) -> dict[str, int]:
        return self.queue.lengths()

    def stop(self) -> None:
        self.queue.stop()


class ExclusiveQueue:
    """Priority queue that refuses duplicate keys while an op is queued or
    in flight — the ingester flush-op dedupe (reference flushqueues)."""

    def __init__(self):
        self._heap: list = []
        self._keys: set = set()
        self._lock = threading.Lock()
        self._counter = itertools.count()

    def enqueue(self, key, priority: float, item) -> bool:
        """False if the key is already queued/in-flight."""
        with self._lock:
            if key in self._keys:
                return False
            self._keys.add(key)
            heapq.heappush(self._heap, (priority, next(self._counter), key, item))
            return True

    def dequeue(self):
        """(key, item) or None. The key stays claimed until done(key)."""
        with self._lock:
            if not self._heap:
                return None
            _, _, key, item = heapq.heappop(self._heap)
            return key, item

    def done(self, key) -> None:
        """Release the key so it can be re-enqueued (e.g. retry after
        backoff)."""
        with self._lock:
            self._keys.discard(key)

    def in_flight(self) -> int:
        """Keys claimed by a dequeue() but not yet released via done() —
        ops some drain thread is executing right now. Shutdown waits on
        this before concluding a flush pass made no progress."""
        with self._lock:
            return len(self._keys) - len(self._heap)

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)
