"""Per-tenant fair request queue + keyed-exclusive flush queues.

Role-equivalent to the reference's pkg/scheduler/queue (frontend v1
per-tenant FIFO fairness with max-outstanding 429s, user_queues.go) and
pkg/flushqueues (priority queues that dedupe in-flight ops,
exclusivequeues.go:10-83).
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import heapq
import itertools
import threading
from collections import OrderedDict, deque


class TooManyRequests(Exception):
    """Queue full for tenant (reference: HTTP 429)."""


class RequestQueue:
    """Round-robin across tenants, FIFO within a tenant. `get` blocks until
    a request is available or the queue stops."""

    def __init__(self, max_outstanding_per_tenant: int = 2000):
        self.max_outstanding = max_outstanding_per_tenant
        self._queues: OrderedDict[str, deque] = OrderedDict()
        self._cv = threading.Condition()
        self._stopped = False

    def enqueue(self, tenant: str, request) -> None:
        with self._cv:
            if self._stopped:
                raise RuntimeError("queue stopped")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
            if len(q) >= self.max_outstanding:
                raise TooManyRequests(tenant)
            q.append(request)
            self._cv.notify()

    def get(self, timeout: float | None = None):
        """(tenant, request) or None on stop/timeout. Tenants are served
        round-robin: the tenant we serve moves to the back."""
        with self._cv:
            while True:
                for tenant in list(self._queues):
                    q = self._queues[tenant]
                    if q:
                        req = q.popleft()
                        self._queues.move_to_end(tenant)
                        if not q:
                            del self._queues[tenant]
                        return tenant, req
                if self._stopped:
                    return None
                if not self._cv.wait(timeout):
                    return None

    def lengths(self) -> dict[str, int]:
        with self._cv:
            return {t: len(q) for t, q in self._queues.items()}

    def purge(self, tenant: str, match) -> int:
        """Remove queued requests for which match(request) is true —
        a rejected caller withdraws its already-enqueued sub-requests so
        they stop counting against the tenant's outstanding cap."""
        with self._cv:
            q = self._queues.get(tenant)
            if not q:
                return 0
            kept = deque(r for r in q if not match(r))
            removed = len(q) - len(kept)
            if kept:
                self._queues[tenant] = kept
            else:
                self._queues.pop(tenant, None)
            return removed

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


class QueueWorkerPool:
    """N workers draining a RequestQueue — the in-process collapse of the
    reference's frontend-v1 fair queue + querier worker fleet
    (v1/frontend.go:33-60, querier/worker): every frontend sub-request
    enqueues under its tenant, workers serve tenants round-robin so a
    noisy tenant cannot starve the rest, and a full tenant queue rejects
    with TooManyRequests (HTTP 429) instead of growing without bound."""

    def __init__(self, workers: int = 50,
                 max_outstanding_per_tenant: int = 2000):
        self.queue = RequestQueue(max_outstanding_per_tenant)
        self._n = max(1, workers)
        self._threads: list[threading.Thread] = []
        self._start_lock = threading.Lock()

    def _ensure_started(self) -> None:
        with self._start_lock:
            if self._threads:
                return
            for i in range(self._n):
                t = threading.Thread(target=self._worker, daemon=True,
                                     name=f"query-worker-{i}")
                t.start()
                self._threads.append(t)

    def _worker(self) -> None:
        while True:
            item = self.queue.get()
            if item is None:
                return  # stopped
            _tenant, (fut, fn, ctx, stop_event) = item
            if not fut.set_running_or_notify_cancel():
                continue
            if stop_event is not None and stop_event.is_set():
                fut.set_result(None)  # request already satisfied (early quit)
                continue
            try:
                fut.set_result(ctx.copy().run(fn))
            except BaseException as e:  # noqa: BLE001 — delivered via future
                fut.set_exception(e)

    def submit(self, tenant: str, fn, stop_event=None,
               ctx: contextvars.Context | None = None) -> concurrent.futures.Future:
        """Raises TooManyRequests when the tenant's queue is full."""
        self._ensure_started()
        fut: concurrent.futures.Future = concurrent.futures.Future()
        ctx = ctx if ctx is not None else contextvars.copy_context()
        self.queue.enqueue(tenant, (fut, fn, ctx, stop_event))
        return fut

    def run_jobs(self, tenant: str, jobs, fn, stop_event=None):
        """Fan `jobs` through the fair queue and gather like db.pool
        run_jobs: (non-None results, errors). A full tenant queue fails
        the WHOLE request with TooManyRequests — the reference returns
        429 for the request rather than silently dropping sub-queries.
        Jobs run under a copy of the caller's contextvars context so the
        active tracing span parents the per-job spans."""
        ctx = contextvars.copy_context()
        futs = []
        try:
            for j in jobs:
                futs.append(self.submit(
                    tenant, (lambda j=j: fn(j)), stop_event=stop_event,
                    ctx=ctx))
        except TooManyRequests:
            # withdraw what we already enqueued: left in place it would
            # keep occupying the tenant's outstanding slots (and a racing
            # retry would 429 again) until a worker drained the corpses
            mine = set(map(id, futs))
            self.queue.purge(tenant, lambda item: id(item[0]) in mine)
            for f in futs:
                f.cancel()
            raise
        results, errors = [], []
        for f in futs:
            try:
                r = f.result()
            except concurrent.futures.CancelledError:
                continue
            except Exception as e:  # noqa: BLE001 — partial results
                errors.append(e)
                continue
            if r is not None:
                results.append(r)
        return results, errors

    def lengths(self) -> dict[str, int]:
        return self.queue.lengths()

    def stop(self) -> None:
        self.queue.stop()


class ExclusiveQueue:
    """Priority queue that refuses duplicate keys while an op is queued or
    in flight — the ingester flush-op dedupe (reference flushqueues)."""

    def __init__(self):
        self._heap: list = []
        self._keys: set = set()
        self._lock = threading.Lock()
        self._counter = itertools.count()

    def enqueue(self, key, priority: float, item) -> bool:
        """False if the key is already queued/in-flight."""
        with self._lock:
            if key in self._keys:
                return False
            self._keys.add(key)
            heapq.heappush(self._heap, (priority, next(self._counter), key, item))
            return True

    def dequeue(self):
        """(key, item) or None. The key stays claimed until done(key)."""
        with self._lock:
            if not self._heap:
                return None
            _, _, key, item = heapq.heappop(self._heap)
            return key, item

    def done(self, key) -> None:
        """Release the key so it can be re-enqueued (e.g. retry after
        backoff)."""
        with self._lock:
            self._keys.discard(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)
