"""Querier: stateless read worker.

Role-equivalent to the reference's modules/querier (querier.go:60-452):
trace-by-ID queries the ingester replica set AND the backend blocklist,
combining partials; SearchRecent fans out to ingesters; SearchBlock
executes one frontend-sharded job against the TPU engine; tag queries
aggregate ingester + block dictionaries under byte limits.
"""

from __future__ import annotations

import contextvars

from tempo_tpu import tempopb
from tempo_tpu.db import TempoDB
from tempo_tpu.model.codec import codec_for, CURRENT_ENCODING
from tempo_tpu.model.matches import trace_search_metadata
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability import tracing
from tempo_tpu.robustness import FAULTS, deadline as rdeadline
from tempo_tpu.search import SearchResults
from tempo_tpu.utils.hashing import token_for
from tempo_tpu.utils.ids import pad_trace_id
from .overrides import Overrides
from .ring import Ring

def _ctx_submit(pool, fn, *args):
    """Submit to the replica pool UNDER the submitter's contextvars:
    the request's current span and deadline follow the read onto the
    worker, so spans opened there parent into the request's trace and
    a breaker fault booked mid-fanout carries the offending trace id
    into its flight-recorder bundle instead of an anonymous None."""
    ctx = contextvars.copy_context()
    return pool.submit(ctx.run, fn, *args)


QUERY_MODE_INGESTERS = "ingesters"
QUERY_MODE_BLOCKS = "blocks"
QUERY_MODE_ALL = "all"


class Querier:
    # blocks consulted by the tag endpoints' backend leg, newest first.
    # The reference answers tags from INGESTERS only (querier.go); the
    # block leg here is a richer answer but must not stage a 10K-block
    # corpus through the 64-entry container LRU per tags call
    TAG_BLOCKS_LIMIT = 100

    def __init__(self, db: TempoDB, ring: Ring, ingesters: dict,
                 overrides: Overrides | None = None,
                 external_endpoints: list | None = None,
                 prefer_self: int = 10,
                 external_hedge_after_s: float = 4.0,
                 fanout_workers: int | None = None):
        """ingesters: instance id → object with find_trace_by_id/search/
        instance() (in-process Ingester or gRPC stub).

        external_endpoints: serverless search-worker URLs; SearchBlock jobs
        overflow to them when more than `prefer_self` jobs run locally
        (reference querier.go:397-452: hedged external search with a
        prefer-self semaphore)."""
        import concurrent.futures
        import threading

        self.db = db
        self.ring = ring
        self.ingesters = ingesters
        self.overrides = overrides or Overrides()
        self.external_endpoints = list(external_endpoints or [])
        self._prefer_self = threading.Semaphore(prefer_self)
        self.external_hedge_after_s = external_hedge_after_s
        self._rr = 0
        # replica fan-out pool: ingester reads go out CONCURRENTLY so one
        # slow replica costs max(replicas), not sum (reference
        # querier.go:252-276 errgroup). Sized for concurrent REQUESTS ×
        # replicas because early-quit stragglers pin their thread until
        # the RPC completes — a pool at ~replica count would head-of-line
        # block independent requests behind one slow ingester
        self._fanout_fixed = fanout_workers is not None
        self._fanout_size = fanout_workers or 32
        self._fanout_lock = threading.Lock()
        self._fanout = concurrent.futures.ThreadPoolExecutor(
            max_workers=self._fanout_size,
            thread_name_prefix="replica-fanout")

    def _fanout_pool(self):
        """The replica pool, re-sized as gossip discovers ingesters: the
        dict is usually EMPTY at construction in microservices mode, so
        a build-time snapshot would lock in the floor and reintroduce
        head-of-line blocking at scale. Growth swaps in a bigger
        executor; the old one drains its in-flight tasks and exits."""
        import concurrent.futures

        if self._fanout_fixed:
            return self._fanout
        try:
            n = len(self.ingesters)
        except Exception:  # noqa: BLE001 — dynamic client dicts
            n = 0
        want = max(32, 8 * max(1, n))
        if want > self._fanout_size:
            with self._fanout_lock:
                if want > self._fanout_size:
                    # deliberately NOT shutting the old pool down: a
                    # concurrent request captured it before the swap and
                    # its next submit would raise "cannot schedule new
                    # futures after shutdown" — dropping the reference
                    # lets in-flight work finish and idle threads die
                    # with the executor at GC
                    self._fanout = concurrent.futures.ThreadPoolExecutor(
                        max_workers=want,
                        thread_name_prefix="replica-fanout")
                    self._fanout_size = want
        return self._fanout

    # ---- trace by id (reference querier.go:171-249) ----

    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         block_start: str = "", block_end: str = "",
                         mode: str = QUERY_MODE_ALL) -> tempopb.TraceByIDResponse:
        tid = pad_trace_id(trace_id)
        partials: list[bytes] = []
        failed = 0

        if mode in (QUERY_MODE_INGESTERS, QUERY_MODE_ALL):
            import concurrent.futures

            replicas = self.ring.get(token_for(tenant, tid))
            futs = []
            for iid in replicas:
                ing = self.ingesters.get(iid)
                if ing is None:
                    failed += 1
                    obs.partial_results.inc(reason="replica")
                    continue
                futs.append(_ctx_submit(self._fanout_pool(),
                                        ing.find_trace_by_id, tenant, tid))
            try:
                # bounded by the request deadline, like search_recent:
                # a replica wedged behind a dead backend must not hold
                # the lookup hostage
                for f in concurrent.futures.as_completed(
                        futs, timeout=rdeadline.remaining()):
                    try:
                        partials.extend(f.result())
                    except Exception:  # noqa: BLE001 — replica → partial
                        failed += 1
                        obs.partial_results.inc(reason="replica")
            except concurrent.futures.TimeoutError:
                undone = sum(1 for f in futs if not f.done())
                failed += undone
                obs.partial_results.inc(undone, reason="deadline")

        if mode in (QUERY_MODE_BLOCKS, QUERY_MODE_ALL):
            obj, block_failed = self.db.find_trace_by_id(
                tenant, tid, block_start, block_end
            )
            failed += block_failed
            if block_failed:
                obs.partial_results.inc(block_failed, reason="backend")
            if obj is not None:
                partials.append(obj)

        resp = tempopb.TraceByIDResponse()
        resp.metrics.failed_blocks = failed
        if partials:
            codec = codec_for(CURRENT_ENCODING)
            obj = partials[0] if len(partials) == 1 else codec.combine(*partials)
            resp.trace.CopyFrom(codec.prepare_for_read(obj))
        return resp

    # ---- search (reference SearchRecent :278, SearchBlock :397) ----

    def search_recent(self, tenant: str, req: tempopb.SearchRequest) -> tempopb.SearchResponse:
        """Concurrent fan-out over the ingester replica set with merge +
        early quit: latency is the slowest replica still NEEDED, not the
        sum of all (reference querier.go:252-276). A failed replica
        counts as failed_blocks — an operator must be able to tell
        "pruned" from "broken" — and the merge stops once the limit is
        satisfied (stragglers complete in the pool, their answers moot)."""
        import concurrent.futures

        results = SearchResults.for_request(req)
        ings = list(self.ingesters.values())
        if not ings:
            return results.response()

        def one(ing):
            if FAULTS.active:
                FAULTS.hit("replica_error")
            local = SearchResults.for_request(req)
            ing.search(tenant, req, local)
            return local.response()

        pool = self._fanout_pool()
        futs = [_ctx_submit(pool, one, ing) for ing in ings]
        try:
            # bounded by the request deadline: a replica stuck behind a
            # dead device must not hold the whole answer hostage —
            # stragglers complete in the pool, their answers moot
            for f in concurrent.futures.as_completed(
                    futs, timeout=rdeadline.remaining()):
                try:
                    results.merge_response(f.result())
                except Exception:  # noqa: BLE001 — replica failure → partial
                    results.metrics.failed_blocks += 1
                    results.metrics.partial = True
                    obs.partial_results.inc(reason="replica")
                    continue
                if results.complete:
                    break
        except concurrent.futures.TimeoutError:
            undone = sum(1 for f in futs if not f.done())
            results.metrics.failed_blocks += undone
            results.metrics.partial = True
            obs.partial_results.inc(undone, reason="deadline")
        return results.response()

    def search_block(self, req: tempopb.SearchBlockRequest) -> tempopb.SearchResponse:
        if self.external_endpoints:
            if self._prefer_self.acquire(blocking=False):
                try:
                    return self.db.search_block(req).response()
                finally:
                    self._prefer_self.release()
            return self._search_external(req)
        return self.db.search_block(req).response()

    def search_blocks(self, req: tempopb.SearchBlocksRequest) -> tempopb.SearchResponse:
        """Batched job execution: one kernel dispatch per geometry group
        — and under concurrency, FEWER: concurrent search_blocks calls
        (several frontend requests, several tenants' dashboards) route
        into the shared BlockBatcher, whose QueryCoalescer fuses
        dispatches that land on the same staged batch within the
        coalescing window into one multi-query kernel launch. The
        querier adds no serialization of its own — each call runs on its
        caller's worker thread so peers can actually meet in the window.
        With serverless endpoints configured the batch degrades to
        singular jobs so overflow can proxy out (the external workers
        speak SearchBlockRequest); that path bypasses batching AND
        coalescing."""
        with tracing.start_span(
                "querier.SearchBlocks", tenant=req.tenant_id,
                jobs=len(req.jobs)) as span:
            resp = self._search_blocks(req)
            # dispatch counts live in scan_dispatches{mode=batched|
            # coalesced}, not here: the batcher's last-search scratch is
            # shared across concurrent searches and would attribute
            # another request's dispatches to this span
            span.set_attributes(
                inspected_blocks=resp.metrics.inspected_blocks)
            return resp

    def _search_blocks(self, req: tempopb.SearchBlocksRequest) -> tempopb.SearchResponse:
        if self.external_endpoints:
            from tempo_tpu.search import SearchResults

            results = SearchResults.for_request(req.search_req)
            for j in req.jobs:
                one = tempopb.SearchBlockRequest()
                one.search_req.CopyFrom(req.search_req)
                one.tenant_id = req.tenant_id
                one.block_id = j.block_id
                one.start_page = j.start_page
                one.pages_to_search = j.pages_to_search
                one.encoding = j.encoding
                one.version = j.version
                one.data_encoding = j.data_encoding
                one.start_time = j.start_time
                one.end_time = j.end_time
                results.merge_response(self.search_block(one))
                if results.complete:
                    break
            return results.response()
        return self.db.search_blocks(req).response()

    def _search_external(self, req: tempopb.SearchBlockRequest) -> tempopb.SearchResponse:
        """Proxy one job to a serverless search worker, hedged (reference
        searchExternalEndpoint: up to 2 extra hedges)."""
        import urllib.request

        from tempo_tpu.db.hedge import hedged_call

        body = req.SerializeToString()
        endpoint = self.external_endpoints[self._rr % len(self.external_endpoints)]
        self._rr += 1

        def call():
            r = urllib.request.Request(
                endpoint.rstrip("/") + "/search-block", data=body,
                headers={"Content-Type": "application/protobuf"},
            )
            with urllib.request.urlopen(r, timeout=30) as resp:
                out = tempopb.SearchResponse()
                out.ParseFromString(resp.read())
                return out

        return hedged_call(call, hedge_after_s=self.external_hedge_after_s,
                           max_hedges=2)

    # ---- tags ----

    def _tag_blocks(self, tenant: str):
        """Newest blocks first, capped: recent blocks carry the live tag
        universe; a full-corpus container sweep per tags call would
        thrash the staging LRU at scale."""
        import heapq

        return heapq.nlargest(self.TAG_BLOCKS_LIMIT,
                              self.db.blocklist.metas(tenant),
                              key=lambda m: m.end_time or 0)

    def search_tags(self, tenant: str) -> tempopb.SearchTagsResponse:
        tags: set[str] = set()
        for ing in self.ingesters.values():
            try:
                tags.update(ing.search_tags(tenant))
            except Exception:  # noqa: BLE001 — replica failure → partial tags
                obs.partial_results.inc(reason="replica")
                continue
        for m in self._tag_blocks(tenant):
            try:
                sp = self.db._search_block_for(m).staged()  # noqa: SLF001
                tags.update(sp.pages.key_dict)
            except Exception:  # noqa: BLE001 — blocks without search data
                obs.partial_results.inc(reason="backend")
                continue
        resp = tempopb.SearchTagsResponse()
        resp.tag_names.extend(sorted(tags))
        return resp

    def search_tag_values(self, tenant: str, tag: str) -> tempopb.SearchTagValuesResponse:
        lim = self.overrides.limits(tenant)
        vals: set[str] = set()
        size = 0
        for ing in self.ingesters.values():
            try:
                vals.update(ing.search_tag_values(
                    tenant, tag, lim.max_bytes_per_tag_values))
            except Exception:  # noqa: BLE001 — replica failure → partial values
                obs.partial_results.inc(reason="replica")
                continue
        budget_hit = False
        for m in self._tag_blocks(tenant):
            if budget_hit:
                # a tripped byte budget must stop the whole sweep, not
                # just the current block — each further block costs a
                # backend read + decompress + staging for nothing
                break
            try:
                sp = self.db._search_block_for(m).staged()  # noqa: SLF001
            except Exception:  # noqa: BLE001
                obs.partial_results.inc(reason="backend")
                continue
            for s in sp.pages.values_for_key(tag):
                if s not in vals:
                    size += len(s)
                    if size > lim.max_bytes_per_tag_values:
                        budget_hit = True
                        break
                    vals.add(s)
        resp = tempopb.SearchTagValuesResponse()
        resp.tag_values.extend(sorted(vals))
        return resp
