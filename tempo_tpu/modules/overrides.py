"""Per-tenant runtime limits.

Role-equivalent to the reference's modules/overrides (limits.go:46-96,
overrides.go:30-55): global defaults + hot-reloadable per-tenant
overrides; ingestion rate limiting is a token bucket (the reference uses
golang.org/x/time/rate with local/global strategies — the global strategy
divides the rate by the distributor count, distributor/ingestion_rate_strategy.go).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class Limits:
    # reference defaults: limits.go:85-96
    ingestion_rate_bytes: int = 15_000_000
    ingestion_burst_bytes: int = 20_000_000
    max_live_traces: int = 10_000
    max_bytes_per_trace: int = 5_000_000
    max_search_bytes_per_trace: int = 5_000
    max_bytes_per_tag_values: int = 5_000_000
    block_retention_s: int = 0  # 0 → use the db default
    ingestion_rate_strategy: str = "local"  # or "global"


class _TokenBucket:
    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t = time.monotonic()
        self.lock = threading.Lock()

    def allow(self, n: float) -> bool:
        with self.lock:
            now = time.monotonic()
            self.tokens = min(self.burst, self.tokens + (now - self.t) * self.rate)
            self.t = now
            if n <= self.tokens:
                self.tokens -= n
                return True
            return False


class Overrides:
    def __init__(self, defaults: Limits | None = None,
                 per_tenant: dict[str, dict] | None = None,
                 distributor_count=lambda: 1):
        self.defaults = defaults or Limits()
        self._per_tenant = dict(per_tenant or {})
        self._buckets: dict[str, _TokenBucket] = {}
        self._lock = threading.Lock()
        self._distributor_count = distributor_count

    def limits(self, tenant: str) -> Limits:
        over = self._per_tenant.get(tenant)
        if not over:
            return self.defaults
        return replace(self.defaults, **{
            k: v for k, v in over.items() if k in Limits.__dataclass_fields__
        })

    def reload(self, per_tenant: dict[str, dict]) -> None:
        """Hot reload (reference: runtimeconfig poll every 10s)."""
        with self._lock:
            self._per_tenant = dict(per_tenant)
            self._buckets.clear()

    def allow_ingestion(self, tenant: str, nbytes: int) -> bool:
        lim = self.limits(tenant)
        rate = lim.ingestion_rate_bytes
        if lim.ingestion_rate_strategy == "global":
            rate = max(1.0, rate / max(1, self._distributor_count()))
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None or b.rate != rate:
                b = _TokenBucket(rate, lim.ingestion_burst_bytes)
                self._buckets[tenant] = b
        return b.allow(nbytes)
