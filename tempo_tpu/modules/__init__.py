"""Distributed service modules.

Role-equivalent to the reference's modules/ tree (SURVEY.md §2.2):
distributor → ingester → (WAL/blocks) ← querier ← frontend, with
overrides (per-tenant limits) and the ring (placement) shared by all.
In-process wiring lives in app.py (the "single binary" / scalable
single-binary target); each module keeps a narrow interface so a gRPC
boundary can replace in-process calls without touching the logic.
"""

from .overrides import Overrides, Limits
from .ring import Ring, RingInstance
from .distributor import Distributor
from .ingester import Ingester
from .querier import Querier
from .frontend import QueryFrontend
from .app import App, AppConfig

__all__ = [
    "Overrides", "Limits", "Ring", "RingInstance", "Distributor",
    "Ingester", "Querier", "QueryFrontend", "App", "AppConfig",
]
