"""Ingester: live traces → WAL head block → completed backend blocks.

Role-equivalent to the reference's modules/ingester (ingester.go:53-416,
instance.go:92-661, flush.go:124-389): per-tenant instances hold live
traces in memory under byte/count limits; a sweep cuts idle/complete
traces into the WAL head block (trace WAL + parallel search WAL); when the
head block is big or old enough it is cut and completed into an immutable
backend block; on restart both WALs replay (SURVEY.md §5 checkpoint).

Divergence from the reference: completed blocks go straight to the shared
backend via TempoDB.complete_block (the reference stages them on an
ingester-local backend first and flushes async with retry/backoff —
flush.go opKindComplete/opKindFlush; collapse is safe in-process because
the backend write is atomic, and the retry queue lives one level up).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from tempo_tpu import tempopb
from tempo_tpu.db import TempoDB
from tempo_tpu.model.codec import segment_codec_for, CURRENT_ENCODING
from tempo_tpu.search import SearchResults, decode_search_data
from tempo_tpu.search.data import SearchData, search_data_matches
from tempo_tpu.search.live_tier import LIVE_TIER
from tempo_tpu.search.streaming import StreamingSearchBlock, _meta_from_sd
from tempo_tpu.observability import metrics as obs
from tempo_tpu.utils.ids import pad_trace_id
from .overrides import Overrides
from .queue import ExclusiveQueue


class LimitError(Exception):
    pass


class FlushIncompleteError(Exception):
    """flush_all could not get every completing block to the backend.
    Carries what DID flush so shutdown callers can log it; the local WAL
    still holds the rest and must not be deleted."""

    def __init__(self, left_behind: int, completed: list):
        super().__init__(
            f"{left_behind} block(s) could not be flushed to the backend")
        self.left_behind = left_behind
        self.completed = completed


@dataclass
class _LiveTrace:
    segments: list = field(default_factory=list)
    nbytes: int = 0
    last_append: float = 0.0
    # monotonic stamp of the FIRST push — the head of the write-path
    # telemetry record (push -> cut -> flush -> poll visibility). Set
    # from the clock read the push path already makes, so stamping
    # costs nothing even with telemetry disabled.
    first_push: float = 0.0
    # encoded SearchData fragments, decoded+merged LAZILY: the ack path
    # runs per push, while folding is only needed at live-search or cut
    # time — decode-per-push was ~10% of distributor→ingester latency
    search_raw: list = field(default_factory=list)
    _search: SearchData | None = None

    def search_data(self, tid: bytes) -> SearchData | None:
        """Folded search entry (caches; drains the raw fragment list).
        A corrupt fragment is DROPPED here, not raised: this runs inside
        cut_complete_traces after the trace object is already appended —
        an exception would leave the trace live, duplicate its WAL
        append on every retry, and wedge the tenant's sweep forever."""
        if self.search_raw:
            raws, self.search_raw = self.search_raw, []
            for raw in raws:
                try:
                    sd = decode_search_data(raw, tid)
                except Exception:  # noqa: BLE001 — skip corrupt fragment
                    from tempo_tpu.observability import get_logger

                    get_logger().warning(
                        "dropping corrupt search-data fragment for %s",
                        tid.hex()[:16])
                    continue
                if self._search is None:
                    self._search = sd
                else:
                    self._search.merge(sd)
        return self._search


@dataclass
class _Completing:
    """A block awaiting completion, with its per-block retry state
    (reference flush.go:359-389 — each failed op is requeued with its own
    exponential backoff rather than stalling the queue)."""
    blk: object
    search: object
    retry_at: float = 0.0   # monotonic time before which we skip it
    backoff_s: float = 0.0
    in_flight: bool = False  # being completed right now (still queryable)
    attempts: int = 0        # failed completion attempts (retry telemetry)
    cut_at: float = 0.0      # monotonic time the block was cut
    # oldest first_push among the traces in this block (None for
    # replayed blocks — their live traces predate this process)
    oldest_ingest: float | None = None


class TenantInstance:
    # completed blocks stay queryable on the ingester until readers have
    # had time to poll the new block into their blocklists (reference
    # complete_block_timeout, instance.ClearFlushedBlocks :373)
    COMPLETE_BLOCK_TIMEOUT_S = 300.0
    # flush retry backoff envelope (reference flush.go:62-67: 30s initial,
    # exponential, capped)
    FLUSH_BACKOFF_S = 30.0
    FLUSH_BACKOFF_MAX_S = 120.0

    def __init__(self, tenant: str, db: TempoDB, overrides: Overrides):
        self.tenant = tenant
        self.db = db
        self.overrides = overrides
        self.lock = threading.Lock()
        self.live: dict[bytes, _LiveTrace] = {}
        self.codec = segment_codec_for(CURRENT_ENCODING)
        self._new_head()
        self.completing: list[_Completing] = []
        self.recent = []      # [(BlockMeta, completed_at)]

    def _new_head(self):
        self.head = self.db.wal.new_block(self.tenant)
        self.head_search = StreamingSearchBlock(self.head.path + ".search")
        self.head_created = time.monotonic()
        # oldest first_push cut into THIS head block (inf = none yet)
        self.head_oldest = float("inf")

    # ---- write path ----

    def push(self, trace_id: bytes, segment: bytes,
             search_data: bytes = b"") -> None:
        tid = pad_trace_id(trace_id)
        lim = self.overrides.limits(self.tenant)
        now = time.monotonic()
        with self.lock:
            t = self.live.get(tid)
            if t is None:
                if len(self.live) >= lim.max_live_traces:
                    raise LimitError(
                        f"max live traces ({lim.max_live_traces}) reached"
                    )
                t = self.live[tid] = _LiveTrace(first_push=now)
            if t.nbytes + len(segment) > lim.max_bytes_per_trace:
                raise LimitError("max bytes per trace reached")
            t.segments.append(segment)
            t.nbytes += len(segment)
            t.last_append = now
            obs.live_traces.set(len(self.live), tenant=self.tenant)
            if search_data:
                t.search_raw.append(search_data)
                # hot tier: absorb under the instance lock so the tier's
                # live stage mirrors self.live deterministically (a cut
                # between push and absorb would otherwise resurrect the
                # trace in the stage and double-answer forever)
                if LIVE_TIER.enabled:
                    LIVE_TIER.absorb(self.tenant, tid, search_data)

    # ---- sweep / cut (reference CutCompleteTraces instance.go:222) ----

    def cut_complete_traces(self, max_idle_s: float = 10.0,
                            force: bool = False) -> int:
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        now = time.monotonic()
        cut = 0
        cut_ages: list[float] = []
        cut_tids: list[bytes] = []
        with self.lock:
            for tid in list(self.live):
                t = self.live[tid]
                if not force and now - t.last_append < max_idle_s:
                    continue
                obj = self.codec.to_object(t.segments)
                r = self.codec.fast_range(obj) or (0, 0)
                self.head.append(tid, obj, r[0], r[1])
                sd = t.search_data(tid)
                if sd is not None:
                    self.head_search.append(tid, sd)
                if t.first_push:
                    if t.first_push < self.head_oldest:
                        self.head_oldest = t.first_push
                    if TELEMETRY.enabled:
                        cut_ages.append(now - t.first_push)
                del self.live[tid]
                cut_tids.append(tid)
                cut += 1
            # same critical section as the head_search appends: the cut
            # traces leave the hot tier's live stage the instant they
            # become WAL-head entries — never both, never neither
            if cut_tids and LIVE_TIER.enabled:
                LIVE_TIER.mark_cut(self.tenant, cut_tids)
            obs.live_traces.set(len(self.live), tenant=self.tenant)
        for age in cut_ages:  # outside the instance lock — observe locks
            TELEMETRY.record_live_cut(age)
        return cut

    def cut_block_if_ready(self, max_block_bytes: int = 500 << 20,
                           max_block_age_s: float = 1800.0,
                           force: bool = False) -> bool:
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        now = time.monotonic()
        with self.lock:
            if len(self.head) == 0:
                return False
            age = now - self.head_created
            if not (force or self.head.data_length >= max_block_bytes
                    or age >= max_block_age_s):
                return False
            oldest = (self.head_oldest
                      if self.head_oldest != float("inf") else None)
            self.completing.append(_Completing(
                self.head, self.head_search, cut_at=now,
                oldest_ingest=oldest))
            self._new_head()
        if TELEMETRY.enabled:
            TELEMETRY.record_block_cut(age)
        return True

    def complete_one(self, block_id: str | None = None,
                     ignore_backoff: bool = False) -> "tempopb.Trace | None":
        """Complete the oldest ELIGIBLE completing block (or the specific
        `block_id`) to the backend and clear its WAL files (reference
        handleComplete flush.go:235-281). On a backend failure the block
        is restored with a per-block exponential backoff (30s→120s cap,
        flush.go:359-389) so a flaky backend neither hot-loops one block
        nor starves its siblings — the next call skips backed-off blocks
        and completes the rest. `ignore_backoff` is the forced-flush path
        (shutdown/scale-down must not skip a backed-off block).

        The block stays IN `completing` (marked in_flight) until the
        backend write succeeds: a streaming completion can take seconds
        to minutes, and queries arriving meanwhile must still see its
        traces — the reference swaps the block out only after
        CompleteBlock returns."""
        now = time.monotonic()
        with self.lock:
            c = next((c for c in self.completing
                      if not c.in_flight
                      and (ignore_backoff or c.retry_at <= now)
                      and (block_id is None
                           or c.blk.meta.block_id == block_id)), None)
            if c is None:
                return None
            c.in_flight = True
        from tempo_tpu.observability import tracing
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        t0 = time.perf_counter()
        with tracing.start_span("ingester.CompleteBlock",
                                tenant=self.tenant) as span:
            try:
                from tempo_tpu.robustness import FAULTS

                if FAULTS.active:
                    FAULTS.hit("flush_error")  # backend flake → backoff
                meta = self.db.complete_block(c.blk, c.search.entries())
                span.set_attributes(block_id=meta.block_id,
                                    objects=meta.total_objects)
            except Exception:
                # span.__exit__ records the propagating exception
                c.backoff_s = (self.FLUSH_BACKOFF_S if not c.backoff_s
                               else min(c.backoff_s * 2,
                                        self.FLUSH_BACKOFF_MAX_S))
                c.retry_at = time.monotonic() + c.backoff_s
                c.attempts += 1
                obs.flush_failures.inc(tenant=self.tenant)
                if TELEMETRY.enabled:
                    TELEMETRY.record_flush_retry(c.attempts)
                with self.lock:
                    c.in_flight = False
                raise
            flush_trace_id = (span.context.trace_id.hex()
                              if span.recording else None)
        done = time.monotonic()
        with self.lock:
            # atomic hand-off: queryable via `recent` (backend) the same
            # instant it leaves `completing` (WAL)
            self.completing.remove(c)
            self.recent.append((meta, done))
        c.blk.clear()
        c.search.clear()
        obs.blocks_completed.inc(tenant=self.tenant)
        obs.live_traces.set(len(self.live), tenant=self.tenant)
        if TELEMETRY.enabled:
            TELEMETRY.record_flush(
                self.tenant, meta.block_id,
                write_s=time.perf_counter() - t0,
                cut_to_flush_s=(done - c.cut_at) if c.cut_at else -1.0,
                oldest_ingest=c.oldest_ingest,
                objects=meta.total_objects, attempts=c.attempts,
                trace_id=flush_trace_id)
        return meta

    def clear_flushed(self) -> None:
        """Drop completed blocks past the query-visibility window."""
        cutoff = time.monotonic() - self.COMPLETE_BLOCK_TIMEOUT_S
        with self.lock:
            self.recent = [(m, t) for m, t in self.recent if t > cutoff]

    # ---- read path (reference instance.FindTraceByID :406) ----

    def find(self, trace_id: bytes) -> list[bytes]:
        tid = pad_trace_id(trace_id)
        partials = []
        with self.lock:
            t = self.live.get(tid)
            if t is not None and t.segments:
                partials.append(self.codec.to_object(list(t.segments)))
            heads = [self.head] + [c.blk for c in self.completing]
        for blk in heads:
            obj = blk.find(tid)
            if obj is not None:
                partials.append(obj)
        # recently completed blocks: cover the reader's blocklist-poll gap.
        # Snapshot AFTER the WAL pass — a block whose completion handed off
        # mid-iteration (its WAL find returned None on the cleared file) is
        # in `recent` by now, so the re-read closes the visibility gap.
        with self.lock:
            recent = [m for m, _ in self.recent]
        from tempo_tpu.encoding.v2 import BackendBlock

        for meta in recent:
            try:
                obj = BackendBlock(self.db.backend, meta).find_by_id(tid)
            except Exception:  # noqa: BLE001 — backend flake → partial
                continue
            if obj is not None:
                partials.append(obj)
        return partials

    # live entries walked between request-deadline reads on the legacy
    # matching loop (the StreamingSearchBlock stride twin)
    _DEADLINE_STRIDE = 256

    def search(self, req, results: SearchResults) -> None:
        from tempo_tpu.robustness import deadline as rdeadline

        if rdeadline.expired():
            # budget already spent: book partial instead of walking a
            # potentially huge live set (PR 9 contract)
            StreamingSearchBlock._book_deadline(results)
            return
        # hot tier first: the live stage kernel-scans OUTSIDE the
        # instance lock (it mirrors self.live via the push/cut hooks).
        # False = gate off or stage overflow — run the legacy walk.
        hot_live = False
        if LIVE_TIER.enabled:
            hot_live = LIVE_TIER.search(self.tenant, req, results)
        with self.lock:
            # the decode (search_data) must stay under the lock — it
            # drains the raw fragment list, which races with push
            # otherwise; the MATCHING below runs outside it
            live_sds = ([] if hot_live else
                        [sd for tid, t in self.live.items()
                         if (sd := t.search_data(tid)) is not None])
            searches = [self.head_search] + [c.search for c in self.completing]
            recent = [m for m, _ in self.recent]
        for i, sd in enumerate(live_sds):
            if i and i % self._DEADLINE_STRIDE == 0 and rdeadline.expired():
                StreamingSearchBlock._book_deadline(results)
                return
            results.metrics.inspected_traces += 1
            if search_data_matches(sd, req):
                results.add(_meta_from_sd(sd))
                if results.complete:
                    return
        for ssb in searches:
            ssb.search(req, results)
            if results.complete or results.metrics.partial:
                return
        for meta in recent:  # blocklist-poll gap, as in find()
            if rdeadline.expired():
                StreamingSearchBlock._book_deadline(results)
                return
            # once the reader's poll made this block visible, its leg of
            # the answer moved to the blocklist path — skipping it here
            # is the hot tier's eviction-on-poll contract (no double
            # scan; dedupe no longer needed for it)
            if LIVE_TIER.enabled and LIVE_TIER.poll_visible(
                    self.tenant, meta.block_id):
                continue
            try:
                self.db._search_block_for(meta).search(req, results)  # noqa: SLF001
            except Exception:  # noqa: BLE001
                continue
            if results.complete:
                return

    def search_tags(self) -> set:
        tags = set()
        with self.lock:
            # bounded lock hold: decode + snapshot references only (the
            # decode drains raw fragment lists, so it cannot leave the
            # lock); the set union over every entry's kv dict runs
            # against the snapshot below, not against pushes
            sds = [sd for tid, t in self.live.items()
                   if (sd := t.search_data(tid)) is not None]
            for ssb in [self.head_search] + [c.search for c in self.completing]:
                sds.extend(ssb.entries())
        for sd in sds:
            tags.update(sd.kvs)
        for meta in self._recent_tag_blocks():
            # blocklist-poll gap, as in find()/search(): a just-completed
            # block is out of head/completing but not yet in any reader's
            # blocklist — without this sweep its tags vanish from
            # dropdowns for a full poll interval
            try:
                sp = self.db._search_block_for(meta)  # noqa: SLF001
                tags.update(sp.pages().key_dict)
            except Exception:  # noqa: BLE001 — backend flake → partial
                continue
        return tags

    # newest-first cap on the recently-completed sweep, mirroring the
    # querier's TAG_BLOCKS_LIMIT: an uncapped sweep of a busy tenant's
    # 5-minute `recent` window would decompress dozens of containers per
    # tags call and thrash the shared block cache (code-review r5)
    RECENT_TAG_BLOCKS_LIMIT = 20

    def _recent_tag_blocks(self):
        import heapq

        with self.lock:
            recent = [m for m, _ in self.recent]
        return heapq.nlargest(self.RECENT_TAG_BLOCKS_LIMIT, recent,
                              key=lambda m: m.end_time or 0)

    def search_tag_values(self, tag: str, max_bytes: int) -> set:
        vals: set[str] = set()
        size = 0
        with self.lock:
            sds = [sd for tid, t in self.live.items()
                   if (sd := t.search_data(tid)) is not None]
            for ssb in [self.head_search] + [c.search for c in self.completing]:
                sds.extend(ssb.entries())
        for sd in sds:
            for v in sd.kvs.get(tag, ()):
                if v not in vals:
                    size += len(v)
                    if size > max_bytes:
                        return vals
                    vals.add(v)
        for meta in self._recent_tag_blocks():  # blocklist-poll gap
            try:
                pages = self.db._search_block_for(meta).pages()  # noqa: SLF001
            except Exception:  # noqa: BLE001
                continue
            for s in pages.values_for_key(tag):
                if s not in vals:
                    size += len(s)
                    if size > max_bytes:
                        return vals
                    vals.add(s)
        return vals


class Ingester:
    """One ingester process: tenant instances + flush machinery + replay."""

    def __init__(self, db: TempoDB, overrides: Overrides | None = None,
                 instance_id: str = "ingester-0",
                 concurrent_flushes: int = 4):
        self.db = db
        self.overrides = overrides or Overrides()
        self.id = instance_id
        self.concurrent_flushes = concurrent_flushes
        # keyed-exclusive completion ops: a block already queued or in
        # flight is never enqueued twice, so overlapping sweeps (periodic
        # tick racing /flush or shutdown) cannot double-complete it
        # (reference pkg/flushqueues exclusivequeues.go:10-83 + flush.go:185)
        self.flush_ops = ExclusiveQueue()
        self._instances: dict[str, TenantInstance] = {}
        self._lock = threading.Lock()
        self.replayed_blocks = 0
        self._replay()

    def instance(self, tenant: str) -> TenantInstance:
        with self._lock:
            inst = self._instances.get(tenant)
            if inst is None:
                inst = self._instances[tenant] = TenantInstance(
                    tenant, self.db, self.overrides
                )
            return inst

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._instances)

    # ---- gRPC-facing surface (Pusher/Querier services) ----

    def push_bytes(self, tenant: str, req: tempopb.PushBytesRequest) -> None:
        inst = self.instance(tenant)
        for tid, seg, sd in zip(req.ids, req.traces, req.search_data):
            inst.push(tid, seg, sd)
        # standing queries evaluate per push micro-batch, AFTER the acks:
        # notification latency must never sit on the write path's lock
        if LIVE_TIER.enabled and LIVE_TIER.has_subscribers(tenant):
            for tid, sd in zip(req.ids, req.search_data):
                if sd:
                    LIVE_TIER.notify_push(tenant, pad_trace_id(tid), sd)

    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> list[bytes]:
        with self._lock:
            inst = self._instances.get(tenant)
        return inst.find(trace_id) if inst else []

    def search(self, tenant: str, req, results: SearchResults) -> None:
        with self._lock:
            inst = self._instances.get(tenant)
        if inst:
            inst.search(req, results)

    def search_tags(self, tenant: str) -> set:
        with self._lock:
            inst = self._instances.get(tenant)
        return inst.search_tags() if inst else set()

    def search_tag_values(self, tenant: str, tag: str,
                          max_bytes: int = 1 << 20) -> set:
        with self._lock:
            inst = self._instances.get(tenant)
        return inst.search_tag_values(tag, max_bytes) if inst else set()

    # ---- flush machinery (reference ingester.loop flush.go:144-218) ----

    def sweep(self, max_idle_s: float = 10.0, force: bool = False,
              max_block_bytes: int = 500 << 20,
              max_block_age_s: float = 1800.0) -> list:
        """One flush-loop tick: cut idle traces, cut ready blocks, then
        enqueue one keyed-exclusive completion op per eligible block and
        drain the op queue with concurrent_flushes workers (reference
        flush.go:144-218). Returns completed block metas."""
        completed: list = []
        now = time.monotonic()
        for tenant in self.tenants():
            inst = self.instance(tenant)
            inst.cut_complete_traces(max_idle_s=max_idle_s, force=force)
            inst.cut_block_if_ready(max_block_bytes=max_block_bytes,
                                    max_block_age_s=max_block_age_s,
                                    force=force)
            with inst.lock:
                # force (shutdown, /flush) overrides retry backoff: a
                # scale-down must attempt every block, not strand the
                # backed-off ones in the local WAL
                eligible = [(c.blk.meta.block_id, c.retry_at)
                            for c in inst.completing
                            if force or c.retry_at <= now]
            for bid, prio in eligible:
                # False (already queued/in flight from a racing sweep) is
                # exactly the dedupe the exclusive queue exists for. The
                # op carries ITS OWN force flag: the queue is shared, so a
                # racing non-force drain may execute an op the force sweep
                # enqueued — it must still bypass the backoff.
                self.flush_ops.enqueue((tenant, bid), prio,
                                       (tenant, bid, force))
            inst.clear_flushed()

        done_lock = threading.Lock()

        def drain():
            while True:
                op = self.flush_ops.dequeue()
                if op is None:
                    return
                key, (tenant, bid, op_force) = op
                try:
                    meta = self.instance(tenant).complete_one(
                        block_id=bid, ignore_backoff=op_force)
                    if meta is not None:
                        with done_lock:
                            completed.append(meta)
                except Exception:  # noqa: BLE001 — block backed off in
                    pass           # completing; a later sweep re-enqueues
                finally:
                    self.flush_ops.done(key)

        n = min(self.concurrent_flushes, len(self.flush_ops))
        if n <= 1:
            drain()
        else:
            threads = [threading.Thread(target=drain, name=f"flush-{i}")
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        self._publish_queue_state()
        return completed

    def _publish_queue_state(self) -> None:
        """Post-drain backlog gauges: per-tenant flush-queue depth and
        the age of the oldest trace not yet flushed (head + completing)
        — the white-box 'how far behind is this ingester' signal."""
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        if not TELEMETRY.enabled:
            return
        now = time.monotonic()
        for tenant in self.tenants():
            inst = self.instance(tenant)
            with inst.lock:
                qlen = len(inst.completing)
                # replayed blocks carry no push stamp (oldest_ingest is
                # None) — fall back to their enqueue time so a wedged
                # post-restart backlog ages instead of reading 0
                candidates = [c.oldest_ingest if c.oldest_ingest is not None
                              else c.cut_at
                              for c in inst.completing if c.cut_at]
                if len(inst.head) and inst.head_oldest != float("inf"):
                    candidates.append(inst.head_oldest)
                live_oldest = [t.first_push
                               for t in inst.live.values() if t.first_push]
                if live_oldest:
                    candidates.append(min(live_oldest))
            oldest = min(candidates, default=None)
            TELEMETRY.set_queue_state(
                tenant, qlen, (now - oldest) if oldest is not None else 0.0)

    def flush_all(self, settle_timeout_s: float = 60.0) -> list:
        """Graceful shutdown / scale-down: force everything to the backend
        (reference /shutdown handler flush.go:91-115). Loops until no
        completing blocks remain. A pass that completes nothing is only
        counted as stalled after all in-flight completions have settled —
        a racing periodic sweep's drain thread may hold the op for a
        streaming completion that takes minutes, during which our own
        passes are no-ops by design (ExclusiveQueue dedupe). Two settled
        no-progress passes mean the backend is genuinely down; then we
        raise FlushIncompleteError so the caller cannot mistake a partial
        flush for success and delete the node's WAL disk.

        settle_timeout_s bounds the wait for RACING in-flight completions
        (a periodic sweep's drain thread holding the op) so they cannot
        pin shutdown indefinitely; a false stall only raises — the WAL
        stays on disk and the racing completion, if any, still finishes.
        It does NOT bound the backend writes our own passes issue: those
        rely on the backend transport's request timeouts (a local/memory
        backend cannot blackhole; cloud backends go through the
        timeout-carrying instrumented transport)."""
        completed: list = []
        stalled = 0
        while stalled < 2:
            before = len(completed)
            completed += self.sweep(force=True)
            if not self._blocks_left():
                return completed
            if len(completed) == before:
                self._wait_inflight_settled(settle_timeout_s)
                if not self._blocks_left():
                    return completed
                stalled += 1
            else:
                stalled = 0
        # raise only — callers own the logging (double error lines per
        # ingester otherwise)
        raise FlushIncompleteError(left_behind=self._blocks_left(),
                                   completed=completed)

    def _blocks_left(self) -> int:
        with self._lock:
            insts = list(self._instances.values())
        return sum(len(i.completing) for i in insts)

    def _wait_inflight_settled(self, timeout_s: float) -> None:
        """Block until no completion op is executing anywhere — neither a
        block marked in_flight nor a claimed-but-unreleased flush-op key
        (the window between dequeue() and complete_one picking the
        block)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                insts = list(self._instances.values())
            busy = self.flush_ops.in_flight() > 0 or any(
                c.in_flight for i in insts for c in i.completing)
            if not busy:
                return
            time.sleep(0.05)

    # ---- replay (reference replayWal ingester.go:327-416) ----

    def _replay(self) -> None:
        from tempo_tpu.observability import get_logger
        from tempo_tpu.observability.ingest_telemetry import TELEMETRY

        blocks, _removed = self.db.wal.replay_all()
        stats = self.db.wal.last_replay or {}
        # replay happens exactly once per process start and gates
        # readiness — log it always, export it when telemetry is on, so
        # a 90-second restart is attributable to the N GB it re-scanned
        if blocks or stats.get("removed_files"):
            get_logger("tempo_tpu.ingester").info(
                "wal replay: %d block(s), %d bytes, %d corrupt record(s) "
                "dropped, %d file(s) removed in %.3fs",
                stats.get("blocks", 0), stats.get("bytes", 0),
                stats.get("corrupt_records", 0),
                stats.get("removed_files", 0),
                stats.get("duration_s", 0.0))
        if TELEMETRY.enabled:
            TELEMETRY.record_wal_replay(
                stats.get("duration_s", 0.0), stats.get("blocks", 0),
                stats.get("bytes", 0), stats.get("corrupt_records", 0))
        for blk in blocks:
            tenant = blk.meta.tenant_id
            inst = self.instance(tenant)
            import os

            spath = blk.path + ".search"
            if os.path.exists(spath):
                ssb = StreamingSearchBlock.rescan(spath)
            else:
                ssb = StreamingSearchBlock(spath)
            # replayed head blocks go straight to completing: they will be
            # completed by the next sweep (reference re-enqueues completion
            # ops for replayed blocks). cut_at stamps NOW — the traces'
            # real push times predate this process, so the queue-age
            # gauge counts from restart (it must read nonzero and GROW
            # while a backlogged restart can't flush, not report 0 =
            # "fully flushed"); oldest_ingest stays None so the
            # push_to_searchable histogram is never fed restart-relative
            # values
            inst.completing.append(_Completing(blk, ssb,
                                               cut_at=time.monotonic()))
            self.replayed_blocks += 1
