"""Metrics generator: span-derived metrics.

Role-equivalent to the reference's modules/generator (SURVEY.md §2.2):
consumes span pushes (distributor forwarder) and derives Prometheus
metrics per tenant via two processors:

  - spanmetrics (spanmetrics.go:34-88): calls_total + latency histogram
    by (service, span_name, span_kind, status_code)
  - service-graphs (servicegraphs.go:56-248): client/server span pairing
    via an expiring edge store → request/failure counts + latency per
    (client, server) edge

plus a ManagedRegistry with per-tenant active-series limits and staleness
expiry (registry/registry.go:51-226). The reference remote-writes to
Prometheus; here samples export through the shared /metrics registry (no
network egress in this environment; a remote-write client slots in where
`collect` drains samples).
"""

from __future__ import annotations

import threading
import time

from tempo_tpu import tempopb
from tempo_tpu.observability.metrics import Registry, Counter, Histogram

LATENCY_BUCKETS_S = (0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128,
                     0.256, 0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


class SpanMetricsProcessor:
    def __init__(self, registry: Registry):
        self.calls = Counter("traces_spanmetrics_calls_total",
                             "span call counts", registry=registry)
        self.latency = Histogram("traces_spanmetrics_latency",
                                 "span latency (s)",
                                 buckets=LATENCY_BUCKETS_S, registry=registry)

    # enum int → name, resolved once: proto .Name() does a descriptor
    # lookup per call, and this runs per SPAN on the ack path
    _KIND_NAMES = {v.number: v.name
                   for v in tempopb.Span.SpanKind.DESCRIPTOR.values}
    _STATUS_NAMES = {v.number: v.name
                     for v in tempopb.Status.StatusCode.DESCRIPTOR.values}

    def consume(self, batch: tempopb.ResourceSpans) -> None:
        svc = ""
        for kv in batch.resource.attributes:
            if kv.key == "service.name":
                svc = kv.value.string_value
        kind_names, status_names = self._KIND_NAMES, self._STATUS_NAMES
        for ss in batch.scope_spans:
            for span in ss.spans:
                labels = dict(
                    service=svc, span_name=span.name,
                    span_kind=kind_names.get(span.kind, str(span.kind)),
                    status_code=status_names.get(span.status.code,
                                                 str(span.status.code)),
                )
                self.calls.inc(**labels)
                dur_s = max(0, span.end_time_unix_nano
                            - span.start_time_unix_nano) / 1e9
                self.latency.observe(dur_s, **labels)


class ServiceGraphProcessor:
    """Pairs client spans with the server spans they called (matched by
    (trace id, client span id == server parent id)) through an expiring
    store; completed pairs emit one edge sample."""

    def __init__(self, registry: Registry, wait_s: float = 10.0,
                 max_items: int = 10_000):
        self.requests = Counter("traces_service_graph_request_total",
                                "edge request counts", registry=registry)
        self.failed = Counter("traces_service_graph_request_failed_total",
                              "edge failures", registry=registry)
        self.latency = Histogram("traces_service_graph_request_seconds",
                                 "edge client latency (s)",
                                 buckets=LATENCY_BUCKETS_S, registry=registry)
        self.wait_s = wait_s
        self.max_items = max_items
        self._store: dict[tuple, tuple] = {}  # key -> (kind, svc, span, t)
        self._lock = threading.Lock()
        self.expired = 0
        self._last_expire = 0.0

    def consume(self, batch: tempopb.ResourceSpans) -> None:
        svc = ""
        for kv in batch.resource.attributes:
            if kv.key == "service.name":
                svc = kv.value.string_value
        now = time.monotonic()
        for ss in batch.scope_spans:
            for span in ss.spans:
                if span.kind == tempopb.Span.SPAN_KIND_CLIENT:
                    key = (bytes(span.trace_id), bytes(span.span_id))
                    self._pair(key, "client", svc, span, now)
                elif span.kind == tempopb.Span.SPAN_KIND_SERVER:
                    key = (bytes(span.trace_id), bytes(span.parent_span_id))
                    self._pair(key, "server", svc, span, now)
        # amortize: an O(store) expiry sweep per BATCH was a steady tax
        # on the ack path; unpaired edges only need to age out at wait_s
        # granularity, so sweep at most once per wait_s/4
        if now - self._last_expire >= self.wait_s / 4:
            self._last_expire = now
            self._expire(now)

    def _pair(self, key, kind, svc, span, now) -> None:
        with self._lock:
            other = self._store.get(key)
            if other is None or other[0] == kind:
                if len(self._store) >= self.max_items:
                    # amortized expiry must not turn the cap into edge
                    # loss: expired entries may be squatting the slots —
                    # sweep NOW and retry the insert (inline expiry, the
                    # lock is already held)
                    dead = [k for k, v in self._store.items()
                            if now - v[3] > self.wait_s]
                    for k in dead:
                        del self._store[k]
                    self.expired += len(dead)
                if len(self._store) < self.max_items:
                    self._store[key] = (
                        kind, svc, span.SerializeToString(), now
                    )
                return
            del self._store[key]
        o_kind, o_svc, o_span_b, _ = other
        o_span = tempopb.Span()
        o_span.ParseFromString(o_span_b)
        if kind == "client":
            client_svc, server_svc, client_span = svc, o_svc, span
            server_span = o_span
        else:
            client_svc, server_svc, client_span = o_svc, svc, o_span
            server_span = span
        labels = dict(client=client_svc, server=server_svc)
        self.requests.inc(**labels)
        if (client_span.status.code == tempopb.Status.STATUS_CODE_ERROR
                or server_span.status.code == tempopb.Status.STATUS_CODE_ERROR):
            self.failed.inc(**labels)
        dur_s = max(0, client_span.end_time_unix_nano
                    - client_span.start_time_unix_nano) / 1e9
        self.latency.observe(dur_s, **labels)

    def _expire(self, now) -> None:
        with self._lock:
            dead = [k for k, v in self._store.items()
                    if now - v[3] > self.wait_s]
            for k in dead:
                del self._store[k]
            self.expired += len(dead)


class ManagedRegistry(Registry):
    """Registry with an active-series cap per tenant (reference
    registry.go: max_active_series drops new series when exceeded)."""

    def __init__(self, max_active_series: int = 100_000):
        super().__init__()
        self.max_active_series = max_active_series

    def active_series(self) -> int:
        n = 0
        for m in self._metrics.values():
            n += len(getattr(m, "_series", ())) + len(getattr(m, "_counts", ()))
        return n

    def over_limit(self) -> bool:
        return self.active_series() >= self.max_active_series


class MetricsGenerator:
    """Per-tenant processor instances fed by the distributor forwarder."""

    def __init__(self, max_active_series: int = 100_000,
                 processors: tuple = ("span-metrics", "service-graphs")):
        self.max_active_series = max_active_series
        self.processors = processors
        self._tenants: dict[str, tuple] = {}
        self._lock = threading.Lock()
        self.dropped_over_limit = 0

    def _instance(self, tenant: str):
        with self._lock:
            inst = self._tenants.get(tenant)
            if inst is None:
                reg = ManagedRegistry(self.max_active_series)
                procs = []
                if "span-metrics" in self.processors:
                    procs.append(SpanMetricsProcessor(reg))
                if "service-graphs" in self.processors:
                    procs.append(ServiceGraphProcessor(reg))
                inst = self._tenants[tenant] = (reg, procs)
            return inst

    def push_spans(self, tenant: str, batches) -> None:
        reg, procs = self._instance(tenant)
        if reg.over_limit():
            self.dropped_over_limit += 1
            return
        for batch in batches:
            for p in procs:
                p.consume(batch)

    def tenants(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def registry(self, tenant: str):
        return self._instance(tenant)[0]

    def collect(self, tenant: str) -> str:
        """Exposition-format samples for a tenant (the remote-write drain
        point)."""
        reg, _ = self._instance(tenant)
        return reg.expose()
