"""Metrics generator: span-derived metrics.

Role-equivalent to the reference's modules/generator (SURVEY.md §2.2):
consumes span pushes (distributor forwarder) and derives Prometheus
metrics per tenant via two processors:

  - spanmetrics (spanmetrics.go:34-88): calls_total + latency histogram
    by (service, span_name, span_kind, status_code)
  - service-graphs (servicegraphs.go:56-248): client/server span pairing
    via an expiring edge store → request/failure counts + latency per
    (client, server) edge

plus a ManagedRegistry with per-tenant active-series limits and staleness
expiry (registry/registry.go:51-226). The reference remote-writes to
Prometheus; here samples export through the shared /metrics registry (no
network egress in this environment; a remote-write client slots in where
`collect` drains samples).
"""

from __future__ import annotations

import struct
import threading
import time
from collections import OrderedDict

from tempo_tpu import tempopb

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
from tempo_tpu.observability.metrics import Registry, Counter, Histogram
from tempo_tpu.search.analytics import ANALYTICS
from tempo_tpu.search.data import _any_value_str

LATENCY_BUCKETS_S = (0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128,
                     0.256, 0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


class SpanMetricsProcessor:
    def __init__(self, registry: Registry):
        self.calls = Counter("traces_spanmetrics_calls_total",
                             "span call counts", registry=registry)
        self.latency = Histogram("traces_spanmetrics_latency",
                                 "span latency (s)",
                                 buckets=LATENCY_BUCKETS_S, registry=registry)
        # bound-handle cache, LRU by last touch: the runaway-cardinality
        # cap evicts the COLDEST series, not the oldest-created one
        self._series: OrderedDict[tuple, tuple] = OrderedDict()

    # enum int → name, resolved once: proto .Name() does a descriptor
    # lookup per call, and this runs per SPAN on the ack path
    _KIND_NAMES = {v.number: v.name
                   for v in tempopb.Span.SpanKind.DESCRIPTOR.values}
    _STATUS_NAMES = {v.number: v.name
                     for v in tempopb.Status.StatusCode.DESCRIPTOR.values}

    def _series_touch(self, sk: tuple) -> tuple:
        """Bound handles for one (service, span_name, kind, status)
        series, LRU-touched: a hit moves the series to the hot end so
        the cap evicts the least-recently-SEEN series — the old
        ``pop(next(iter(...)))`` was FIFO insertion order and rebuilt
        hot series under churn."""
        series = self._series
        hit = series.get(sk)
        if hit is not None:
            series.move_to_end(sk)
            return hit
        svc, name, kind, status = sk
        labels = dict(
            service=svc, span_name=name,
            span_kind=self._KIND_NAMES.get(kind, str(kind)),
            status_code=self._STATUS_NAMES.get(status, str(status)),
        )
        hit = series[sk] = (self.calls.labels(**labels),
                            self.latency.labels(**labels))
        while len(series) > 65_536:  # runaway-cardinality cap
            series.popitem(last=False)
        return hit

    def consume(self, batch: tempopb.ResourceSpans) -> None:
        svc = ""
        for kv in batch.resource.attributes:
            if kv.key == "service.name":
                # stringified AnyValue, not .string_value: a non-string
                # service.name ('true', '123') must yield the same series
                # as search-data extraction and the native summary feed
                svc = _any_value_str(kv.value)
        for ss in batch.scope_spans:
            for span in ss.spans:
                c, h = self._series_touch(
                    (svc, span.name, span.kind, span.status.code))
                c.inc()
                dur_s = max(0, span.end_time_unix_nano
                            - span.start_time_unix_nano) / 1e9
                h.observe(dur_s)

    def consume_rows(self, strs, rows, tids) -> None:
        """Native summary-row feed — same series as consume()."""
        for (_ti, svc_i, name_i, kind, status, _flags,
             start, end, _sid, _pid) in rows:
            c, h = self._series_touch(
                (strs[svc_i], strs[name_i], kind, status))
            c.inc()
            h.observe(max(0, end - start) / 1e9)


class ServiceGraphProcessor:
    """Pairs client spans with the server spans they called (matched by
    (trace id, client span id == server parent id)) through an expiring
    store; completed pairs emit one edge sample."""

    def __init__(self, registry: Registry, wait_s: float = 10.0,
                 max_items: int = 10_000):
        self.requests = Counter("traces_service_graph_request_total",
                                "edge request counts", registry=registry)
        self.failed = Counter("traces_service_graph_request_failed_total",
                              "edge failures", registry=registry)
        self.latency = Histogram("traces_service_graph_request_seconds",
                                 "edge client latency (s)",
                                 buckets=LATENCY_BUCKETS_S, registry=registry)
        self.expired_total = Counter(
            "traces_servicegraph_expired_total",
            "unpaired edges dropped by the expiry sweep before their "
            "partner span arrived", registry=registry)
        self.wait_s = wait_s
        self.max_items = max_items
        # each sweep evicts at most this many entries — a burst of
        # unpaired edges must not stall the ack path under the lock
        self.max_expire_per_sweep = 1024
        self._store: dict[tuple, tuple] = {}  # key -> (kind, svc, span, t)
        self._lock = threading.Lock()
        self.expired = 0
        self._last_expire = 0.0

    def consume(self, batch: tempopb.ResourceSpans) -> None:
        svc = ""
        for kv in batch.resource.attributes:
            if kv.key == "service.name":
                svc = _any_value_str(kv.value)  # match the native feed
        now = time.monotonic()
        for ss in batch.scope_spans:
            for span in ss.spans:
                if span.kind == tempopb.Span.SPAN_KIND_CLIENT:
                    key = (bytes(span.trace_id), bytes(span.span_id))
                    self._pair(key, "client", svc,
                               (span.status.code, span.start_time_unix_nano,
                                span.end_time_unix_nano), now)
                elif span.kind == tempopb.Span.SPAN_KIND_SERVER:
                    key = (bytes(span.trace_id), bytes(span.parent_span_id))
                    self._pair(key, "server", svc,
                               (span.status.code, span.start_time_unix_nano,
                                span.end_time_unix_nano), now)
        self._maybe_expire(now)

    def consume_rows(self, strs, rows, tids) -> None:
        """Native summary-row feed: same pairing store as consume().
        Span/parent ids arrive zero-padded to 8 bytes — both sides of a
        pair use the same padding, so keys match (OTLP span ids are 8
        bytes on the wire anyway)."""
        now = time.monotonic()
        for (ti, svc_i, _name_i, kind, status, _flags,
             start, end, sid, pid) in rows:
            if kind == 3:    # SPAN_KIND_CLIENT
                self._pair((tids[ti], sid), "client", strs[svc_i],
                           (status, start, end), now)
            elif kind == 2:  # SPAN_KIND_SERVER
                self._pair((tids[ti], pid), "server", strs[svc_i],
                           (status, start, end), now)
        self._maybe_expire(now)

    def _pair(self, key, kind, svc, surrogate, now) -> None:
        em = self._pair_collect(key, kind, svc, surrogate, now)
        if em is not None:
            self._emit(em)

    def _pair_collect(self, key, kind, svc, surrogate, now):
        """One pairing-store round-trip; surrogate is (status_code,
        start_ns, end_ns) — all the edge emission needs. Returns the
        emission tuple (client_svc, server_svc, c_status, s_status,
        c_start, c_end) when the pair completed, else None — the
        batched analytics path collects emissions and counts them in
        one pass, the walk emits each immediately via _pair."""
        with self._lock:
            other = self._store.get(key)
            if other is None or other[0] == kind:
                if len(self._store) >= self.max_items:
                    # amortized expiry must not turn the cap into edge
                    # loss: expired entries may be squatting the slots —
                    # sweep NOW and retry the insert (inline expiry, the
                    # lock is already held)
                    self._sweep_locked(now)
                if len(self._store) < self.max_items:
                    self._store[key] = (kind, svc, surrogate, now)
                return None
            del self._store[key]
        o_kind, o_svc, o_sur, _ = other
        if kind == "client":
            c_status, c_start, c_end = surrogate
            return (svc, o_svc, c_status, o_sur[0], c_start, c_end)
        c_status, c_start, c_end = o_sur
        return (o_svc, svc, c_status, surrogate[0], c_start, c_end)

    def _emit(self, em) -> None:
        client_svc, server_svc, c_status, s_status, c_start, c_end = em
        labels = dict(client=client_svc, server=server_svc)
        self.requests.inc(**labels)
        ERR = tempopb.Status.STATUS_CODE_ERROR
        if c_status == ERR or s_status == ERR:
            self.failed.inc(**labels)
        self.latency.observe(max(0, c_end - c_start) / 1e9, **labels)

    def _sweep_locked(self, now) -> None:
        """One bounded expiry sweep (lock held): at most
        max_expire_per_sweep evictions per call, booked to the
        per-tenant traces_servicegraph_expired_total counter."""
        dead = []
        limit = self.max_expire_per_sweep
        for k, v in self._store.items():
            if now - v[3] > self.wait_s:
                dead.append(k)
                if len(dead) >= limit:
                    break
        for k in dead:
            del self._store[k]
        if dead:
            self.expired += len(dead)
            self.expired_total.inc(len(dead))

    def _maybe_expire(self, now) -> None:
        # amortize: an O(store) expiry sweep per BATCH was a steady tax
        # on the ack path; unpaired edges only need to age out at wait_s
        # granularity, so sweep at most once per wait_s/4
        if now - self._last_expire >= self.wait_s / 4:
            self._last_expire = now
            self._expire(now)

    def _expire(self, now) -> None:
        with self._lock:
            self._sweep_locked(now)


class ManagedRegistry(Registry):
    """Registry with an active-series cap per tenant (reference
    registry.go: max_active_series drops new series when exceeded)."""

    def __init__(self, max_active_series: int = 100_000):
        super().__init__()
        self.max_active_series = max_active_series

    def active_series(self) -> int:
        n = 0
        for m in self._metrics.values():
            n += len(getattr(m, "_series", ())) + len(getattr(m, "_counts", ()))
        return n

    def over_limit(self) -> bool:
        return self.active_series() >= self.max_active_series


class MetricsGenerator:
    """Per-tenant processor instances fed by the distributor forwarder."""

    def __init__(self, max_active_series: int = 100_000,
                 processors: tuple = ("span-metrics", "service-graphs")):
        self.max_active_series = max_active_series
        self.processors = processors
        self._tenants: dict[str, tuple] = {}
        self._lock = threading.Lock()
        self.dropped_over_limit = 0

    def _instance(self, tenant: str):
        with self._lock:
            inst = self._tenants.get(tenant)
            if inst is None:
                reg = ManagedRegistry(self.max_active_series)
                procs = []
                if "span-metrics" in self.processors:
                    procs.append(SpanMetricsProcessor(reg))
                if "service-graphs" in self.processors:
                    procs.append(ServiceGraphProcessor(reg))
                inst = self._tenants[tenant] = (reg, procs)
            return inst

    def push_spans(self, tenant: str, batches) -> None:
        reg, procs = self._instance(tenant)
        if reg.over_limit():
            self.dropped_over_limit += 1
            return
        for batch in batches:
            for p in procs:
                p.consume(batch)

    def forward(self, tenant: str, payload) -> None:
        """Distributor forwarder entry: parsed batches, or the native
        walker's ("summaries", blob, tids) fast feed — fixed 56-byte
        rows decoded here (off the ack path) instead of a second proto
        walk per span."""
        if (isinstance(payload, tuple) and payload
                and payload[0] == "summaries"):
            self.push_summary_blob(tenant, payload[1], payload[2])
        else:
            self.push_spans(tenant, payload)

    forward.accepts_summaries = True  # distributor capability probe

    _ROW = struct.Struct("<6IQQ8s8s")  # native RowTmp layout (the ABI)

    def push_summary_blob(self, tenant: str, blob: bytes,
                          tids: list) -> None:
        reg, procs = self._instance(tenant)
        if reg.over_limit():
            self.dropped_over_limit += 1
            return
        (n_str,) = _U32.unpack_from(blob, 0)
        off = 4
        strs = []
        for _ in range(n_str):
            (ln,) = _U16.unpack_from(blob, off)
            off += 2
            strs.append(blob[off:off + ln].decode("utf-8", "replace"))
            off += ln
        (n_rows,) = _U32.unpack_from(blob, off)
        off += 4
        if ANALYTICS.enabled:
            if ANALYTICS.consume_blob(procs, strs, blob, off, n_rows,
                                      tids):
                return  # batched device reduction fed the same series
        rows = list(self._ROW.iter_unpack(
            blob[off:off + n_rows * self._ROW.size]))
        for p in procs:
            p.consume_rows(strs, rows, tids)

    def tenants(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def registry(self, tenant: str):
        return self._instance(tenant)[0]

    def collect(self, tenant: str) -> str:
        """Exposition-format samples for a tenant (the remote-write drain
        point)."""
        reg, _ = self._instance(tenant)
        return reg.expose()
