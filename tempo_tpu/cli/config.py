"""YAML config loading with env substitution and footgun warnings.

Role-equivalent to the reference's cmd/tempo config load (main.go:117-175
``-config.file`` + ``-config.expand-env``) and CheckConfig warnings
(app.go:136-164). The YAML tree mirrors AppConfig/TempoDBConfig fields:

    server:
      http_port: 3200
      grpc_port: 9095
    multitenancy_enabled: true
    storage:
      backend: local            # local | memory
      local: {path: /var/tempo/blocks}
      wal_dir: /var/tempo/wal
      block_encoding: zstd
      search_encoding: zstd
    ingester:
      n_ingesters: 1
      replication_factor: 1
      write_quorum: majority    # or "one" (RF=2 eventual consistency)
    querier:
      external_endpoints: []    # serverless search-worker URLs
    compactor: {window_s: 3600, max_inputs: 8}
    retention: {block_s: 1209600, compacted_s: 3600}
    overrides:
      defaults: {ingestion_rate_bytes: 15000000, ...}
      per_tenant: {tenant-a: {max_live_traces: 100000}}
"""

from __future__ import annotations

import os
import re

import yaml

from tempo_tpu.db import TempoDBConfig
from tempo_tpu.modules import AppConfig, Limits
from tempo_tpu.modules.frontend import FrontendConfig

_ENV_RE = re.compile(r"\$\{(\w+)(?::([^}]*))?\}")


def expand_env(text: str) -> str:
    """${VAR} / ${VAR:default} substitution (reference -config.expand-env)."""
    return _ENV_RE.sub(
        lambda m: os.environ.get(m.group(1), m.group(2) or ""), text
    )


def load_config(path: str | None = None, text: str | None = None) -> tuple[AppConfig, dict]:
    if text is None:
        text = open(path).read() if path else "{}"
    doc = yaml.safe_load(expand_env(text)) or {}

    # `or {}` throughout: a bare section key with its children commented
    # out parses to None, which must mean "all defaults", not a crash
    storage = doc.get("storage") or {}
    ingester = doc.get("ingester") or {}
    compactor = doc.get("compactor") or {}
    retention = doc.get("retention") or {}
    overrides = doc.get("overrides") or {}
    frontend_doc = doc.get("frontend") or {}
    querier_doc = doc.get("querier") or {}

    # self_tracing passes through to init_tracing as a dict, but the
    # dogfood knobs are read (and type-normalized) HERE explicitly so
    # the yaml-knob drift catalog pins them to documented rows
    # (docs/configuration.md; tests/test_config_docs.py)
    self_tracing = dict(doc.get("self_tracing") or {})
    self_tracing["selftrace_ingest_enabled"] = bool(
        self_tracing.get("selftrace_ingest_enabled", False))
    self_tracing["selftrace_flight_recorder_max"] = int(
        self_tracing.get("selftrace_flight_recorder_max", 32))

    db = TempoDBConfig(
        block_encoding=storage.get("block_encoding", "zstd"),
        wal_encoding=storage.get("wal_encoding", "auto"),
        search_encoding=storage.get("search_encoding", "zstd"),
        compaction_window_s=compactor.get("window_s", 3600),
        compaction_max_inputs=compactor.get("max_inputs", 8),
        retention_s=retention.get("block_s", 14 * 24 * 3600),
        compacted_retention_s=retention.get("compacted_s", 3600),
        blocklist_poll_s=storage.get("blocklist_poll_s", 30),
        # serving-tier budgets the runbook tells operators to raise
        # under staging pressure (/debug/scan)
        search_batch_cache_bytes=storage.get(
            "search_batch_cache_bytes", 4 << 30),
        search_host_cache_bytes=storage.get("search_host_cache_bytes"),
        search_prewarm_on_poll=storage.get("search_prewarm_on_poll", False),
        # cross-request query coalescing (docs/search-coalescing.md)
        search_coalesce_window_s=storage.get(
            "search_coalesce_window_s", 0.003),
        search_coalesce_max_queries=storage.get(
            "search_coalesce_max_queries", 8),
        # device-resident dictionary probe threshold
        # (docs/search-dict-probe.md); absent/null = library default
        # (50k distinct values), <= 0 = host-only probing
        search_device_probe_min_vals=storage.get(
            "search_device_probe_min_vals"),
        # dispatch profiler (docs/observability.md): per-dispatch stage
        # telemetry + /debug/profile; false is a true noop on the
        # dispatch hot path
        search_profiling_enabled=storage.get(
            "search_profiling_enabled", True),
        search_profiling_fence=storage.get(
            "search_profiling_fence", False),
        search_profiling_ring=storage.get("search_profiling_ring", 256),
        # per-query execution inspector (docs/search-query-stats.md):
        # per-tenant device-seconds accounting, slow-query log,
        # /debug/querystats, ?explain=1; false is a true noop on the
        # search path
        search_query_stats_enabled=storage.get(
            "search_query_stats_enabled", True),
        search_slow_query_log_s=storage.get(
            "search_slow_query_log_s", 10.0),
        search_query_stats_ring=storage.get(
            "search_query_stats_ring", 256),
        # adaptive host/device offload planner
        # (docs/search-offload-planner.md): cost-model placement of the
        # dictionary prefilter above the device-probe floor; false
        # (default) keeps the static threshold behavior exactly
        search_offload_planner_enabled=storage.get(
            "search_offload_planner_enabled", False),
        search_offload_planner_ewma=storage.get(
            "search_offload_planner_ewma", 0.25),
        search_offload_planner_ring=storage.get(
            "search_offload_planner_ring", 256),
        # hot-tier live search (docs/search-live-tail.md): in-flight
        # traces kernel-scan at query time and tail subscriptions
        # evaluate per push; false (default) is a true noop — live/WAL
        # search keeps the per-entry host walk byte-identically
        search_live_tier_enabled=storage.get(
            "search_live_tier_enabled", False),
        search_live_tier_max_entries=storage.get(
            "search_live_tier_max_entries", 4096),
        search_live_tail_max_subscriptions=storage.get(
            "search_live_tail_max_subscriptions", 16),
        # device-side aggregate analytics (docs/search-analytics.md):
        # batched RED/service-graph reductions on the generator feed +
        # query-time ?agg=; false (default) is a true noop and the
        # drained series are byte-identical either way
        search_analytics_enabled=storage.get(
            "search_analytics_enabled", False),
        search_analytics_min_rows=storage.get(
            "search_analytics_min_rows", 64),
        # packed HBM residency (docs/search-packed-residency.md):
        # bit-width-adaptive staged columns + in-kernel unpack; false
        # (default) is a true noop and byte-identical either way
        search_packed_residency=storage.get(
            "search_packed_residency", False),
        # structural query engine (docs/search-structural-queries.md):
        # the ?q= IR compiled onto the fused scan kernels; false
        # (default) is a true noop on the legacy search path
        search_structural_enabled=storage.get(
            "search_structural_enabled", False),
        search_structural_max_spans=storage.get(
            "search_structural_max_spans", 512),
        search_structural_max_span_kvs=storage.get(
            "search_structural_max_span_kvs", 16),
        search_structural_stack_enabled=storage.get(
            "search_structural_stack_enabled", False),
        search_structural_shard_spans=storage.get(
            "search_structural_shard_spans", False),
        # shape-bucketed cross-plan stacking + remainder-shard staging
        # (docs/search-structural-queries.md#shape-bucketed-stacking):
        # both false (default) are true noops and byte-identical on
        search_structural_bucket_enabled=storage.get(
            "search_structural_bucket_enabled", False),
        search_structural_bucket_max_nodes=storage.get(
            "search_structural_bucket_max_nodes", 16),
        search_structural_remainder_pages=storage.get(
            "search_structural_remainder_pages", False),
        # persistent XLA compile cache for the search kernels
        # (docs/search-packed-residency.md#persistent-compile-cache);
        # empty = off, hits surface as jit_cache_events{result=persisted}
        search_compile_cache_dir=storage.get(
            "search_compile_cache_dir", ""),
        # owner-routed HBM (docs/search-hbm-ownership.md): consistent-
        # hash block-group ownership across the fleet; false (default)
        # is a true noop, members/self auto-derive from the multihost
        # env contract when left empty
        search_hbm_ownership_enabled=storage.get(
            "search_hbm_ownership_enabled", False),
        search_hbm_ownership_members=storage.get(
            "search_hbm_ownership_members", ""),
        search_hbm_ownership_self=storage.get(
            "search_hbm_ownership_self", ""),
        search_hbm_ownership_groups=storage.get(
            "search_hbm_ownership_groups", 64),
        # heat-adaptive replication + hedged dispatch
        # (docs/search-hbm-ownership.md#replication-heat-and-hedged-
        # dispatch): rf=1 (default) keeps single-owner placement bit
        # for bit — heat table, replica lookups and hedge timer are
        # each one attribute read
        search_hbm_ownership_rf=storage.get(
            "search_hbm_ownership_rf", 1),
        search_hbm_ownership_hot_rate=storage.get(
            "search_hbm_ownership_hot_rate", 50.0),
        search_hedge_delay_ms=storage.get(
            "search_hedge_delay_ms", 0.0),
        # robustness (docs/robustness.md): device dispatch watchdog,
        # collective-lock bound, request deadlines, circuit breaker,
        # fault-injection arming. Breaker off + faults disarmed is a
        # true noop on the dispatch path.
        search_device_dispatch_timeout_s=storage.get(
            "search_device_dispatch_timeout_s", 30.0),
        search_dispatch_lock_timeout_s=storage.get(
            "search_dispatch_lock_timeout_s", 60.0),
        search_request_timeout_s=storage.get(
            "search_request_timeout_s", 0.0),
        search_breaker_enabled=storage.get("search_breaker_enabled", True),
        search_breaker_fault_threshold=storage.get(
            "search_breaker_fault_threshold", 3),
        search_breaker_window_s=storage.get(
            "search_breaker_window_s", 30.0),
        search_breaker_cooldown_s=storage.get(
            "search_breaker_cooldown_s", 5.0),
        robustness_faults=storage.get("robustness_faults", ""),
        # restartable host state (header snapshot + persistent XLA
        # compile cache); absent = auto (<wal_dir>/host-state), "" = off
        host_state_dir=storage.get("host_state_dir"),
    )
    cfg = AppConfig(
        backend={
            "backend": storage.get("backend", "local"),
            "local": storage.get("local", {"path": "./tempo-blocks"}),
            "s3": storage.get("s3", {}),
            "gcs": storage.get("gcs", {}),
            "azure": storage.get("azure", {}),
        },
        cache=storage.get("cache", {}),
        wal_dir=storage.get("wal_dir", "./tempo-wal"),
        n_ingesters=ingester.get("n_ingesters", 1),
        replication_factor=ingester.get("replication_factor", 1),
        write_quorum=ingester.get("write_quorum", "majority"),
        external_endpoints=querier_doc.get("external_endpoints", []),
        # frontend: {query_shards, max_concurrent_jobs, retries,
        # tolerate_failed_blocks, max_outstanding_per_tenant,
        # target_bytes_per_job, batch_jobs_per_request} — sharding/queue
        # knobs (reference query_frontend block)
        frontend=FrontendConfig(**{
            k: v for k, v in frontend_doc.items()
            if k in FrontendConfig.__dataclass_fields__
        }),
        frontend_worker_parallelism=querier_doc.get(
            "frontend_worker_parallelism", 2),
        frontend_grpc_max_workers=frontend_doc.get("grpc_max_workers", 256),
        flush_tick_s=ingester.get("flush_tick_s", 10.0),
        # write-path telemetry + freshness canary
        # (docs/observability.md write-path section): telemetry-off is a
        # true noop on the ingest path; the canary is opt-in because it
        # writes real (tiny) blocks into its tenant every interval
        ingest_telemetry_enabled=ingester.get(
            "ingest_telemetry_enabled", True),
        ingest_slow_flush_log_s=ingester.get(
            "ingest_slow_flush_log_s", 30.0),
        ingest_canary_enabled=ingester.get("ingest_canary_enabled", False),
        ingest_canary_interval_s=ingester.get(
            "ingest_canary_interval_s", 30.0),
        ingest_canary_tenant=ingester.get("ingest_canary_tenant", "canary"),
        poll_tick_s=storage.get("poll_tick_s", 30.0),
        compaction_tick_s=compactor.get("tick_s", 30.0),
        db=db,
        limits=Limits(**{
            k: v for k, v in overrides.get("defaults", {}).items()
            if k in Limits.__dataclass_fields__
        }),
        per_tenant_overrides=overrides.get("per_tenant", {}),
        self_tracing=self_tracing,
        metrics_generator=doc.get("metrics_generator", {}),
        receivers=doc.get("distributor", {}).get("receivers", {}),
    )
    server = doc.get("server", {})
    runtime = {
        "http_port": server.get("http_port", 3200),
        "grpc_port": server.get("grpc_port", 9095),
        # jaeger agent UDP ingest (compact/binary thrift emitBatch);
        # 0/absent = disabled, 6831 is the jaeger default
        "jaeger_agent_port": server.get("jaeger_agent_port", 0),
        # /debug/* (stack dumps, scan internals) off by default on the
        # serving port; flip on for a triage session or bind a separate
        # admin ingress to a debug-enabled target (ADVICE r4)
        "debug_endpoints": server.get("debug_endpoints", False),
        "multitenancy": doc.get("multitenancy_enabled", True),
        # memberlist: {bind: "host:port", join: [addr, ...], advertise_host,
        # gossip_interval_s, suspect_timeout_s} — multi-process gossip
        "memberlist": doc.get("memberlist", {}),
        "instance_id": doc.get("instance_id", ""),
        # multi-host mesh: {coordinator: "host:port", num_processes,
        # process_id, cpu_devices_per_host} — env-substitutable
        # (${TEMPO_PROCESS_ID}); empty/absent = single host. A v5e-64
        # (BASELINE config 5) is coordinator + num_processes: 16 (4 chips
        # per host), the scan mesh axis spanning all 64 chips.
        "distributed": doc.get("distributed", {}),
        "warnings": check_config(cfg, doc),
    }
    return cfg, runtime


def check_config(cfg: AppConfig, doc: dict) -> list[str]:
    warnings = []
    if cfg.replication_factor > cfg.n_ingesters:
        warnings.append(
            f"replication_factor ({cfg.replication_factor}) exceeds ingester "
            f"count ({cfg.n_ingesters}); writes will fail quorum"
        )
    if cfg.db.compacted_retention_s == 0:
        warnings.append(
            "compacted block retention is 0: compacted blocks are deleted "
            "immediately, racing in-flight queries"
        )
    if cfg.backend.get("backend") == "memory":
        warnings.append("memory backend: data does not survive restarts")
    return warnings
