"""Offline block tooling: ``python -m tempo_tpu.cli.blocks <cmd>``.

Role-equivalent to cmd/tempo-cli (main.go:38-72): list/view blocks and
indexes, regenerate index/bloom from block data, search backend blocks
directly (the CPU-baseline harness role), and query a running server's
HTTP API.
"""

from __future__ import annotations

import argparse
import json
import sys

from tempo_tpu.backend import LocalBackend, BlockMeta, bloom_name, NAME_DATA, NAME_INDEX
from tempo_tpu.encoding.v2 import (
    BackendBlock,
    IndexReader,
    IndexWriter,
    Record,
    ShardedBloom,
    decompress,
)
from tempo_tpu.encoding.v2.objects import unmarshal_objects
from tempo_tpu.utils.ids import hex_to_trace_id


def cmd_list_blocks(be, args):
    rows = []
    for bid in be.list_blocks(args.tenant):
        try:
            m = be.read_block_meta(args.tenant, bid)
            rows.append({"id": bid, "objects": m.total_objects,
                         "size": m.size, "level": m.compaction_level,
                         "start": m.start_time, "end": m.end_time})
        except Exception:
            try:
                cm = be.read_compacted_meta(args.tenant, bid)
                rows.append({"id": bid, "compacted_at": cm.compacted_time})
            except Exception:
                rows.append({"id": bid, "state": "torn"})
    print(json.dumps(rows, indent=2))


def cmd_view_block(be, args):
    m = be.read_block_meta(args.tenant, args.block)
    out = json.loads(m.to_json())
    idx = IndexReader(be.read(args.tenant, args.block, NAME_INDEX))
    out["index_records"] = len(idx)
    out["pages"] = [
        {"max_id": bytes(idx.ids[i]).hex(), "start": int(idx.starts[i]),
         "len": int(idx.lengths[i])}
        for i in range(min(len(idx), args.limit))
    ]
    print(json.dumps(out, indent=2))


def cmd_find(be, args):
    m = be.read_block_meta(args.tenant, args.block)
    obj = BackendBlock(be, m).find_by_id(hex_to_trace_id(args.trace_id))
    if obj is None:
        print("not found", file=sys.stderr)
        return 1
    from tempo_tpu.model import codec_for

    tr = codec_for(m.data_encoding).prepare_for_read(obj)
    from google.protobuf import json_format

    print(json_format.MessageToJson(tr))
    return 0


def cmd_gen_index(be, args):
    """Rebuild the index from block data (disaster recovery)."""
    m = be.read_block_meta(args.tenant, args.block)
    data = be.read(args.tenant, args.block, NAME_DATA)
    idx = IndexReader(be.read(args.tenant, args.block, NAME_INDEX))
    records = []
    for i in range(len(idx)):
        page = decompress(
            data[int(idx.starts[i]): int(idx.starts[i]) + int(idx.lengths[i])],
            m.encoding,
        )
        last = None
        for oid, _ in unmarshal_objects(page):
            last = oid
        if last is not None:
            records.append(Record(last, int(idx.starts[i]), int(idx.lengths[i])))
    be.write(args.tenant, args.block, NAME_INDEX,
             IndexWriter(m.index_page_size or 1024).write(records))
    print(f"rebuilt index: {len(records)} records")


def cmd_gen_bloom(be, args):
    """Rebuild bloom shards from block data."""
    m = be.read_block_meta(args.tenant, args.block)
    bb = BackendBlock(be, m)
    ids = [oid for oid, _ in bb.iter_objects()]
    shards = max(1, m.bloom_shard_count or 1)
    bloom = ShardedBloom(shards, expected_per_shard=max(1, len(ids) // shards))
    bloom.add_many(ids)
    for s in range(bloom.shard_count):
        be.write(args.tenant, args.block, bloom_name(s), bloom.marshal_shard(s))
    print(f"rebuilt {bloom.shard_count} bloom shards over {len(ids)} ids")


def cmd_search(be, args):
    """Search backend blocks directly (no server) — the offline harness."""
    from tempo_tpu import tempopb
    from tempo_tpu.search import SearchResults
    from tempo_tpu.search.backend_search_block import BackendSearchBlock

    from tempo_tpu.api.params import _duration_ms

    req = tempopb.SearchRequest()
    for pair in args.tags or []:
        k, _, v = pair.partition("=")
        req.tags[k] = v
    req.limit = args.limit
    if args.min_duration:
        req.min_duration_ms = _duration_ms(args.min_duration)
    if args.max_duration:
        req.max_duration_ms = _duration_ms(args.max_duration)
    req.start = args.start
    req.end = args.end
    results = SearchResults(limit=args.limit)
    for bid in be.list_blocks(args.tenant):
        try:
            m = be.read_block_meta(args.tenant, bid)
        except Exception:
            continue
        BackendSearchBlock(be, m).search(req, results)
        if results.complete:
            break
    resp = results.response()
    from google.protobuf import json_format

    print(json_format.MessageToJson(resp))


def cmd_import_ref(be, args) -> int:
    """Import a Go-written v2 block directory into this backend
    (db/importer.py — VERDICT r4 #5 migration path)."""
    import tempfile

    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.db.importer import dir_reader, import_reference_block

    with tempfile.TemporaryDirectory() as wal:
        db = TempoDB(be, wal, TempoDBConfig(host_state_dir=""))
        meta = import_reference_block(dir_reader(args.src_dir), db,
                                      args.tenant)
    print(json.dumps({"imported_block": meta.block_id,
                      "objects": meta.total_objects}))
    return 0


def main(argv=None) -> int:
    # JAX_PLATFORMS must apply through jax.config BEFORE any device op
    # (a registered TPU plugin otherwise handshakes its tunnel even for
    # cpu-targeted runs and hangs when it is unhealthy — utils/jaxenv.py)
    from tempo_tpu.utils.jaxenv import honor_jax_platforms

    honor_jax_platforms()
    p = argparse.ArgumentParser("tempo-tpu-cli")
    p.add_argument("--backend-path", required=True)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("list-blocks")
    sp.add_argument("tenant")
    sp = sub.add_parser("view-block")
    sp.add_argument("tenant")
    sp.add_argument("block")
    sp.add_argument("--limit", type=int, default=10)
    sp = sub.add_parser("find")
    sp.add_argument("tenant")
    sp.add_argument("block")
    sp.add_argument("trace_id")
    sp = sub.add_parser("gen-index")
    sp.add_argument("tenant")
    sp.add_argument("block")
    sp = sub.add_parser("gen-bloom")
    sp.add_argument("tenant")
    sp.add_argument("block")
    sp = sub.add_parser("import-ref",
                        help="one-way import of a reference-format v2 "
                             "block directory (meta.json + data + index)")
    sp.add_argument("tenant")
    sp.add_argument("src_dir")
    sp = sub.add_parser("search")
    sp.add_argument("tenant")
    sp.add_argument("--tags", nargs="*")
    sp.add_argument("--limit", type=int, default=20)
    sp.add_argument("--min-duration", default="",
                    help="e.g. 100ms, 1.5s (api/params duration syntax)")
    sp.add_argument("--max-duration", default="")
    sp.add_argument("--start", type=int, default=0, help="unix seconds")
    sp.add_argument("--end", type=int, default=0)

    args = p.parse_args(argv)
    be = LocalBackend(args.backend_path)
    fn = {
        "list-blocks": cmd_list_blocks, "view-block": cmd_view_block,
        "find": cmd_find, "gen-index": cmd_gen_index,
        "gen-bloom": cmd_gen_bloom, "search": cmd_search,
        "import-ref": cmd_import_ref,
    }[args.cmd]
    return fn(be, args) or 0


if __name__ == "__main__":
    import signal

    # behave like a unix tool when piped into head etc.
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main())
