"""Black-box consistency checker.

Role-equivalent to cmd/tempo-vulture (main.go:69-205): writes
deterministically-regenerable traces, re-reads them by id and by search,
and reports missing/mismatched counts — the continuous prod prober. In
this build it drives an in-process App or a remote HTTP endpoint.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from dataclasses import dataclass, field

from tempo_tpu import tempopb
from tempo_tpu.utils.test_data import make_trace


def seeded_trace_id(seed: int) -> bytes:
    return hashlib.sha256(f"vulture-{seed}".encode()).digest()[:16]


@dataclass
class VultureStats:
    written: int = 0
    found: int = 0
    missing: int = 0
    mismatched: int = 0
    search_found: int = 0
    search_missing: int = 0

    def to_json(self) -> str:
        return json.dumps(self.__dict__)


class Vulture:
    """Write traces keyed by a time seed; any reader can regenerate the
    expected content from the seed alone (reference util.TraceInfo)."""

    def __init__(self, app, tenant: str = "vulture"):
        self.app = app
        self.tenant = tenant
        self.stats = VultureStats()
        self._seeds: list[int] = []

    def write_pass(self, n: int = 10, epoch: int | None = None) -> None:
        epoch = epoch if epoch is not None else int(time.time())
        for i in range(n):
            seed = epoch * 1000 + i
            tid = seeded_trace_id(seed)
            tr = make_trace(tid, seed=seed)
            self.app.push(self.tenant, list(tr.batches))
            self._seeds.append(seed)
            self.stats.written += 1

    def read_pass(self) -> None:
        for seed in self._seeds:
            tid = seeded_trace_id(seed)
            expected = make_trace(tid, seed=seed)
            resp = self.app.find_trace(self.tenant, tid)
            if not resp.trace.batches:
                self.stats.missing += 1
                continue
            got_spans = sorted(
                s.span_id for b in resp.trace.batches
                for ss in b.scope_spans for s in ss.spans
            )
            want_spans = sorted(
                s.span_id for b in expected.batches
                for ss in b.scope_spans for s in ss.spans
            )
            if got_spans == want_spans:
                self.stats.found += 1
            else:
                self.stats.mismatched += 1

    def search_pass(self) -> None:
        for seed in self._seeds:
            tid = seeded_trace_id(seed)
            expected = make_trace(tid, seed=seed)
            svc = ""
            for kv in expected.batches[0].resource.attributes:
                if kv.key == "service.name":
                    svc = kv.value.string_value
            req = tempopb.SearchRequest()
            req.tags["service.name"] = svc
            req.limit = 10_000
            resp = self.app.search(self.tenant, req)
            if any(t.trace_id == tid.hex() for t in resp.traces):
                self.stats.search_found += 1
            else:
                self.stats.search_missing += 1

    def run_cycle(self, n: int = 10) -> VultureStats:
        self.write_pass(n)
        self.read_pass()
        self.search_pass()
        return self.stats
