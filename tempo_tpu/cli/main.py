"""The server binary: ``python -m tempo_tpu.cli.main -config.file=...``.

Role-equivalent to the reference's cmd/tempo main (config load, logger,
module startup, signal-driven graceful shutdown) with `-target` module
selection (cmd/tempo/app/modules.go:35-50):

  -target=all            single process, whole pipeline (default)
  -target=distributor    OTLP receivers → ring writes over gRPC
  -target=ingester       Pusher/IngesterQuerier gRPC + WAL/flush loops
  -target=querier        Querier gRPC job execution
  -target=query-frontend external HTTP API, job sharding over queriers
  -target=compactor      ownership-gated compaction + retention

Microservice targets discover each other via gossip membership
(`memberlist:` config section — bind/join addresses).
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
import uuid

# BEFORE anything touches a device (see utils/jaxenv.py: the env var
# alone does not stop a registered TPU plugin from handshaking its
# tunnel; jax stays optional for write-only targets → required=False)
from tempo_tpu.utils.jaxenv import honor_jax_platforms

honor_jax_platforms()

from tempo_tpu.api import HTTPApi, make_grpc_server, serve_http
from tempo_tpu.modules import App
from tempo_tpu.observability import get_logger
from .config import load_config


def main(argv=None) -> int:
    from tempo_tpu.modules.microservices import TARGETS, ModuleProcess

    p = argparse.ArgumentParser("tempo-tpu")
    p.add_argument("-config.file", dest="config_file", default=None)
    p.add_argument("-target", dest="target", default="all", choices=TARGETS)
    p.add_argument("-http-port", type=int, default=None)
    p.add_argument("-grpc-port", type=int, default=None)
    p.add_argument("-instance-id", dest="instance_id", default=None)
    args = p.parse_args(argv)

    log = get_logger()
    cfg, runtime = load_config(args.config_file)
    for w in runtime["warnings"]:
        log.warning("config: %s", w)

    http_port = args.http_port or runtime["http_port"]
    grpc_port = args.grpc_port or runtime["grpc_port"]

    dist = runtime.get("distributed") or {}
    if dist.get("coordinator") or "TEMPO_COORDINATOR" in __import__("os").environ:
        # must run before anything touches jax devices: the scan mesh
        # then spans every host's chips (SURVEY §2.6 TPU note)
        from tempo_tpu.parallel.multihost import init_distributed

        if init_distributed(
            coordinator=dist.get("coordinator"),
            num_processes=dist.get("num_processes"),
            process_id=dist.get("process_id"),
            cpu_devices_per_host=dist.get("cpu_devices_per_host"),
        ):
            log.info("joined distributed runtime")
        else:
            log.info("no coordinator configured; running single-host")

    stop = threading.Event()

    def on_signal(signum, frame):
        log.info("signal %s: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    if args.target == "all":
        app = App(cfg)
        app.run_maintenance()
        api = HTTPApi(app, multitenancy=runtime["multitenancy"],
                      debug_endpoints=runtime["debug_endpoints"])
        http_server = serve_http(api, port=http_port)
        threading.Thread(target=http_server.serve_forever, daemon=True).start()
        grpc_server = make_grpc_server(app, f"0.0.0.0:{grpc_port}")
        grpc_server.start()
        jaeger_agent = None
        if runtime.get("jaeger_agent_port"):
            from tempo_tpu.api.jaeger import JaegerAgentUDP
            jaeger_agent = JaegerAgentUDP(app.push,
                                          port=runtime["jaeger_agent_port"])
        log.info("tempo-tpu up: http=:%d grpc=:%d ingesters=%d rf=%d",
                 http_port, grpc_port, cfg.n_ingesters,
                 cfg.replication_factor)
        stop.wait()
        grpc_server.stop(grace=5)
        http_server.shutdown()
        if jaeger_agent is not None:
            jaeger_agent.close()
        try:
            app.shutdown()  # flush everything (reference /shutdown drain)
        except Exception as e:  # noqa: BLE001 — flush incomplete
            log.error("shutdown finished with unflushed WAL data: %s — "
                      "do NOT delete this node's WAL directory", e)
            return 1
        log.info("shutdown complete")
        return 0

    # microservice target
    instance_id = (args.instance_id or runtime["instance_id"]
                   or f"{args.target}-{uuid.uuid4().hex[:6]}")
    proc = ModuleProcess(
        cfg, args.target, instance_id=instance_id,
        grpc_port=grpc_port if args.target in
        ("ingester", "querier", "distributor", "query-frontend",
         "metrics-generator") else 0,
        http_port=http_port,
        memberlist_cfg=runtime["memberlist"],
    )
    api = HTTPApi(proc, multitenancy=runtime["multitenancy"],
                  debug_endpoints=runtime["debug_endpoints"])
    http_server = serve_http(api, port=http_port)
    threading.Thread(target=http_server.serve_forever, daemon=True).start()
    jaeger_agent = None
    if runtime.get("jaeger_agent_port"):
        if args.target == "distributor":
            from tempo_tpu.api.jaeger import JaegerAgentUDP
            jaeger_agent = JaegerAgentUDP(proc.push,
                                          port=runtime["jaeger_agent_port"])
        else:
            log.warning("jaeger_agent_port is only served by the "
                        "distributor target (ignored for %s)", args.target)
    log.info("tempo-tpu %s up: id=%s http=:%d grpc=%s gossip=%s",
             args.target, instance_id, http_port, proc.grpc_addr or "-",
             proc.ml.gossip_addr)
    stop.wait()
    http_server.shutdown()
    if jaeger_agent is not None:
        jaeger_agent.close()
    try:
        proc.shutdown()
    except Exception as e:  # noqa: BLE001 — flush incomplete
        log.error("shutdown finished with unflushed WAL data: %s — "
                  "do NOT delete this node's WAL directory", e)
        return 1
    log.info("shutdown complete")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
