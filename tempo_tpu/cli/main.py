"""The server binary: ``python -m tempo_tpu.cli.main -config.file=...``.

Role-equivalent to the reference's cmd/tempo main (config load, logger,
module startup, signal-driven graceful shutdown). One process runs the
whole pipeline (the reference's ``-target=all`` / scalable-single-binary);
gRPC exposes the module boundaries so additional processes can join as
pushers/queriers.
"""

from __future__ import annotations

import argparse
import signal
import threading

from tempo_tpu.api import HTTPApi, make_grpc_server, serve_http
from tempo_tpu.modules import App
from tempo_tpu.observability import get_logger
from .config import load_config


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tempo-tpu")
    p.add_argument("-config.file", dest="config_file", default=None)
    p.add_argument("-target", dest="target", default="all",
                   choices=["all"], help="module target (single-binary)")
    p.add_argument("-http-port", type=int, default=None)
    p.add_argument("-grpc-port", type=int, default=None)
    args = p.parse_args(argv)

    log = get_logger()
    cfg, runtime = load_config(args.config_file)
    for w in runtime["warnings"]:
        log.warning("config: %s", w)

    app = App(cfg)
    app.run_maintenance()

    http_port = args.http_port or runtime["http_port"]
    grpc_port = args.grpc_port or runtime["grpc_port"]

    api = HTTPApi(app, multitenancy=runtime["multitenancy"])
    http_server = serve_http(api, port=http_port)
    threading.Thread(target=http_server.serve_forever, daemon=True).start()

    grpc_server = make_grpc_server(app, f"0.0.0.0:{grpc_port}")
    grpc_server.start()
    log.info("tempo-tpu up: http=:%d grpc=:%d ingesters=%d rf=%d",
             http_port, grpc_port, cfg.n_ingesters, cfg.replication_factor)

    stop = threading.Event()

    def on_signal(signum, frame):
        log.info("signal %s: draining", signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    stop.wait()

    grpc_server.stop(grace=5)
    http_server.shutdown()
    app.shutdown()  # flush everything (reference /shutdown drain)
    log.info("shutdown complete")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
