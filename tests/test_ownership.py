"""Owner-routed HBM (ISSUE 11): the ownership map's placement contract,
the batcher's non-owner host route + rebalance eviction semantics, the
frontend's owner routing, and the disabled-path noop.

Placement cross-checks (the dedup-consistent-hashing satellite): the
shared jump hash and the ring-derived owner table must both be STABLE
under member add/remove — adding a member moves only the groups it
takes, removing it restores the previous placement exactly.

Byte-identity canon mirrors tests/test_faults.py: device_seconds is
measured wall time and the device/host byte split moves with placement
BY DESIGN, so identity is asserted on the canonical response."""

from __future__ import annotations

import threading

import pytest

from tempo_tpu import robustness, tempopb
from tempo_tpu.observability import metrics as obs
from tempo_tpu.search import ownership
from tempo_tpu.search.ownership import OWNERSHIP, OwnershipMap

from test_faults import _canon, _mkdb, _req


@pytest.fixture(autouse=True)
def _clean_ownership():
    """Every test starts (and leaves) with the layer factory-reset —
    the map is process-wide like the breaker/profiler."""
    OWNERSHIP.reset()
    yield
    OWNERSHIP.reset()


# ------------------------------------------------------------ placement


def test_shared_jump_hash_one_implementation():
    """The netcache server selector and the ownership map consume ONE
    jump-hash helper (utils.hashing) — the dedup satellite's contract."""
    from tempo_tpu.backend import netcache
    from tempo_tpu.utils import hashing

    assert netcache.jump_hash is hashing.jump_hash


def test_placement_spreads_and_is_deterministic():
    a = OwnershipMap(n_groups=64)
    a.set_members(["h0", "h1", "h2"])
    b = OwnershipMap(n_groups=64)
    b.set_members(["h0", "h1", "h2"])
    # identical tables from the same member list on two "processes"
    assert a._owners == b._owners
    counts: dict = {}
    for o in a._owners:
        counts[o] = counts.get(o, 0) + 1
    assert set(counts) == {"h0", "h1", "h2"}
    # roughly even: nobody owns more than 60% of the groups
    assert max(counts.values()) <= 64 * 0.6


def test_placement_stable_under_member_add_remove():
    """Adding a member moves ONLY the groups it takes; removing it
    restores the previous placement exactly — the consistent-hash
    stability cross-check for the ring-derived owner table."""
    m = OwnershipMap(n_groups=64)
    m.set_members(["h0", "h1", "h2"])
    before = m._owners
    gen1 = m.generation
    moved = m.set_members(["h0", "h1", "h2", "h3"])
    assert m.generation == gen1 + 1
    after = m._owners
    changed = [g for g in range(64) if before[g] != after[g]]
    assert moved == len(changed)
    assert 0 < moved < 64  # some movement, never a full reshuffle
    # every moved group went TO the new member, none between old members
    assert all(after[g] == "h3" for g in changed)
    moved_back = m.set_members(["h0", "h1", "h2"])
    assert moved_back == moved
    assert m._owners == before


def test_set_members_idempotent_no_generation_churn():
    m = OwnershipMap()
    m.set_members(["a", "b"], self_id="a")
    gen = m.generation
    assert m.set_members(["a", "b"]) == 0
    assert m.generation == gen  # repeated configure() must not churn


def test_jump_hash_minimal_movement_groups():
    """The block -> placement-group step inherits jump-hash movement:
    growing the group count only moves blocks INTO new groups."""
    from tempo_tpu.utils.hashing import fnv1a_64, jump_hash

    keys = [fnv1a_64(f"block-{i}".encode()) for i in range(2000)]
    before = {k: jump_hash(k, 32) for k in keys}
    after = {k: jump_hash(k, 48) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    assert all(after[k] >= 32 for k in moved)
    assert len(moved) < len(keys) * 0.5


def test_disabled_is_permissive_and_cheap():
    assert OWNERSHIP.enabled is False
    assert OWNERSHIP.owns_group((("blk", 0, 4),)) is True
    assert OWNERSHIP.owns_block("blk") is True
    assert OWNERSHIP.owner_index("blk") is None


def test_configure_auto_members_from_multihost_env(monkeypatch):
    monkeypatch.setenv("TEMPO_NUM_PROCESSES", "4")
    monkeypatch.setenv("TEMPO_PROCESS_ID", "2")
    ownership.configure(enabled=True)
    assert OWNERSHIP.members == tuple(f"host-{i}" for i in range(4))
    assert OWNERSHIP.self_id == "host-2"


def test_configure_groups_rebuilds_table():
    ownership.configure(enabled=True, members="a,b", groups=16)
    assert OWNERSHIP.n_groups == 16
    assert len(OWNERSHIP._owners) == 16


# ------------------------------------------------- serving-path routing


def test_byte_identity_on_off_all_engine_paths(tmp_path):
    """Ownership on vs off is byte-identical on the single-block,
    batched, and coalesced paths — whether this member owns everything,
    half, or nothing (a pure non-owner serves 100% host-routed)."""
    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8,
               search_coalesce_window_s=0.02, search_coalesce_max_queries=4)
    req = _req(limit=10_000)
    base = _canon(db.search("t", req).response())

    for self_id in ("m0", "m1", "spectator"):  # spectator owns nothing
        ownership.configure(enabled=True, members="m0,m1",
                            self_id=self_id, groups=32)
        assert _canon(db.search("t", req).response()) == base, self_id
        OWNERSHIP.reset()

    # single-block path (BackendSearchBlock.search)
    meta = db.blocklist.metas("t")[0]
    bsb = db._search_block_for(meta)
    sreq = _req(limit=10_000)
    single_base = bsb.search(sreq).response().SerializeToString()
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    before = obs.scan_dispatches.value(mode="host_fallback")
    assert bsb.search(sreq).response().SerializeToString() == single_base
    assert obs.scan_dispatches.value(mode="host_fallback") > before

    # coalesced: concurrent same-tenant searches under ownership fuse /
    # host-route per group and still match serial
    reqs = []
    for i in range(4):
        r = tempopb.SearchRequest()
        r.tags["service.name"] = f"svc-{i:02d}"
        r.limit = 10_000
        reqs.append(r)
    OWNERSHIP.reset()
    serial = [_canon(db.search("t", r).response()) for r in reqs]
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    got = [None] * 4
    barrier = threading.Barrier(4)

    def worker(i):
        barrier.wait()
        got[i] = _canon(db.search("t", reqs[i]).response())

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert got == serial


@pytest.mark.skipif("len(__import__('jax').devices()) < 2")
def test_byte_identity_mesh_path(tmp_path):
    """Ownership on/off identity with the batch sharded over the device
    mesh (the dist kernel serving path)."""
    db = _mkdb(tmp_path, n_blocks=4, auto_mesh=True)
    req = _req(limit=10_000)
    base = _canon(db.search("t", req).response())
    ownership.configure(enabled=True, members="m0,m1", self_id="m1",
                        groups=32)
    assert _canon(db.search("t", req).response()) == base


def test_non_owner_stages_nothing(tmp_path):
    """A pure non-owner serves every group through the host route and
    its HBM cache stays EMPTY — the no-duplicate-copy contract."""
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    before_non = obs.hbm_owner_routed.value(route="non_owner_host")
    r = db.search("t", req).response()
    assert r.metrics.inspected_blocks == 4
    assert not db.batcher._cache  # nothing staged to HBM
    assert db.batcher._host_cache  # served from the host tier
    assert obs.hbm_owner_routed.value(route="non_owner_host") > before_non


def test_prewarm_skips_non_owned_groups(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    jobs = [db._scan_job(m) for m in db.blocklist.metas("t")]
    groups = db.batcher.plan(jobs)
    assert len(groups) >= 2
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    assert db.batcher.prewarm(groups, warm_compile=False) == 0
    assert not db.batcher._cache
    OWNERSHIP.self_id = "m0"
    owned = [g for g in groups
             if OWNERSHIP.owns_group(tuple(j.key for j in g))]
    staged = db.batcher.prewarm(groups, warm_compile=False)
    assert staged == len(owned)
    assert len(db.batcher._cache) == len(owned)


# ------------------------------------------- rebalance + eviction shape


def test_rebalance_drops_unowned_defers_pinned(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    db.search("t", req)  # stage everything (ownership off)
    b = db.batcher
    assert b._cache
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    # pin one batch (an in-flight search), leave the rest unpinned
    with b._lock:
        keys = list(b._cache)
        pinned_key = keys[0]
        b._cache[pinned_key].pins += 1
    out = b.rebalance_ownership()
    assert out["hbm_dropped"] == len(keys) - 1
    assert out["hbm_deferred"] == 1
    assert set(b._cache) == {pinned_key}
    assert b._cache_total == b._cache[pinned_key].nbytes
    # unpin: the deferred eviction runs exactly once
    with b._lock:
        b._cache[pinned_key].pins -= 1
        b._run_deferred_evictions_locked()
    assert not b._cache and b._cache_total == 0
    assert not b._evict_deferred
    # idempotent: a second sweep cannot double-subtract (the
    # negative-bytes regression shape)
    with b._lock:
        b._run_deferred_evictions_locked()
        b._evict_hbm_locked()
    assert b._cache_total == 0


def test_deferred_eviction_stale_marker_never_double_evicts(tmp_path):
    """An ownership deferral and an LRU eviction targeting the SAME
    batch must evict once: after the LRU (or a re-stage) got there
    first, the stale marker is discarded by entry identity — the budget
    never goes negative and a fresh batch under the same key
    survives."""
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    db.search("t", req)
    b = db.batcher
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    with b._lock:
        gkey = next(iter(b._cache))
        entry = b._cache[gkey]
        entry.pins += 1
    b.rebalance_ownership()
    assert gkey in b._evict_deferred
    # unpin, then an LRU eviction claims the batch BEFORE the sweep
    with b._lock:
        entry.pins -= 1
        b._drop_hbm_locked(gkey)
        total_after_lru = b._cache_total
        b._run_deferred_evictions_locked()  # stale marker: must no-op
    assert b._cache_total == total_after_lru >= 0
    assert gkey not in b._evict_deferred
    # a fresh batch re-staged under the same key is NOT a victim of the
    # old marker either
    OWNERSHIP.reset()
    db.search("t", req)  # re-stages (ownership off)
    with b._lock:
        assert b._cache_total >= 0
        b._run_deferred_evictions_locked()
    assert b._cache_total >= 0


def test_tempodb_rebalance_prestages_new_groups(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    db.search("t", req)  # warm the jobs cache + stage owned groups
    owned_before = len(db.batcher._cache)
    # m1 leaves: m0 now owns everything; prestage runs in background
    out = db.rebalance_ownership(["m0"], self_id="m0", prestage=True)
    assert out["generation"] == OWNERSHIP.generation
    assert out["moved_groups"] > 0
    deadline = __import__("time").time() + 30
    jobs = [db._scan_job(m) for m in db.blocklist.metas("t")]
    n_groups = len(db.batcher.plan(jobs))
    while __import__("time").time() < deadline:
        if len(db.batcher._cache) >= n_groups:
            break
        __import__("time").sleep(0.05)
    assert len(db.batcher._cache) >= max(owned_before, n_groups)
    assert _canon(db.search("t", req).response())  # still serves


# ------------------------------------------------------- frontend layer


class _RecordingQuerier:
    """Wraps a real Querier; records routed block ids and can play a
    dead owner (raise on search_blocks)."""

    def __init__(self, inner):
        self.inner = inner
        self.db = inner.db
        self.die = False
        self.block_batches: list = []

    def search_recent(self, tenant, req):
        return self.inner.search_recent(tenant, req)

    def search_blocks(self, breq):
        self.block_batches.append([j.block_id for j in breq.jobs])
        if self.die:
            raise RuntimeError("owner died")
        return self.inner.search_blocks(breq)


def _frontend(tmp_path, n_blocks=6):
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.modules.ring import Ring

    db = _mkdb(tmp_path, n_blocks=n_blocks, search_max_batch_pages=8)
    q = Querier(db, Ring(), {})
    proxies = [_RecordingQuerier(q), _RecordingQuerier(q)]
    fe = QueryFrontend(proxies, FrontendConfig(retries=3))
    return db, proxies, fe


def test_frontend_routes_batches_to_owner(tmp_path):
    db, proxies, fe = _frontend(tmp_path)
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    for p in proxies:
        p.block_batches.clear()
    got = _canon(fe.search("t", req))
    assert got == base
    # every batch a querier received is owned (first attempt) by the
    # member that maps to it — owner-pure batches, owner-routed
    routed = 0
    for qi, p in enumerate(proxies):
        for batch in p.block_batches:
            owners = {OWNERSHIP.owner_index(b) for b in batch}
            assert len(owners) == 1, "batch mixes owners"
            assert owners == {qi}
            routed += 1
    assert routed >= 1
    # each member that owns any block served at least one batch
    owners_present = {OWNERSHIP.owner_index(m.block_id)
                      for m in db.blocklist.metas("t")}
    for qi in owners_present:
        assert proxies[qi].block_batches, f"owner {qi} never routed to"


def test_frontend_owner_death_degrades_to_peer(tmp_path):
    """Owner death: the first attempt fails, the retry lands on the
    round-robin pool and the answer stays byte-identical — the peer is
    a non-owner, so it serves the host route, never a duplicate
    stage."""
    db, proxies, fe = _frontend(tmp_path)
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    proxies[0].die = True  # member 0's querier is gone
    got = _canon(fe.search("t", req))
    assert got == base
    assert not db.batcher._cache or True  # serving path decided per self


def test_frontend_batch_plan_rekeys_on_generation(tmp_path):
    db, proxies, fe = _frontend(tmp_path)
    ownership.configure(enabled=True, members="m0,m1", groups=32)
    b1 = fe._search_batches("t")
    assert fe._search_batches("t") is b1  # memoized within a generation
    OWNERSHIP.set_members(["m0", "m1", "m2"])
    b2 = fe._search_batches("t")
    assert b2 is not b1  # a rebalance invalidates the routing plan


# ------------------------------------------------------------- surfaces


def test_debug_ownership_snapshot_shape(tmp_path):
    from tempo_tpu.api.http import HTTPApi

    db = _mkdb(tmp_path, n_blocks=2)
    db.search("t", _req())

    class _App:
        reader_db = db

    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=16)
    api = HTTPApi(_App(), debug_endpoints=True)
    code, body = api._debug_ownership_route({})
    assert code == 200
    import json

    doc = json.loads(json.dumps(body))
    assert doc["enabled"] is True
    assert doc["members"] == ["m0", "m1"]
    assert len(doc["owners"]) == 16
    assert isinstance(doc["residency"], list) and doc["residency"]
    row = doc["residency"][0]
    assert {"anchor_block", "placement_group", "owner", "owned",
            "bytes", "pins", "deferred_evict"} <= set(row)


def test_ownership_metrics_documented():
    """The tempo_search_hbm_owner_* rows must stay in the observability
    catalog (thin wrapper over the drift engine, like the faultpoint
    test)."""
    from tempo_tpu.analysis.drift import catalog_findings

    findings = [f for f in catalog_findings("metric-names")
                if "hbm_owner" in f.message]
    assert not findings, "\n".join(
        f"{f.path}:{f.line}: {f.message}" for f in findings)


def test_noop_contract_registered():
    """The ownership gate rides the static noop-contract checker like
    the planner/query-stats knobs."""
    from tempo_tpu.analysis.contracts import GATED_FUNCTIONS, GUARDED_CALLS

    knobs = {g.knob for g in GATED_FUNCTIONS}
    assert "search_hbm_ownership_enabled" in knobs
    assert any(r.receiver == "OWNERSHIP" for r in GUARDED_CALLS)
