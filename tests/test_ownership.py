"""Owner-routed HBM (ISSUE 11): the ownership map's placement contract,
the batcher's non-owner host route + rebalance eviction semantics, the
frontend's owner routing, and the disabled-path noop.

Placement cross-checks (the dedup-consistent-hashing satellite): the
shared jump hash and the ring-derived owner table must both be STABLE
under member add/remove — adding a member moves only the groups it
takes, removing it restores the previous placement exactly.

Byte-identity canon mirrors tests/test_faults.py: device_seconds is
measured wall time and the device/host byte split moves with placement
BY DESIGN, so identity is asserted on the canonical response."""

from __future__ import annotations

import threading

import pytest

from tempo_tpu import robustness, tempopb
from tempo_tpu.observability import metrics as obs
from tempo_tpu.search import ownership
from tempo_tpu.search.ownership import OWNERSHIP, OwnershipMap

from test_faults import _canon, _mkdb, _req


@pytest.fixture(autouse=True)
def _clean_ownership():
    """Every test starts (and leaves) with the layer factory-reset —
    the map is process-wide like the breaker/profiler."""
    OWNERSHIP.reset()
    yield
    OWNERSHIP.reset()


# ------------------------------------------------------------ placement


def test_shared_jump_hash_one_implementation():
    """The netcache server selector and the ownership map consume ONE
    jump-hash helper (utils.hashing) — the dedup satellite's contract."""
    from tempo_tpu.backend import netcache
    from tempo_tpu.utils import hashing

    assert netcache.jump_hash is hashing.jump_hash


def test_placement_spreads_and_is_deterministic():
    a = OwnershipMap(n_groups=64)
    a.set_members(["h0", "h1", "h2"])
    b = OwnershipMap(n_groups=64)
    b.set_members(["h0", "h1", "h2"])
    # identical tables from the same member list on two "processes"
    assert a._owners == b._owners
    counts: dict = {}
    for o in a._owners:
        counts[o] = counts.get(o, 0) + 1
    assert set(counts) == {"h0", "h1", "h2"}
    # roughly even: nobody owns more than 60% of the groups
    assert max(counts.values()) <= 64 * 0.6


def test_placement_stable_under_member_add_remove():
    """Adding a member moves ONLY the groups it takes; removing it
    restores the previous placement exactly — the consistent-hash
    stability cross-check for the ring-derived owner table."""
    m = OwnershipMap(n_groups=64)
    m.set_members(["h0", "h1", "h2"])
    before = m._owners
    gen1 = m.generation
    moved = m.set_members(["h0", "h1", "h2", "h3"])
    assert m.generation == gen1 + 1
    after = m._owners
    changed = [g for g in range(64) if before[g] != after[g]]
    assert moved == len(changed)
    assert 0 < moved < 64  # some movement, never a full reshuffle
    # every moved group went TO the new member, none between old members
    assert all(after[g] == "h3" for g in changed)
    moved_back = m.set_members(["h0", "h1", "h2"])
    assert moved_back == moved
    assert m._owners == before


def test_set_members_idempotent_no_generation_churn():
    m = OwnershipMap()
    m.set_members(["a", "b"], self_id="a")
    gen = m.generation
    assert m.set_members(["a", "b"]) == 0
    assert m.generation == gen  # repeated configure() must not churn


def test_jump_hash_minimal_movement_groups():
    """The block -> placement-group step inherits jump-hash movement:
    growing the group count only moves blocks INTO new groups."""
    from tempo_tpu.utils.hashing import fnv1a_64, jump_hash

    keys = [fnv1a_64(f"block-{i}".encode()) for i in range(2000)]
    before = {k: jump_hash(k, 32) for k in keys}
    after = {k: jump_hash(k, 48) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    assert all(after[k] >= 32 for k in moved)
    assert len(moved) < len(keys) * 0.5


def test_disabled_is_permissive_and_cheap():
    assert OWNERSHIP.enabled is False
    assert OWNERSHIP.owns_group((("blk", 0, 4),)) is True
    assert OWNERSHIP.owns_block("blk") is True
    assert OWNERSHIP.owner_index("blk") is None


def test_configure_auto_members_from_multihost_env(monkeypatch):
    monkeypatch.setenv("TEMPO_NUM_PROCESSES", "4")
    monkeypatch.setenv("TEMPO_PROCESS_ID", "2")
    ownership.configure(enabled=True)
    assert OWNERSHIP.members == tuple(f"host-{i}" for i in range(4))
    assert OWNERSHIP.self_id == "host-2"


def test_configure_groups_rebuilds_table():
    ownership.configure(enabled=True, members="a,b", groups=16)
    assert OWNERSHIP.n_groups == 16
    assert len(OWNERSHIP._owners) == 16


# ------------------------------------------------- serving-path routing


def test_byte_identity_on_off_all_engine_paths(tmp_path):
    """Ownership on vs off is byte-identical on the single-block,
    batched, and coalesced paths — whether this member owns everything,
    half, or nothing (a pure non-owner serves 100% host-routed)."""
    db = _mkdb(tmp_path, n_blocks=6, search_max_batch_pages=8,
               search_coalesce_window_s=0.02, search_coalesce_max_queries=4)
    req = _req(limit=10_000)
    base = _canon(db.search("t", req).response())

    for self_id in ("m0", "m1", "spectator"):  # spectator owns nothing
        ownership.configure(enabled=True, members="m0,m1",
                            self_id=self_id, groups=32)
        assert _canon(db.search("t", req).response()) == base, self_id
        OWNERSHIP.reset()

    # single-block path (BackendSearchBlock.search)
    meta = db.blocklist.metas("t")[0]
    bsb = db._search_block_for(meta)
    sreq = _req(limit=10_000)
    single_base = bsb.search(sreq).response().SerializeToString()
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    before = obs.scan_dispatches.value(mode="host_fallback")
    assert bsb.search(sreq).response().SerializeToString() == single_base
    assert obs.scan_dispatches.value(mode="host_fallback") > before

    # coalesced: concurrent same-tenant searches under ownership fuse /
    # host-route per group and still match serial
    reqs = []
    for i in range(4):
        r = tempopb.SearchRequest()
        r.tags["service.name"] = f"svc-{i:02d}"
        r.limit = 10_000
        reqs.append(r)
    OWNERSHIP.reset()
    serial = [_canon(db.search("t", r).response()) for r in reqs]
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    got = [None] * 4
    barrier = threading.Barrier(4)

    def worker(i):
        barrier.wait()
        got[i] = _canon(db.search("t", reqs[i]).response())

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert got == serial


@pytest.mark.skipif("len(__import__('jax').devices()) < 2")
def test_byte_identity_mesh_path(tmp_path):
    """Ownership on/off identity with the batch sharded over the device
    mesh (the dist kernel serving path)."""
    db = _mkdb(tmp_path, n_blocks=4, auto_mesh=True)
    req = _req(limit=10_000)
    base = _canon(db.search("t", req).response())
    ownership.configure(enabled=True, members="m0,m1", self_id="m1",
                        groups=32)
    assert _canon(db.search("t", req).response()) == base


def test_non_owner_stages_nothing(tmp_path):
    """A pure non-owner serves every group through the host route and
    its HBM cache stays EMPTY — the no-duplicate-copy contract."""
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    before_non = obs.hbm_owner_routed.value(route="non_owner_host")
    r = db.search("t", req).response()
    assert r.metrics.inspected_blocks == 4
    assert not db.batcher._cache  # nothing staged to HBM
    assert db.batcher._host_cache  # served from the host tier
    assert obs.hbm_owner_routed.value(route="non_owner_host") > before_non


def test_prewarm_skips_non_owned_groups(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    jobs = [db._scan_job(m) for m in db.blocklist.metas("t")]
    groups = db.batcher.plan(jobs)
    assert len(groups) >= 2
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    assert db.batcher.prewarm(groups, warm_compile=False) == 0
    assert not db.batcher._cache
    OWNERSHIP.self_id = "m0"
    owned = [g for g in groups
             if OWNERSHIP.owns_group(tuple(j.key for j in g))]
    staged = db.batcher.prewarm(groups, warm_compile=False)
    assert staged == len(owned)
    assert len(db.batcher._cache) == len(owned)


# ------------------------------------------- rebalance + eviction shape


def test_rebalance_drops_unowned_defers_pinned(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    db.search("t", req)  # stage everything (ownership off)
    b = db.batcher
    assert b._cache
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    # pin one batch (an in-flight search), leave the rest unpinned
    with b._lock:
        keys = list(b._cache)
        pinned_key = keys[0]
        b._cache[pinned_key].pins += 1
    out = b.rebalance_ownership()
    assert out["hbm_dropped"] == len(keys) - 1
    assert out["hbm_deferred"] == 1
    assert set(b._cache) == {pinned_key}
    assert b._cache_total == b._cache[pinned_key].nbytes
    # unpin: the deferred eviction runs exactly once
    with b._lock:
        b._cache[pinned_key].pins -= 1
        b._run_deferred_evictions_locked()
    assert not b._cache and b._cache_total == 0
    assert not b._evict_deferred
    # idempotent: a second sweep cannot double-subtract (the
    # negative-bytes regression shape)
    with b._lock:
        b._run_deferred_evictions_locked()
        b._evict_hbm_locked()
    assert b._cache_total == 0


def test_deferred_eviction_stale_marker_never_double_evicts(tmp_path):
    """An ownership deferral and an LRU eviction targeting the SAME
    batch must evict once: after the LRU (or a re-stage) got there
    first, the stale marker is discarded by entry identity — the budget
    never goes negative and a fresh batch under the same key
    survives."""
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    db.search("t", req)
    b = db.batcher
    ownership.configure(enabled=True, members="m0,m1",
                        self_id="spectator", groups=32)
    with b._lock:
        gkey = next(iter(b._cache))
        entry = b._cache[gkey]
        entry.pins += 1
    b.rebalance_ownership()
    assert gkey in b._evict_deferred
    # unpin, then an LRU eviction claims the batch BEFORE the sweep
    with b._lock:
        entry.pins -= 1
        b._drop_hbm_locked(gkey)
        total_after_lru = b._cache_total
        b._run_deferred_evictions_locked()  # stale marker: must no-op
    assert b._cache_total == total_after_lru >= 0
    assert gkey not in b._evict_deferred
    # a fresh batch re-staged under the same key is NOT a victim of the
    # old marker either
    OWNERSHIP.reset()
    db.search("t", req)  # re-stages (ownership off)
    with b._lock:
        assert b._cache_total >= 0
        b._run_deferred_evictions_locked()
    assert b._cache_total >= 0


def test_tempodb_rebalance_prestages_new_groups(tmp_path):
    db = _mkdb(tmp_path, n_blocks=4, search_max_batch_pages=8)
    req = _req(limit=10_000)
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    db.search("t", req)  # warm the jobs cache + stage owned groups
    owned_before = len(db.batcher._cache)
    # m1 leaves: m0 now owns everything; prestage runs in background
    out = db.rebalance_ownership(["m0"], self_id="m0", prestage=True)
    assert out["generation"] == OWNERSHIP.generation
    assert out["moved_groups"] > 0
    deadline = __import__("time").time() + 30
    jobs = [db._scan_job(m) for m in db.blocklist.metas("t")]
    n_groups = len(db.batcher.plan(jobs))
    while __import__("time").time() < deadline:
        if len(db.batcher._cache) >= n_groups:
            break
        __import__("time").sleep(0.05)
    assert len(db.batcher._cache) >= max(owned_before, n_groups)
    assert _canon(db.search("t", req).response())  # still serves


# ------------------------------------------------------- frontend layer


class _RecordingQuerier:
    """Wraps a real Querier; records routed block ids and can play a
    dead owner (raise on search_blocks)."""

    def __init__(self, inner):
        self.inner = inner
        self.db = inner.db
        self.die = False
        self.block_batches: list = []

    def search_recent(self, tenant, req):
        return self.inner.search_recent(tenant, req)

    def search_blocks(self, breq):
        self.block_batches.append([j.block_id for j in breq.jobs])
        if self.die:
            raise RuntimeError("owner died")
        return self.inner.search_blocks(breq)


def _frontend(tmp_path, n_blocks=6):
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.modules.ring import Ring

    db = _mkdb(tmp_path, n_blocks=n_blocks, search_max_batch_pages=8)
    q = Querier(db, Ring(), {})
    proxies = [_RecordingQuerier(q), _RecordingQuerier(q)]
    fe = QueryFrontend(proxies, FrontendConfig(retries=3))
    return db, proxies, fe


def test_frontend_routes_batches_to_owner(tmp_path):
    db, proxies, fe = _frontend(tmp_path)
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    for p in proxies:
        p.block_batches.clear()
    got = _canon(fe.search("t", req))
    assert got == base
    # every batch a querier received is owned (first attempt) by the
    # member that maps to it — owner-pure batches, owner-routed
    routed = 0
    for qi, p in enumerate(proxies):
        for batch in p.block_batches:
            owners = {OWNERSHIP.owner_index(b) for b in batch}
            assert len(owners) == 1, "batch mixes owners"
            assert owners == {qi}
            routed += 1
    assert routed >= 1
    # each member that owns any block served at least one batch
    owners_present = {OWNERSHIP.owner_index(m.block_id)
                      for m in db.blocklist.metas("t")}
    for qi in owners_present:
        assert proxies[qi].block_batches, f"owner {qi} never routed to"


def test_frontend_owner_death_degrades_to_peer(tmp_path):
    """Owner death: the first attempt fails, the retry lands on the
    round-robin pool and the answer stays byte-identical — the peer is
    a non-owner, so it serves the host route, never a duplicate
    stage."""
    db, proxies, fe = _frontend(tmp_path)
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32)
    proxies[0].die = True  # member 0's querier is gone
    got = _canon(fe.search("t", req))
    assert got == base
    assert not db.batcher._cache or True  # serving path decided per self


def test_frontend_pool_resize_mid_flight_keeps_plan_mapping(tmp_path):
    """Regression (satellite): the batch plan carries the pool width it
    was computed against, so a querier joining the pool BETWEEN
    planning and dispatch cannot silently remap every owner — the
    in-flight batch lands on the plan-mapped querier, and the new pool
    member only receives freshly-planned work."""
    db, proxies, fe = _frontend(tmp_path)
    ownership.configure(enabled=True, members="m0,m1,m2", self_id="m0",
                        groups=32)
    req = _req(limit=10_000)
    batches = fe._search_batches("t")
    assert all(b[3] == 2 for b in batches)  # planned against 2 queriers
    payload, template, owner, width = next(
        b for b in batches if b[2] is not None)
    breq = tempopb.SearchBlocksRequest()
    breq.CopyFrom(template)
    breq.search_req.CopyFrom(req)
    breq.tenant_id = "t"
    # the pool grows mid-flight
    q3 = _RecordingQuerier(proxies[0].inner)
    fe.queriers.append(q3)
    fe._dispatch_batch(breq, owner, width, payload[0][0].block_id)
    # plan-width mapping: owner % 2 — the live-pool indexing this
    # replaces would have sent owner-2 batches to the NEW querier
    assert not q3.block_batches
    assert proxies[owner % width].block_batches


def test_frontend_batch_plan_rekeys_on_generation(tmp_path):
    db, proxies, fe = _frontend(tmp_path)
    ownership.configure(enabled=True, members="m0,m1", groups=32)
    b1 = fe._search_batches("t")
    assert fe._search_batches("t") is b1  # memoized within a generation
    OWNERSHIP.set_members(["m0", "m1", "m2"])
    b2 = fe._search_batches("t")
    assert b2 is not b1  # a rebalance invalidates the routing plan


# ------------------------------------------------------------- surfaces


def test_debug_ownership_snapshot_shape(tmp_path):
    from tempo_tpu.api.http import HTTPApi

    db = _mkdb(tmp_path, n_blocks=2)
    db.search("t", _req())

    class _App:
        reader_db = db

    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=16)
    api = HTTPApi(_App(), debug_endpoints=True)
    code, body = api._debug_ownership_route({})
    assert code == 200
    import json

    doc = json.loads(json.dumps(body))
    assert doc["enabled"] is True
    assert doc["members"] == ["m0", "m1"]
    assert len(doc["owners"]) == 16
    assert isinstance(doc["residency"], list) and doc["residency"]
    row = doc["residency"][0]
    assert {"anchor_block", "placement_group", "owner", "owned",
            "bytes", "pins", "deferred_evict", "replica"} <= set(row)
    # the replication surface rides the same snapshot (empty heat
    # table and a disarmed hedge timer at the rf=1 default)
    assert doc["rf"] == 1 and doc["replicated"] is False
    assert doc["heat"] == {}
    assert doc["hedge"]["armed"] is False


def test_ownership_metrics_documented():
    """The tempo_search_hbm_owner_* rows must stay in the observability
    catalog (thin wrapper over the drift engine, like the faultpoint
    test)."""
    from tempo_tpu.analysis.drift import catalog_findings

    findings = [f for f in catalog_findings("metric-names")
                if "hbm_owner" in f.message]
    assert not findings, "\n".join(
        f"{f.path}:{f.line}: {f.message}" for f in findings)


def test_noop_contract_registered():
    """The ownership gate rides the static noop-contract checker like
    the planner/query-stats knobs — and the replication/hedge gates
    ride beside it (heat table, replica lookups and the hedge timer
    must each cost one attribute read at rf=1)."""
    from tempo_tpu.analysis.contracts import GATED_FUNCTIONS, GUARDED_CALLS

    knobs = {g.knob for g in GATED_FUNCTIONS}
    assert "search_hbm_ownership_enabled" in knobs
    assert "search_hbm_ownership_rf" in knobs
    assert "search_hbm_ownership_hot_rate" in knobs
    assert "search_hedge_delay_ms" in knobs
    gated = {g.qualname for g in GATED_FUNCTIONS}
    assert {"OwnershipMap.record_access", "OwnershipMap.replica_indices",
            "OwnershipMap.sweep", "HedgeTimer.observe",
            "HedgeTimer.delay_s"} <= gated
    assert any(r.receiver == "OWNERSHIP" for r in GUARDED_CALLS)
    assert any(r.receiver == "HEDGE" and "observe" in r.methods
               for r in GUARDED_CALLS)
    assert any(r.receiver == "OWNERSHIP" and "record_access" in r.methods
               for r in GUARDED_CALLS)


# ------------------------------------- heat-adaptive replication (rf>1)


def test_replica_table_primary_first_distinct():
    """The per-generation replica table: rf distinct ring members per
    group, primary (the owner) first — the frontend's hedge order."""
    ownership.configure(enabled=True, members="h0,h1,h2", self_id="h0",
                        groups=32, rf=2, hot_rate=5.0)
    assert OWNERSHIP.replicated is True
    assert OWNERSHIP._replica_depth == 2
    for g in range(32):
        reps = OWNERSHIP._replicas[g]
        assert len(reps) == 2 and len(set(reps)) == 2
        assert reps[0] == OWNERSHIP._owners[g]


def test_rf_defaults_are_true_noop():
    """rf=1 (the default): the heat table never records, replica
    lookups return empty, the sweep no-ops, the hedge timer stays
    disarmed — single-owner behavior bit for bit."""
    from tempo_tpu.search.ownership import HEDGE

    ownership.configure(enabled=True, members="h0,h1", self_id="h0",
                        groups=32)
    assert OWNERSHIP.rf == 1 and OWNERSHIP.replicated is False
    OWNERSHIP.record_access("blk")  # one attribute read: no heat entry
    assert OWNERSHIP._heat == {}
    assert OWNERSHIP.replica_indices("blk") == ()
    assert OWNERSHIP.replicas_of("blk") == ()
    assert OWNERSHIP.sweep() == 0
    assert HEDGE.armed is False
    t = ownership.HedgeTimer()
    t.observe(1.0)  # disarmed: must not touch the estimator
    assert t._n == 0


def test_record_access_promotes_and_sweep_demotes():
    import time as _t

    ownership.configure(enabled=True, members="h0,h1,h2", self_id="h0",
                        groups=32, rf=2, hot_rate=0.01)
    up0 = obs.hbm_replica_promotions.value(dir="up")
    down0 = obs.hbm_replica_promotions.value(dir="down")
    events: list = []
    OWNERSHIP.set_change_hook(
        lambda g, d, reps: events.append((g, d, reps)))
    # one access books rate 1/30 ≈ 0.033 ≥ the 0.01 threshold: promote
    OWNERSHIP.record_access("blk-0")
    g = OWNERSHIP.group_of("blk-0")
    assert g in OWNERSHIP._promoted
    reps = OWNERSHIP.replicas_of("blk-0")
    assert len(reps) == 2 and reps[0] == OWNERSHIP.owner_of("blk-0")
    assert len(OWNERSHIP.replica_indices("blk-0")) == 2
    assert obs.hbm_replica_promotions.value(dir="up") == up0 + 1
    # every replica owns the promoted group (serves it device-resident);
    # the third member still doesn't
    for m in reps:
        with ownership.self_as(m):
            assert OWNERSHIP.owns_block("blk-0")
            assert OWNERSHIP.is_replica("blk-0")
    (other,) = set(OWNERSHIP.members) - set(reps)
    with ownership.self_as(other):
        assert not OWNERSHIP.owns_block("blk-0")
    # two minutes of silence: the rate decays below the hysteresis
    # floor and the sweep demotes
    assert OWNERSHIP.sweep(now=_t.monotonic() + 120.0) == 1
    assert g not in OWNERSHIP._promoted
    assert OWNERSHIP.replica_indices("blk-0") == ()
    assert obs.hbm_replica_promotions.value(dir="down") == down0 + 1
    # the change hook saw both transitions (fired on background threads)
    deadline = _t.time() + 5
    while _t.time() < deadline and len(events) < 2:
        _t.sleep(0.01)
    assert [e[1] for e in events] == ["up", "down"]
    assert events[0][0] == g and events[0][2] == reps


def test_demotion_is_hysteretic():
    """A group whose rate sits between half the threshold and the
    threshold stays promoted — oscillating around hot_rate must not
    flap replica residency."""
    import time as _t

    ownership.configure(enabled=True, members="h0,h1", self_id="h0",
                        groups=32, rf=2, hot_rate=0.02)
    OWNERSHIP.record_access("blk-0")  # 0.033 ≥ 0.02: promoted
    g = OWNERSHIP.group_of("blk-0")
    assert g in OWNERSHIP._promoted
    # 24 s of decay: rate ≈ 0.015 — under the threshold but above the
    # 0.01 floor. No demotion.
    assert OWNERSHIP.sweep(now=_t.monotonic() + 24.0) == 0
    assert g in OWNERSHIP._promoted


def test_snapshot_heat_and_hedge_shape():
    ownership.configure(enabled=True, members="h0,h1,h2", self_id="h0",
                        groups=32, rf=2, hot_rate=0.01,
                        hedge_delay_ms=25)
    OWNERSHIP.record_access("blk-0")
    snap = OWNERSHIP.snapshot()
    assert snap["rf"] == 2 and snap["replicated"] is True
    assert snap["hot_rate"] == 0.01
    row = snap["heat"][str(OWNERSHIP.group_of("blk-0"))]
    assert row["promoted"] is True and row["rf"] == 2
    assert len(row["replicas"]) == 2
    assert row["rate"] > 0 and "promoted_t" in row
    assert snap["hedge"]["armed"] is True
    assert snap["hedge"]["delay_ms"] == 25.0


def test_hedge_timer_delay_derivation():
    t = ownership.HedgeTimer()
    # disarmed: the default, after one attribute read
    assert t.delay_s() == 0.05
    t.armed = True
    t.fixed_ms = 40.0
    assert t.delay_s() == pytest.approx(0.040)
    t.fixed_ms = 0.0
    # profiler-stage seed carries the estimate before direct samples
    t._on_stage("execute", "device", 0.02, 0)
    assert t.delay_s() == pytest.approx(0.06)
    t._on_stage("header_prune", "host", 9.9, 0)  # not a dispatch stage
    assert t.delay_s() == pytest.approx(0.06)
    # enough direct observations: Jacobson/Karels mean + 3*dev
    for _ in range(12):
        t.observe(0.05)
    assert 0.05 <= t.delay_s() <= 0.2
    t.reset()
    assert t.armed is False and t._n == 0


def test_configure_rf_change_rebuilds_replica_depth():
    """Raising rf after the members installed rebuilds the replica
    table at the new depth (generation bumps: the frontend's plans
    must re-key — routing potential changed)."""
    ownership.configure(enabled=True, members="h0,h1,h2", self_id="h0",
                        groups=32)
    gen = OWNERSHIP.generation
    assert OWNERSHIP._replica_depth == 1
    ownership.configure(rf=2, hot_rate=0.5)
    assert OWNERSHIP._replica_depth == 2
    assert OWNERSHIP.generation == gen + 1
    # idempotent re-configure at the same depth: no churn
    ownership.configure(rf=2, hot_rate=0.5)
    assert OWNERSHIP.generation == gen + 1


def test_group_resize_clears_heat_state():
    ownership.configure(enabled=True, members="h0,h1", self_id="h0",
                        groups=32, rf=2, hot_rate=0.01)
    OWNERSHIP.record_access("blk-0")
    assert OWNERSHIP._promoted
    ownership.configure(groups=64, members="h0,h1")
    # group ids re-hashed: stale heat/promotions describe dead groups
    assert not OWNERSHIP._promoted and OWNERSHIP._heat == {}


# ----------------------------------------- hedged dispatch (frontend)


def test_owner_querier_plan_width_and_replica_preference():
    """Satellite: the owner→querier mapping keys on the PLAN-TIME pool
    width (riding the generation-keyed batch plan), so a pool resize
    mid-flight cannot silently remap every owner; replica retries walk
    the replica set before the round-robin fallback."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend

    fe = QueryFrontend(["q0", "q1", "q2"], FrontendConfig())
    # plan-time width pins the mapping even though the live pool is 3
    assert fe._owner_querier(2, 0, 2) == "q0"   # 2 % plan-width 2
    assert fe._owner_querier(1, 0, 2) == "q1"
    # replica preference: attempts 1..rf-1 walk the replica set
    assert fe._owner_querier(2, 0, 3, (2, 0)) == "q2"
    assert fe._owner_querier(2, 1, 3, (2, 0)) == "q0"
    # past the replica set: round-robin fallback
    assert fe._owner_querier(2, 2, 3, (2, 0)) in ("q0", "q1", "q2")
    # a plan index past a SHRUNK pool degrades to round-robin, never an
    # IndexError or an arbitrary wrong owner
    small = QueryFrontend(["q0", "q1"], FrontendConfig())
    assert small._owner_querier(5, 0, 6) in ("q0", "q1")


class _FakeQuerier:
    """search_blocks stub with a programmable wall/failure — the
    hedged-send race harness. Checks the per-attempt deadline between
    'groups' like the real batcher, so a cancelled loser stops early."""

    def __init__(self, resp, delay_s=0.0, fail=False):
        self.resp = resp
        self.delay_s = delay_s
        self.fail = fail
        self.calls = 0
        self.cancelled = 0

    def search_blocks(self, breq):
        from tempo_tpu.robustness import deadline as _dl
        import time as _t

        self.calls += 1
        t_end = _t.monotonic() + self.delay_s
        while _t.monotonic() < t_end:
            if _dl.expired():
                self.cancelled += 1
                raise robustness.DeadlineExceeded("cancelled mid-scan")
            _t.sleep(0.005)
        if self.fail:
            raise RuntimeError("querier died")
        return self.resp


def _hedge_armed(fixed_ms=20.0):
    from tempo_tpu.search.ownership import HEDGE

    HEDGE.armed = True
    HEDGE.fixed_ms = fixed_ms
    return HEDGE


def test_hedged_send_primary_wins_inside_delay():
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend

    _hedge_armed(fixed_ms=50.0)
    primary = _FakeQuerier("fast", delay_s=0.0)
    hedge = _FakeQuerier("never", delay_s=0.0)
    fe = QueryFrontend([primary, hedge], FrontendConfig())
    before = obs.hedged_dispatches.value(result="primary")
    r = fe._hedged_send(tempopb.SearchBlocksRequest(), primary, hedge)
    assert r == "fast"
    assert hedge.calls == 0  # the hedge never fired
    assert obs.hedged_dispatches.value(result="primary") == before + 1


def test_hedged_send_replica_wins_and_loser_cancelled():
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend

    _hedge_armed(fixed_ms=20.0)
    primary = _FakeQuerier("slow", delay_s=5.0)   # wedged past the delay
    hedge = _FakeQuerier("fast", delay_s=0.0)
    fe = QueryFrontend([primary, hedge], FrontendConfig())
    won0 = obs.hedged_dispatches.value(result="hedge_won")
    can0 = obs.hedged_dispatches.value(result="cancelled")
    r = fe._hedged_send(tempopb.SearchBlocksRequest(), primary, hedge)
    assert r == "fast"
    assert hedge.calls == 1
    assert obs.hedged_dispatches.value(result="hedge_won") == won0 + 1
    assert obs.hedged_dispatches.value(result="cancelled") == can0 + 1
    # the loser's force-expired deadline stops it at the next check —
    # it must not burn its full 5 s wall
    deadline = __import__("time").time() + 3
    while __import__("time").time() < deadline and not primary.cancelled:
        __import__("time").sleep(0.01)
    assert primary.cancelled == 1


def test_hedged_send_fast_primary_failure_raises_for_retry():
    """A primary that FAILS inside the hedge delay raises immediately —
    _retrying moves to the surviving replica (attempt 1 prefers it)
    instead of waiting out the delay."""
    from tempo_tpu.modules.frontend import FrontendConfig, QueryFrontend

    _hedge_armed(fixed_ms=5000.0)  # the delay must not be waited out
    primary = _FakeQuerier(None, delay_s=0.0, fail=True)
    hedge = _FakeQuerier("alive", delay_s=0.0)
    fe = QueryFrontend([primary, hedge], FrontendConfig())
    t0 = __import__("time").monotonic()
    with pytest.raises(RuntimeError, match="querier died"):
        fe._hedged_send(tempopb.SearchBlocksRequest(), primary, hedge)
    assert __import__("time").monotonic() - t0 < 2.0
    assert hedge.calls == 0


def test_dispatch_batch_hedges_only_promoted_groups(tmp_path):
    """End to end through _dispatch_batch: an un-promoted group keeps
    the exact rf=1 single dispatch; a promoted one hedges and stays
    byte-identical."""
    db, proxies, fe = _frontend(tmp_path)
    req = _req(limit=10_000)
    base = _canon(fe.search("t", req))
    ownership.configure(enabled=True, members="m0,m1", self_id="m0",
                        groups=32, rf=2, hot_rate=0.01,
                        hedge_delay_ms=15)
    for p in proxies:
        p.block_batches.clear()
    calls_before = sum(len(p.block_batches) for p in proxies)
    assert calls_before == 0
    # not promoted yet: no hedging, one dispatch per batch
    assert _canon(fe.search("t", req)) == base
    batches = fe._search_batches("t")
    n_owned = sum(1 for b in batches if b[2] is not None)
    assert sum(len(p.block_batches) for p in proxies) == n_owned
    # promote every group, wedge the primaries: the hedge answers and
    # the response stays byte-identical
    for m in db.blocklist.metas("t"):
        OWNERSHIP.record_access(m.block_id)
    won0 = obs.hedged_dispatches.value(result="hedge_won")

    class _SlowFirst:
        """Delay injected around member-0's querier only."""

        def __init__(self, inner):
            self.inner = inner
            self.db = inner.db

        def search_recent(self, tenant, req):
            return self.inner.search_recent(tenant, req)

        def search_blocks(self, breq):
            __import__("time").sleep(0.25)
            return self.inner.search_blocks(breq)

    fe.queriers[0] = _SlowFirst(proxies[0])
    got = _canon(fe.search("t", req))
    assert got == base
    # at least one batch was owned by the slow member: its hedge won
    if any(b[2] == 0 for b in batches):
        assert obs.hedged_dispatches.value(result="hedge_won") > won0
