"""Concurrency stress suite — the `go test -race` analog (SURVEY.md §5).

Python has no compiler race detector, and the GIL does not prevent
logical races (check-then-act windows, lost updates across bytecode
boundaries, iteration-during-mutation). This suite hammers every
structure the design documents as concurrent — live-trace maps under
push/flush, the blocklist's staged updates during reads, ring
membership during owner lookups, the metrics registry, the request
queue, gossip merge — from many threads with exact-count invariants,
and fails fast (watchdog, thread-exception capture) instead of
deadlocking the run.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest


class Harness:
    """Runs workers concurrently, re-raising any worker exception and
    enforcing a wall-clock deadline (a hung lock fails, not hangs, CI)."""

    def __init__(self, deadline_s: float = 60.0):
        self.deadline_s = deadline_s
        self.stop = threading.Event()
        self.errors: list[BaseException] = []
        self._lock = threading.Lock()

    def _wrap(self, fn):
        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — reported to pytest
                with self._lock:
                    self.errors.append(e)
                self.stop.set()

        return run

    def run(self, *fns, duration_s: float = 1.5):
        threads = [threading.Thread(target=self._wrap(f), daemon=True)
                   for f in fns]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        self.stop.wait(duration_s)
        self.stop.set()
        for t in threads:
            t.join(timeout=self.deadline_s - (time.monotonic() - t0))
            assert not t.is_alive(), "worker deadlocked (watchdog)"
        if self.errors:
            raise self.errors[0]


def test_push_flush_search_concurrently(tmp_path):
    """Writers + searchers + maintenance ticks on one App: every pushed
    trace must be findable afterwards — no lost writes, no exceptions."""
    from tempo_tpu import tempopb
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    h = Harness()
    written: list[bytes] = []
    wlock = threading.Lock()

    def writer(k):
        def run():
            i = 0
            while not h.stop.is_set():
                tid = random_trace_id()
                app.push("race", list(make_trace(tid, seed=k * 10_000 + i).batches))
                with wlock:
                    written.append(tid)
                i += 1

        return run

    def searcher():
        req = tempopb.SearchRequest()
        req.limit = 5
        while not h.stop.is_set():
            app.search("race", req)

    def maintenance():
        while not h.stop.is_set():
            app.flush_tick(force=True)
            app.poll_tick()
            app.compaction_tick()

    h.run(writer(1), writer(2), writer(3), searcher, searcher, maintenance,
          duration_s=2.0)
    # settle: one final flush+poll, then every write must be readable
    app.flush_tick(force=True)
    app.poll_tick()
    assert len(written) > 50
    missing = [t for t in written
               if not len(app.find_trace("race", t).trace.batches)]
    assert not missing, f"{len(missing)}/{len(written)} traces lost"
    app.shutdown()


def test_metrics_registry_exact_counts_under_contention():
    """Lost-update check: N threads x M incs must land exactly N*M, with
    expose() running concurrently (iteration-during-mutation)."""
    from tempo_tpu.observability.metrics import Counter, Histogram, Registry

    reg = Registry()
    c = Counter("race_total", registry=reg)
    hist = Histogram("race_seconds", registry=reg)
    N, M = 8, 5_000

    def inc():
        for i in range(M):
            c.inc(tenant="t")
            hist.observe(i / M, tenant="t")

    def scrape():
        for _ in range(200):
            reg.expose()
            reg.samples()

    with ThreadPoolExecutor(N + 2) as ex:
        futs = [ex.submit(inc) for _ in range(N)] + [ex.submit(scrape) for _ in range(2)]
        for f in futs:
            f.result()
    assert c.value(tenant="t") == N * M
    (_, _, count) = [s for s in hist.samples() if s[0].endswith("_count")][0]
    assert count == N * M


def test_ring_membership_during_owner_lookups():
    """Heartbeat/join/leave churn while readers shard keys — lookups never
    raise and always return a live instance."""
    from tempo_tpu.modules.ring import Ring
    from tempo_tpu.utils.hashing import token_for

    ring = Ring(replication_factor=2)
    for i in range(4):
        ring.register(f"stable-{i}")
    h = Harness()

    def churn():
        i = 0
        while not h.stop.is_set():
            iid = f"churn-{i % 8}"
            ring.register(iid)
            ring.heartbeat(iid)
            if i % 3 == 0:
                ring.leave(iid)
            i += 1

    def reader():
        import os
        while not h.stop.is_set():
            owners = ring.get(token_for("t", os.urandom(16)))
            assert owners, "ring returned no owners with stable members"

    h.run(churn, churn, reader, reader, reader, duration_s=1.5)


def test_request_queue_drains_exactly_once():
    """Concurrent producers/consumers: every enqueued job consumed exactly
    once, per-tenant fairness structure intact."""
    from tempo_tpu.modules.queue import RequestQueue

    q = RequestQueue(max_outstanding_per_tenant=10_000)
    N_PROD, PER = 4, 2_000
    seen: set[tuple] = set()
    slock = threading.Lock()
    done = threading.Event()

    def producer(k):
        for i in range(PER):
            q.enqueue(f"tenant-{k % 2}", (k, i))

    def consumer():
        while True:
            got = q.get(timeout=0.05)
            if got is None:
                if done.is_set() and not any(q.lengths().values()):
                    return
                continue
            _tenant, item = got
            with slock:
                assert item not in seen, f"double-delivery of {item}"
                seen.add(item)

    with ThreadPoolExecutor(8) as ex:
        cons = [ex.submit(consumer) for _ in range(3)]
        prods = [ex.submit(producer, k) for k in range(N_PROD)]
        for f in prods:
            f.result()
        done.set()
        for f in cons:
            f.result(timeout=30)
    assert len(seen) == N_PROD * PER


def test_gossip_merge_during_ticks():
    """Concurrent merges (incoming exchanges) + local ticks must keep the
    member map consistent (no exceptions, monotone heartbeats)."""
    from tempo_tpu.modules.membership import Memberlist

    a = Memberlist("a", "ingester", bind="127.0.0.1:0")
    b = Memberlist("b", "querier", bind="127.0.0.1:0",
                   join=[a.gossip_addr])
    c = Memberlist("c", "querier", bind="127.0.0.1:0",
                   join=[a.gossip_addr])
    h = Harness()

    def tick(ml):
        def run():
            while not h.stop.is_set():
                ml.tick()

        return run

    high_water: dict[tuple[str, str], int] = {}
    hw_lock = threading.Lock()

    def read(ml):
        def run():
            while not h.stop.is_set():
                ms = ml.members(alive_only=False)
                assert len({m.id for m in ms}) == len(ms), "duplicate member"
                for m in ms:
                    key = (ml.id, m.id)
                    with hw_lock:
                        prev = high_water.get(key, 0)
                        # a torn merge would let a member's heartbeat
                        # counter go backwards on this node's view
                        assert m.heartbeat >= prev, (
                            f"{key}: heartbeat regressed {prev}→{m.heartbeat}"
                        )
                        high_water[key] = m.heartbeat

        return run

    try:
        h.run(tick(a), tick(b), tick(c), read(a), read(b), read(c),
              duration_s=2.0)
        ids = {m.id for m in a.members(alive_only=False)}
        assert ids >= {"a", "b", "c"}
    finally:
        for ml in (a, b, c):
            ml.shutdown()


def test_netcache_background_writer_under_load():
    """Write-behind cache: concurrent stores drain without loss beyond the
    documented bounded-queue drops, and reads never raise."""
    from tempo_tpu.backend.netcache import BackgroundCache

    class Slow:
        def __init__(self):
            self.data = {}
            self.lock = threading.Lock()

        def store(self, key, val):
            with self.lock:
                self.data[key] = val

        def fetch(self, key):
            with self.lock:
                return self.data.get(key)

        def stop(self):
            pass

    inner = Slow()
    bc = BackgroundCache(inner, queue_size=10_000)
    N = 2_000

    def store(k):
        for i in range(N):
            bc.store(f"k-{k}-{i}", b"v" * 32)

    def read():
        for i in range(N):
            bc.fetch(f"k-0-{i}")

    with ThreadPoolExecutor(4) as ex:
        futs = [ex.submit(store, k) for k in range(3)] + [ex.submit(read)]
        for f in futs:
            f.result()
    bc.flush(timeout_s=30)  # drain write-behind queue before asserting
    bc.stop()
    assert len(inner.data) == 3 * N  # queue was large enough: zero drops


def test_pull_dispatch_exact_counts_under_worker_churn():
    """Pull dispatch invariant under churn: every submitted job resolves
    exactly once (result or JobFailed) while worker streams connect and
    die continuously — no lost futures, no double delivery."""
    import socket

    from tempo_tpu import tempopb
    from tempo_tpu.api.grpc_service import make_module_grpc_server
    from tempo_tpu.modules.worker import (
        JobFailed, PullDispatcher, PullQuerierStub, PullWorker,
    )

    class CountingQuerier:
        def __init__(self):
            self.lock = threading.Lock()
            self.served = 0

        def search_tag_values(self, tenant, tag):
            with self.lock:
                self.served += 1
            resp = tempopb.SearchTagValuesResponse()
            resp.tag_values.append(tag)
            return resp

    d = PullDispatcher(max_redeliveries=8)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    server = make_module_grpc_server(f"127.0.0.1:{port}",
                                     frontend_dispatcher=d)
    server.start()
    q = CountingQuerier()
    stop_churn = threading.Event()

    def churn():
        # workers live ~80ms then die mid-whatever they hold
        while not stop_churn.is_set():
            w = PullWorker(q, f"127.0.0.1:{port}", parallelism=2,
                           reconnect_backoff_s=0.05)
            time.sleep(0.08)
            w.stop()

    churners = [threading.Thread(target=churn, daemon=True)
                for _ in range(2)]
    for t in churners:
        t.start()
    # one stable worker guarantees eventual progress
    stable = PullWorker(q, f"127.0.0.1:{port}", parallelism=2)

    N = 120
    stub = PullQuerierStub(d, job_timeout_s=30)
    outcomes = []
    out_lock = threading.Lock()

    def one(i):
        tenant = f"tenant-{i % 5}"
        try:
            r = stub.search_tag_values(tenant, f"k{i}")
            with out_lock:
                outcomes.append(("ok", r.tag_values[0]))
        except JobFailed:
            with out_lock:
                outcomes.append(("failed", None))

    try:
        with ThreadPoolExecutor(max_workers=16) as ex:
            list(ex.map(one, range(N)))
    finally:
        stop_churn.set()
        for t in churners:
            t.join(timeout=5)
        stable.stop()
        d.stop()
        server.stop(0)

    # exactly one outcome per job; churn may fail SOME jobs past the
    # redelivery budget, but the overwhelming majority must succeed and
    # nothing may hang or double-resolve
    assert len(outcomes) == N
    oks = [v for s, v in outcomes if s == "ok"]
    assert len(oks) >= N * 0.9, f"only {len(oks)}/{N} succeeded under churn"
    assert len(set(oks)) == len(oks)  # each job's answer is its own
    assert not d._pending, "pending table leaked entries"
