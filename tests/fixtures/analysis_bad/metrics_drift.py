"""Metric-catalog fixture: a metric registered without a docs row, a
catalogued metric written with an undeclared label, and the clean twins.

The self-tests construct :class:`MetricsCatalogChecker` with an
injected catalog ({"tempo_fixture_good_total": {"tenant"}}) so no doc
file is involved. No locks, no jit, no guarded receivers — this file
must stay invisible to the other checkers (the lock-order CLI test pins
its fixture finding count).
"""


class Counter:  # stand-in ctor shape; the checker matches statically
    def __init__(self, name, help_=""):
        self.name = name

    def inc(self, n=1, **labels):
        pass


# BAD: tempo-prefixed metric with no catalog row
uncatalogued_metric = Counter("tempo_fixture_missing_total",
                              "registered but never documented")

# catalogued (by the injected catalog) — the write sites below exercise
# the label check
good_metric = Counter("tempo_fixture_good_total", "has a catalog row")


def bad_label_write():
    # BAD: `shard` is not in the catalog row's labels cell
    good_metric.inc(tenant="t1", shard="s0")


def clean_label_write():
    # GOOD twin: only catalogued labels
    good_metric.inc(tenant="t1")


def dynamic_labels_skipped(labels):
    # GOOD: **expansion is not statically checkable — must stay silent
    good_metric.inc(**labels)
