"""Known-bad fixture package for the static-analysis suite's
self-tests (tests/test_static_analysis.py).

Each module reproduces a bug class the suite exists to catch — the PR 1
rendezvous-deadlock lock cycle, a noop-contract gate violation, a
tracer leak in a jit body. These files are PARSED by the checkers,
never imported or executed; they also carry clean twins of each
construct so the self-tests pin the checkers' precision (no
false positives) alongside their recall.
"""
