"""Noop-contract violations: work before the gate, unguarded record
calls. ``record_clean`` / ``hit_guarded`` are the good twins."""

import time

from . import fakes as obs

FAULTS = object()        # stand-in singleton; never executed
TELEMETRY = object()
HEDGE = object()
ANALYTICS = object()


class Telemetry:
    def __init__(self):
        self.enabled = True

    def record_thing(self, seconds):
        # VIOLATION: metric write + clock read before the gate test —
        # the disabled path pays both on every call
        obs.things_recorded.inc()
        t = time.time()
        if not self.enabled:
            return
        obs.thing_seconds.observe(t - seconds)

    def record_clean(self, seconds):
        if not self.enabled:
            return
        obs.things_recorded.inc()
        obs.thing_seconds.observe(time.time() - seconds)


def hit_unguarded():
    # VIOLATION: record-protocol call with no dominating .active check
    FAULTS.hit("some_faultpoint")


def hit_guarded():
    if FAULTS.active:
        FAULTS.hit("some_faultpoint")


def record_unguarded(age):
    # VIOLATION: telemetry record with no dominating .enabled check
    TELEMETRY.record_age(age)


def hit_inverted_gate():
    # VIOLATION: the polarity trap — this exits on the ARMED path and
    # runs the record protocol on the disabled one
    if FAULTS.active:
        return
    FAULTS.hit("some_faultpoint")


def record_with_item(span):
    # VIOLATION: a record-protocol call used as a context manager is
    # still a record-protocol call
    with TELEMETRY.record_span(span):
        pass


def hit_in_else():
    # VIOLATION: the else branch of a gate test is the gate-OFF path —
    # it must not inherit the guard credit
    if FAULTS.active:
        pass
    else:
        FAULTS.hit("some_faultpoint")


def hedge_unguarded(seconds):
    # VIOLATION: hedge-timer touch with no dominating .armed check —
    # the rf=1 deployment would pay the estimator lock on every call
    HEDGE.observe(seconds)


def hedge_guarded(seconds):
    if HEDGE.armed:
        HEDGE.observe(seconds)


def analytics_unguarded(batch):
    # VIOLATION: analytics staging with no dominating gate check — the
    # disabled deployment would build the composite-key column on every
    # search
    ANALYTICS.stage_for_batch(batch)


def analytics_guarded(batch):
    if ANALYTICS.enabled:
        ANALYTICS.stage_for_batch(batch)
