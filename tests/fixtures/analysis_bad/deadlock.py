"""The PR 1 rendezvous-deadlock shape, as a lock cycle.

Two "device queue" locks; ``dispatch_ab`` takes A then B, ``dispatch_ba``
takes B then A — two threads entering from different ends deadlock,
exactly how concurrent shard_map dispatch from two threads interleaved
the per-device program queues. Plus the blocking-under-lock shape (a
Future.result() while holding a dispatch lock) and a self-reacquire.
``clean_dispatch`` is the good twin: same locks, one global order,
blocking call made after release.
"""

import threading

queue_lock_a = threading.Lock()
queue_lock_b = threading.Lock()


def dispatch_ab(program):
    with queue_lock_a:
        with queue_lock_b:
            program.enqueue()


def dispatch_ba(program):
    # opposite order: the A->B / B->A cycle the analyzer must flag
    with queue_lock_b:
        with queue_lock_a:
            program.enqueue()


def wait_under_lock(fut):
    # blocking-under-lock: result() parks this thread while every other
    # dispatcher queues behind queue_lock_a
    with queue_lock_a:
        return fut.result()


def reacquire(program):
    with queue_lock_a:
        return helper_locked(program)


def helper_locked(program):
    # called with queue_lock_a held: non-reentrant self-deadlock
    with queue_lock_a:
        return program.enqueue()


def clean_dispatch(program, fut):
    # good twin: consistent order, sync outside the locked region
    with queue_lock_a:
        with queue_lock_b:
            out = program.enqueue()
    return out, fut.result(timeout=5.0)


# -- cycle THROUGH a context-manager helper (the locked_collective
# shape): the helper's acquisition must reach callers' summaries, or
# this AB/BA pair is invisible

import contextlib

enqueue_lock = threading.Lock()


@contextlib.contextmanager
def hold_enqueue():
    enqueue_lock.acquire()
    try:
        yield
    finally:
        enqueue_lock.release()


def submit_through_helper(program):
    with queue_lock_b:
        with hold_enqueue():       # B -> enqueue_lock
            program.enqueue()


def submit_reversed(program):
    with hold_enqueue():
        with queue_lock_b:         # enqueue_lock -> B
            program.enqueue()


def wait_none_under_lock(fut):
    # result(None) is EXPLICITLY unbounded — it must not pass for a
    # bounded wait just because an argument is present
    with queue_lock_a:
        return fut.result(None)


def clean_try_acquire(other_lock):
    # good twin: acquire(blocking=False) returns immediately — holding
    # a lock across it is fine
    with queue_lock_a:
        return other_lock.acquire(blocking=False)
