"""Metric stand-ins for the gate fixture (parsed, never imported)."""


class _Noop:
    def inc(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass


things_recorded = _Noop()
thing_seconds = _Noop()
