"""Jit-purity violations: tracer leaks inside a kernel body.
``clean_kernel`` is the good twin (shape reads, None tests, static
branching are all allowed)."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("top_k",))
def leaky_kernel(scores, mask, *, top_k):
    t0 = time.time()               # VIOLATION: clock read in jit body
    best = scores.max()
    if best > 0:                   # VIOLATION: Python branch on tracer
        scores = scores + 1
    peak = best.item()             # VIOLATION: .item() host sync
    host = np.asarray(scores)      # VIOLATION: host materialization
    n = int(scores[0])             # VIOLATION: int() on a tracer
    return scores, mask, t0, peak, host, n


@functools.partial(jax.jit, static_argnames=())
def retraced_kernel(scores, *, top_k):
    # VIOLATION (cache-key hygiene): keyword-only shape knob not in
    # static_argnames — every distinct top_k silently retraces
    return jax.lax.top_k(scores, 4)


def _unpack_fixture(arr, w):
    """Width-descriptor-shaped helper (packed-residency idiom): branches
    on its descriptor, so a tracer reaching `w` is a trace-time leak."""
    if w == "u4":
        return arr & 0xF
    return arr


@functools.partial(jax.jit, static_argnames=("widths",))
def descriptor_taint_kernel(arr, sel, *, widths):
    # VIOLATION: tracer data passed as a width descriptor — the helper
    # branches on it at trace time
    return _unpack_fixture(arr, sel[0])


@functools.partial(jax.jit, static_argnames=("widths",))
def descriptor_clean_kernel(arr, *, widths):
    # the good twin: the descriptor comes from the static `widths`
    return _unpack_fixture(arr, widths[0])


def _lower_fixture(arr, plan):
    """Plan-descriptor-shaped helper (structural-engine idiom): the
    lowering recurses/branches on its plan at trace time, so a tracer
    reaching `plan` is a trace-time leak."""
    if plan is None:
        return arr
    if plan[0] == "and":
        return arr & 1
    return arr


@functools.partial(jax.jit, static_argnames=("plan",))
def plan_taint_kernel(arr, sel, *, plan):
    # VIOLATION: tracer data passed as a structural plan descriptor —
    # the lowering branches on it at trace time
    return _lower_fixture(arr, sel)


@functools.partial(jax.jit, static_argnames=("plan",))
def plan_clean_kernel(arr, *, plan):
    # the good twin: the descriptor comes from the static `plan`
    return _lower_fixture(arr, plan)


def _layout_fixture(arr, span_sharded):
    """Span-layout-descriptor-shaped helper (segment-aligned span
    sharding idiom): selects the replicated-vs-sharded evaluation
    placement by branching on its descriptor at trace time, so a
    tracer reaching `span_sharded` is a trace-time leak."""
    if span_sharded:
        return arr[: arr.shape[0] // 2]
    return arr


@functools.partial(jax.jit, static_argnames=("span_sharded",))
def span_layout_taint_kernel(arr, sel, *, span_sharded):
    # VIOLATION: tracer data passed as the span-layout descriptor —
    # the helper picks the layout branch at trace time
    return _layout_fixture(arr, sel[0])


@functools.partial(jax.jit, static_argnames=("span_sharded",))
def span_layout_clean_kernel(arr, *, span_sharded):
    # the good twin: the descriptor comes from the static `span_sharded`
    return _layout_fixture(arr, span_sharded)


def _bucket_fixture(arr, bucket):
    """Shape-bucket-descriptor-shaped helper (bucketed cross-plan
    stacking idiom): unpacks slot tiers from its descriptor at trace
    time, so a tracer reaching `bucket` is a trace-time leak."""
    if bucket[1]:
        return arr[: bucket[1]]
    return arr


@functools.partial(jax.jit, static_argnames=("bucket",))
def bucket_taint_kernel(arr, sel, *, bucket):
    # VIOLATION: tracer data passed as the shape-bucket descriptor —
    # the helper unpacks slot tiers from it at trace time
    return _bucket_fixture(arr, sel)


@functools.partial(jax.jit, static_argnames=("bucket",))
def bucket_clean_kernel(arr, *, bucket):
    # the good twin: the descriptor comes from the static `bucket`
    return _bucket_fixture(arr, bucket)


def _tier_fixture(arr, tier):
    """Capacity-tier-descriptor-shaped helper (hot-tier rolling stage
    idiom): selects the capacity-masking arm by branching on its
    descriptor at trace time, so a tracer reaching `tier` is a
    trace-time leak."""
    if tier is not None and tier:
        return arr[:tier]
    return arr


@functools.partial(jax.jit, static_argnames=("tier",))
def tier_taint_kernel(arr, sel, *, tier):
    # VIOLATION: tracer data passed as the capacity-tier descriptor —
    # the helper picks the masking arm on it at trace time
    return _tier_fixture(arr, sel[0])


@functools.partial(jax.jit, static_argnames=("tier",))
def tier_clean_kernel(arr, *, tier):
    # the good twin: the descriptor comes from the static `tier`
    return _tier_fixture(arr, tier)


@functools.partial(jax.jit, static_argnames=("top_k",))
def clean_kernel(scores, mask, extra=None, *, top_k):
    n = scores.shape[0]            # shape reads are static: fine
    k = min(top_k, n)
    if extra is not None:          # None-ness is static: fine
        scores = scores + extra
    if k > 16:                     # branches on statics: fine
        scores = scores * 2
    masked = jnp.where(mask, scores, -1)
    return jax.lax.top_k(masked, k)
