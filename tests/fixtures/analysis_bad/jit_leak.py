"""Jit-purity violations: tracer leaks inside a kernel body.
``clean_kernel`` is the good twin (shape reads, None tests, static
branching are all allowed)."""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("top_k",))
def leaky_kernel(scores, mask, *, top_k):
    t0 = time.time()               # VIOLATION: clock read in jit body
    best = scores.max()
    if best > 0:                   # VIOLATION: Python branch on tracer
        scores = scores + 1
    peak = best.item()             # VIOLATION: .item() host sync
    host = np.asarray(scores)      # VIOLATION: host materialization
    n = int(scores[0])             # VIOLATION: int() on a tracer
    return scores, mask, t0, peak, host, n


@functools.partial(jax.jit, static_argnames=())
def retraced_kernel(scores, *, top_k):
    # VIOLATION (cache-key hygiene): keyword-only shape knob not in
    # static_argnames — every distinct top_k silently retraces
    return jax.lax.top_k(scores, 4)


@functools.partial(jax.jit, static_argnames=("top_k",))
def clean_kernel(scores, mask, extra=None, *, top_k):
    n = scores.shape[0]            # shape reads are static: fine
    k = min(top_k, n)
    if extra is not None:          # None-ness is static: fine
        scores = scores + extra
    if k > 16:                     # branches on statics: fine
        scores = scores * 2
    masked = jnp.where(mask, scores, -1)
    return jax.lax.top_k(masked, k)
