"""Prometheus remote-write shipper (modules/remote_write).

The fake receiver decodes the real wire contract — snappy-compressed
prompb.WriteRequest bodies with the remote-write headers — standing in
for Prometheus/Mimir the way the reference's e2e asserts PromQL against a
scraped mock (SURVEY.md §4 metrics_generator_test).
"""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tempo_tpu.modules.generator import MetricsGenerator
from tempo_tpu.modules.remote_write import (
    RemoteWriteShipper, encode_write_request,
)
from tempo_tpu.ops import native
from tempo_tpu.tempopb import remote_write_pb2 as prompb
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace

def _snappy_available() -> bool:
    try:
        return native.snappy_decompress(
            native.snappy_compress(b"probe")) == b"probe"
    except Exception:  # noqa: BLE001 — any failure means unavailable
        return False


pytestmark = pytest.mark.skipif(not _snappy_available(),
                                reason="native snappy unavailable")


class FakeReceiver:
    """Decoding remote-write endpoint; optionally fails first N posts."""

    def __init__(self, fail_first: int = 0):
        self.requests = []  # (tenant, WriteRequest)
        self.fail_first = fail_first
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                if outer.fail_first > 0:
                    outer.fail_first -= 1
                    self.send_response(500)
                    self.end_headers()
                    return
                assert self.headers["Content-Encoding"] == "snappy"
                assert self.headers["X-Prometheus-Remote-Write-Version"] == "0.1.0"
                raw = native.snappy_decompress(body)
                req = prompb.WriteRequest.FromString(raw)
                outer.requests.append(
                    (self.headers.get("X-Scope-OrgID"), req))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}/api/v1/push"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()  # release the listening socket

    def series(self, i: int = -1) -> dict:
        _, req = self.requests[i]
        out = {}
        for ts in req.timeseries:
            labels = {l.name: l.value for l in ts.labels}
            name = labels.pop("__name__")
            out[(name, tuple(sorted(labels.items())))] = ts.samples[0].value
        return out


def _generator_with_traffic(tenant="t1", n=5):
    gen = MetricsGenerator()
    for i in range(n):
        tr = make_trace(random_trace_id(), seed=i)
        gen.push_spans(tenant, list(tr.batches))
    return gen


def test_encode_write_request_wire():
    samples = [("calls_total", (("service", "a"),), 3.0),
               ("latency_sum", (), 1.5)]
    raw = encode_write_request(samples, 1234, {"cluster": "c1"})
    req = prompb.WriteRequest.FromString(raw)
    assert len(req.timeseries) == 2
    first = req.timeseries[0]
    assert first.labels[0].name == "__name__"  # prometheus contract
    labels = {l.name: l.value for l in first.labels}
    assert labels == {"__name__": "calls_total", "service": "a",
                      "cluster": "c1"}
    assert first.samples[0].timestamp == 1234


def test_ship_and_decode(tmp_path):
    rx = FakeReceiver()
    gen = _generator_with_traffic()
    shipper = RemoteWriteShipper(gen, rx.url, spool_dir=str(tmp_path / "sp"),
                                 external_labels={"cluster": "test"})
    try:
        shipper.tick(now_ms=1_700_000_000_000)
        assert shipper.sent == 1 and shipper.failed == 0
        tenant, req = rx.requests[0]
        assert tenant == "t1"
        series = rx.series()
        span_metric_names = {n for n, _ in series}
        assert "tempo_generator_calls_total" in str(span_metric_names) or \
            any("calls" in n for n in span_metric_names)
        # external labels on every series
        for ts in req.timeseries:
            assert any(l.name == "cluster" and l.value == "test"
                       for l in ts.labels)
        # timestamps ride the tick time
        assert req.timeseries[0].samples[0].timestamp == 1_700_000_000_000
    finally:
        rx.close()


def test_failure_spools_then_recovers(tmp_path):
    rx = FakeReceiver(fail_first=1)
    gen = _generator_with_traffic()
    shipper = RemoteWriteShipper(gen, rx.url, spool_dir=str(tmp_path / "sp"),
                                 backoff_min_s=0.0)
    try:
        shipper.tick(now_ms=1000)
        assert shipper.failed == 1 and shipper.spooled == 1
        assert len(shipper._spool_files()) == 1
        # receiver recovers: next tick drains the spool first, then ships
        # the fresh snapshot — ordering preserved via filename sort
        shipper._next_retry = 0.0
        shipper.tick(now_ms=2000)
        assert len(shipper._spool_files()) == 0
        timestamps = [r[1].timeseries[0].samples[0].timestamp
                      for r in rx.requests]
        assert timestamps == [1000, 2000]
    finally:
        rx.close()


def test_spool_survives_restart(tmp_path):
    """The WAL contract: spooled payloads from a dead shipper are shipped
    by a fresh one (reference: prometheus agent WAL survives restarts)."""
    rx = FakeReceiver(fail_first=1)
    gen = _generator_with_traffic()
    spool = str(tmp_path / "sp")
    s1 = RemoteWriteShipper(gen, rx.url, spool_dir=spool, backoff_min_s=0.0)
    s1.tick(now_ms=1000)
    assert s1.spooled == 1

    s2 = RemoteWriteShipper(MetricsGenerator(), rx.url, spool_dir=spool,
                            backoff_min_s=0.0)
    try:
        s2.tick(now_ms=2000)
        assert len(s2._spool_files()) == 0
        assert rx.requests and rx.requests[0][0] == "t1"
        assert rx.requests[0][1].timeseries[0].samples[0].timestamp == 1000
    finally:
        rx.close()


def test_spool_cap_drops_oldest(tmp_path):
    gen = _generator_with_traffic()
    shipper = RemoteWriteShipper(gen, "http://127.0.0.1:1/nope",
                                 spool_dir=str(tmp_path / "sp"),
                                 backoff_min_s=60.0, max_spool_bytes=1)
    shipper.tick(now_ms=1000)  # fails, spools (cap overridden per payload)
    shipper.tick(now_ms=2000)  # in backoff → snapshot to spool, drop oldest
    files = shipper._spool_files()
    assert len(files) == 1  # oldest dropped
    assert shipper.dropped_spool >= 1


def test_backoff_avoids_hammering(tmp_path):
    gen = _generator_with_traffic()
    shipper = RemoteWriteShipper(gen, "http://127.0.0.1:1/nope",
                                 spool_dir=str(tmp_path / "sp"),
                                 backoff_min_s=30.0)
    shipper.tick(now_ms=1000)
    assert shipper.failed == 1
    # second tick inside the backoff window: no new send attempt
    shipper.tick(now_ms=2000)
    assert shipper.failed == 1
    assert shipper.spooled >= 2  # but samples were not lost


def test_app_wiring(tmp_path):
    from tempo_tpu.modules import App, AppConfig

    rx = FakeReceiver()
    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        metrics_generator={"remote_write": {"url": rx.url,
                                            "interval_s": 0.05}},
    ))
    try:
        tr = make_trace(random_trace_id(), seed=9)
        app.push("t1", list(tr.batches))
        # distributor→generator forwarding is async (bounded queue +
        # worker, reference forwarder.go) — wait for the samples to land
        import time as _time

        deadline = _time.time() + 5
        while _time.time() < deadline:
            if "t1" in app.generator.tenants() and app.generator.registry("t1").samples():
                break
            _time.sleep(0.01)
        app.remote_write.tick()
        assert rx.requests and rx.requests[-1][0] == "t1"
    finally:
        # shut the app (final ship) while the receiver still serves —
        # closing rx first leaves the final tick blocking on its timeout
        app.shutdown()
        rx.close()
