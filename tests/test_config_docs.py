"""Configuration-docs drift guard (the configuration twin of
test_observability.py's metrics-catalog test).

PRs 1-6 each added TempoDBConfig knobs, and nothing enforced that
docs/configuration.md kept up — knob/doc skew was only caught by
review. Two invariants (unchanged since this test's hand-rolled
original; the walk now lives in the analysis drift engine and these
are thin wrappers over its declarations — tempo_tpu/analysis/drift.py
CATALOGS):

  1. every `TempoDBConfig` dataclass field name appears in
     docs/configuration.md (as the YAML knob, or in the documented
     constructor-only / renamed-knob lists) — catalog "config-fields";
  2. every YAML key the config loader actually reads
     (`*.get("<key>"...)` in cli/config.py) appears in
     docs/configuration.md — catalog "yaml-knobs".
"""

from tempo_tpu.analysis.drift import catalog_findings


def _render(findings) -> str:
    return "\n".join(f"{f.path}:{f.line}: {f.message}" for f in findings)


def test_every_tempodb_config_field_documented():
    findings = catalog_findings("config-fields")
    assert not findings, (
        "TempoDBConfig fields missing from docs/configuration.md "
        "(document the knob, or list it under 'fields without their "
        "own YAML knob'):\n" + _render(findings))


def test_every_yaml_knob_documented():
    findings = catalog_findings("yaml-knobs")
    assert not findings, (
        "YAML knobs read by cli/config.py but absent from "
        "docs/configuration.md:\n" + _render(findings))
