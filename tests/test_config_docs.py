"""Configuration-docs drift guard (the configuration twin of
test_observability.py's metrics-catalog test).

PRs 1-6 each added TempoDBConfig knobs, and nothing enforced that
docs/configuration.md kept up — knob/doc skew was only caught by
review. Two invariants:

  1. every `TempoDBConfig` dataclass field name appears in
     docs/configuration.md (as the YAML knob, or in the documented
     constructor-only / renamed-knob lists);
  2. every YAML key the config loader actually reads
     (`*.get("<key>"...)` in cli/config.py) appears in
     docs/configuration.md.
"""

import dataclasses
import os
import re

from tempo_tpu.db import TempoDBConfig

_ROOT = os.path.join(os.path.dirname(__file__), "..")


def _doc() -> str:
    with open(os.path.join(_ROOT, "docs", "configuration.md"),
              encoding="utf-8") as f:
        return f.read()


def test_every_tempodb_config_field_documented():
    doc = _doc()
    missing = sorted(
        f.name for f in dataclasses.fields(TempoDBConfig)
        if f.name not in doc
    )
    assert not missing, (
        "TempoDBConfig fields missing from docs/configuration.md "
        f"(document the knob, or list it under 'fields without their "
        f"own YAML knob'): {missing}")


_GET_RE = re.compile(r"""\.get\(\s*["']([a-z0-9_]+)["']""")


def test_every_yaml_knob_documented():
    with open(os.path.join(_ROOT, "tempo_tpu", "cli", "config.py"),
              encoding="utf-8") as f:
        src = f.read()
    keys = set(_GET_RE.findall(src))
    assert len(keys) >= 30, f"config-loader grep looks broken: {sorted(keys)}"
    doc = _doc()
    missing = sorted(k for k in keys if k not in doc)
    assert not missing, (
        "YAML knobs read by cli/config.py but absent from "
        f"docs/configuration.md: {missing}")
