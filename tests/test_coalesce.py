"""Cross-request query coalescing (search/batcher.QueryCoalescer).

Concurrent SearchRequests whose dispatches land on the same staged
BlockBatch within the coalescing window stack along a query axis and run
as ONE fused coalesced_scan_kernel launch. These tests pin down the
contract:

  - coalesced results are byte-identical to serial execution
  - the window NEVER waits for peers (timer- or size-triggered flush)
  - solo searches skip the window entirely (no added latency)
  - the HBM batch cache evicts LRU under budget pressure, skips pinned
    (actively scanned) batches, and survives blocklist invalidation
    mid-flight
"""

import random
import threading
import time

import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.observability import metrics as obs
from tempo_tpu.search import ColumnarPages, PageGeometry, SearchResults
from tempo_tpu.search.batcher import BlockBatcher, QueryCoalescer, ScanJob
from tempo_tpu.search.data import SearchData
from tempo_tpu.search.engine import fetch_coalesced_out, resolve_top_k
from tempo_tpu.search.multiblock import (
    MultiBlockEngine,
    compile_multi,
    stack_queries,
)


def _corpus(n=200, seed=0):
    """Entries with UNIQUE start seconds: the two top-k implementations
    only differ in tie-breaks among equal starts (documented as
    semantically invisible), and byte-identity tests must not depend on
    that."""
    rng = random.Random(seed)
    entries = []
    for i in range(n):
        tid = (seed.to_bytes(2, "big") + i.to_bytes(4, "big")).rjust(16, b"\x00")
        sd = SearchData(trace_id=tid)
        sd.start_s = 1_600_000_000 + seed * 1_000_000 + i
        sd.end_s = sd.start_s + 5
        sd.dur_ms = rng.randint(1, 30_000)
        sd.root_service = f"svc-{rng.randrange(6)}"
        sd.root_name = "GET /"
        sd.kvs = {
            "service.name": {sd.root_service},
            "http.status_code": {str(rng.choice([200, 404, 500]))},
        }
        entries.append(sd)
    return entries


def _blocks(n=4, entries=200):
    return [ColumnarPages.build(_corpus(entries, seed=s), PageGeometry(32, 8))
            for s in range(n)]


def _jobs(blocks):
    jobs = []
    for i, p in enumerate(blocks):
        jobs.append(ScanJob(
            key=(f"blk-{i:03d}", 0, p.n_pages), pages_fn=(lambda p=p: p),
            header=dict(p.header), n_pages=p.n_pages, n_entries=p.n_entries,
            geometry=(p.header["entries_per_page"],
                      p.header["kv_per_entry"])))
    return jobs


def _mk_req(tags=None, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def _rand_req(rng):
    tags = {}
    if rng.random() < 0.8:
        tags["service.name"] = f"svc-{rng.randrange(6)}"
    if rng.random() < 0.6:
        tags["http.status_code"] = str(rng.choice([200, 404, 500]))
    kw = {"limit": rng.choice([5, 20, 50])}
    if rng.random() < 0.4:
        kw["min_duration_ms"] = rng.choice([100, 5_000])
    if rng.random() < 0.3:
        kw["max_duration_ms"] = 25_000
    return _mk_req(tags, **kw)


# ---------------------------------------------------------------------------
# kernel-level identity


def test_coalesced_kernel_matches_serial_dispatches():
    """The fused kernel's per-query outputs equal N independent
    multi_scan_kernel dispatches exactly — counts, scores AND indices."""
    blocks = _blocks(3)
    eng = MultiBlockEngine(top_k=128)
    batch = eng.stage(blocks)
    rng = random.Random(11)
    reqs = [_rand_req(rng) for _ in range(5)]
    mqs = [compile_multi(blocks, r) for r in reqs]
    mqs = [m for m in mqs if m is not None]
    assert len(mqs) >= 2
    serial = [eng.scan(batch, mq) for mq in mqs]
    cq = stack_queries(mqs)
    k = max(resolve_top_k(eng.top_k, mq.limit) for mq in mqs)
    counts, inspected, scores, idx = fetch_coalesced_out(
        eng.coalesced_scan_async(batch, cq, k))
    for qi, (c, ins, s, i) in enumerate(serial):
        assert int(counts[qi]) == c
        assert inspected == ins
        kq = s.shape[0]
        np.testing.assert_array_equal(scores[qi][:kq], s)
        np.testing.assert_array_equal(idx[qi][:kq], i)


def test_stack_queries_buckets_shapes():
    """The jit cache must key on predicate SHAPE, not values: different
    tag-sets with the same bucketed (Q, T, R) stack to identical array
    shapes, and odd counts pad to the next power of two."""
    blocks = _blocks(2, entries=64)
    a = compile_multi(blocks, _mk_req({"service.name": "svc-1"}, limit=20))
    b = compile_multi(blocks, _mk_req({"service.name": "svc-2",
                                       "http.status_code": "500"}, limit=20))
    c = compile_multi(blocks, _mk_req({"http.status_code": "404"}, limit=20))
    s1 = stack_queries([a, b])
    s2 = stack_queries([b, c])
    assert s1.term_keys.shape == s2.term_keys.shape
    assert s1.val_ranges.shape == s2.val_ranges.shape
    s3 = stack_queries([a, b, c])  # Q=3 → pads to 4
    assert s3.term_keys.shape[0] == 4
    assert s3.n_queries == 3


# ---------------------------------------------------------------------------
# coalescer mechanics


def _engine_and_batch(blocks):
    eng = MultiBlockEngine(top_k=128)
    return eng, eng.stage(blocks)


def test_window_timeout_flushes_without_peers():
    """A lone query under (pretend) concurrency is released by the
    window TIMER — never stuck waiting for a peer that will not come."""
    blocks = _blocks(2, entries=64)
    eng, batch = _engine_and_batch(blocks)
    co = QueryCoalescer(eng, window_s=0.15, max_queries=4,
                        active_fn=lambda: 2)
    req = _mk_req({"service.name": "svc-1"}, limit=20)
    mq = compile_multi(blocks, req)
    want = eng.scan(batch, mq)
    t0 = time.perf_counter()
    fut = co.submit(batch, mq, resolve_top_k(eng.top_k, mq.limit))
    assert not fut.done(), "window should park the query, not dispatch"
    out = fut.result(timeout=10)
    waited = time.perf_counter() - t0
    assert waited >= 0.10, f"flushed after {waited * 1e3:.1f}ms, window 150ms"
    count, inspected, scores, idx = out
    assert (int(count), int(inspected)) == (want[0], want[1])
    np.testing.assert_array_equal(np.asarray(scores), want[2])
    np.testing.assert_array_equal(np.asarray(idx), want[3])


def test_max_queries_triggers_immediate_fused_flush():
    """Hitting max_queries flushes NOW — a full group never waits out
    the window (window set absurdly high to prove it)."""
    blocks = _blocks(2, entries=64)
    eng, batch = _engine_and_batch(blocks)
    co = QueryCoalescer(eng, window_s=60.0, max_queries=2,
                        active_fn=lambda: 2)
    r1 = _mk_req({"service.name": "svc-1"}, limit=20)
    r2 = _mk_req({"service.name": "svc-2"}, limit=20)
    mq1, mq2 = compile_multi(blocks, r1), compile_multi(blocks, r2)
    want1, want2 = eng.scan(batch, mq1), eng.scan(batch, mq2)
    f1 = co.submit(batch, mq1, resolve_top_k(eng.top_k, mq1.limit))
    f2 = co.submit(batch, mq2, resolve_top_k(eng.top_k, mq2.limit))
    out1 = f1.result(timeout=30)
    out2 = f2.result(timeout=30)
    assert co.fused == 1 and co.queries == 2
    for out, want in ((out1, want1), (out2, want2)):
        count, inspected, scores, idx = out
        assert (int(count), int(inspected)) == (want[0], want[1])
        kq = want[2].shape[0]
        np.testing.assert_array_equal(np.asarray(scores)[:kq], want[2])
        np.testing.assert_array_equal(np.asarray(idx)[:kq], want[3])


def test_solo_search_skips_window_entirely():
    """active_searches <= 1 → no peer can arrive → the window would be
    pure added latency; submit must dispatch inline."""
    blocks = _blocks(2, entries=64)
    eng, batch = _engine_and_batch(blocks)
    co = QueryCoalescer(eng, window_s=60.0, max_queries=8,
                        active_fn=lambda: 1)
    mq = compile_multi(blocks, _mk_req({"service.name": "svc-1"}, limit=20))
    fut = co.submit(batch, mq, resolve_top_k(eng.top_k, mq.limit))
    assert fut.done(), "solo submit must flush inline, not wait 60s"
    assert co.fused == 0 and co.dispatches == 1


def test_peers_hint_overrides_process_global_activity():
    """The per-batch `peers` hint decides the window, not the process-
    global activity count: a dispatch whose batch no other search can
    target flushes inline even while unrelated searches are in flight."""
    blocks = _blocks(2, entries=64)
    eng, batch = _engine_and_batch(blocks)
    co = QueryCoalescer(eng, window_s=60.0, max_queries=8,
                        active_fn=lambda: 99)  # process looks busy
    mq = compile_multi(blocks, _mk_req({"service.name": "svc-1"}, limit=20))
    fut = co.submit(batch, mq, resolve_top_k(eng.top_k, mq.limit), peers=1)
    assert fut.done(), "peers=1 must flush inline despite global activity"
    assert co.fused == 0 and co.dispatches == 1


def test_disjoint_concurrent_searches_skip_window():
    """Two concurrent searches over DISJOINT batches (the shape of one
    frontend request's sharded sub-requests) can never fuse, so neither
    may park in the coalescing window — with a process-global activity
    hint each group would stall ~window_s for a peer that cannot exist."""
    blocks = _blocks(4, entries=200)
    jobs = _jobs(blocks)
    half_a, half_b = jobs[:2], jobs[2:]
    req = _mk_req({"service.name": "svc-1"})
    b = BlockBatcher(max_batch_pages=8, coalesce_window_s=0.6,
                     coalesce_max_queries=8)
    # warm: stage + compile both halves outside the clock
    b.search(list(half_a), req)
    b.search(list(half_b), req)

    best = float("inf")
    for _ in range(3):  # min-of-3: tolerate one lost plan-timing race
        barrier = threading.Barrier(2)
        done = []

        def one(js):
            barrier.wait()
            t0 = time.perf_counter()
            b.search(list(js), req)
            done.append(time.perf_counter() - t0)

        ts = [threading.Thread(target=one, args=(h,))
              for h in (half_a, half_b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        best = min(best, max(done))
    assert best < 0.5, (f"disjoint concurrent searches waited out the "
                        f"coalescing window ({best:.3f}s, window 0.6s)")


def test_device_params_cached_after_deferred_window_flush():
    """The per-predicate device tables must land in the query cache even
    when the flush runs on the window-timer thread, after submit()
    returned — a submit-time harvest saw nothing there, so every repeat
    of the predicate re-uploaded its tables per dispatch."""
    from tempo_tpu.search.batcher import _predicate_sig

    blocks = _blocks(2, entries=64)
    jobs = _jobs(blocks)
    b = BlockBatcher(coalesce_window_s=0.05, coalesce_max_queries=8)
    b.search(list(jobs), _mk_req({"service.name": "svc-1"}))  # warm/stage
    req = _mk_req({"service.name": "svc-2"})  # fresh predicate, no dp yet
    # phantom peer on every staged batch: arms the window, so the solo
    # flush is timer-deferred instead of inline
    with b._lock:
        gkeys = list(b._cache)
        for k in gkeys:
            b._interest[k] = b._interest.get(k, 0) + 1
    try:
        b.search(list(jobs), req)
    finally:
        with b._lock:
            for k in gkeys:
                n = b._interest.get(k, 0) - 1
                if n <= 0:
                    b._interest.pop(k, None)
                else:
                    b._interest[k] = n
    sig = _predicate_sig(req)
    cached_dps = [c.query_cache[sig].get("device_params")
                  for c in b._cache.values() if sig in c.query_cache]
    assert cached_dps and all(dp is not None for dp in cached_dps), (
        "deferred-flush dispatch did not cache its uploaded query tables")


# ---------------------------------------------------------------------------
# serving-path property test


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_concurrent_coalesced_results_identical_to_serial(seed):
    """N concurrent searches with distinct random predicates over shared
    blocks return byte-identical SearchResponses to the same N run
    serially — with real fusion happening (asserted via the coalesced
    dispatch counter)."""
    rng = random.Random(seed)
    blocks = _blocks(3, entries=150)
    jobs = _jobs(blocks)
    N = 6
    reqs = [_rand_req(rng) for _ in range(N)]

    serial_b = BlockBatcher(coalesce_max_queries=1)  # coalescing OFF
    serial = [serial_b.search(list(jobs), r).response().SerializeToString()
              for r in reqs]

    co_b = BlockBatcher(coalesce_window_s=0.05, coalesce_max_queries=N)
    # warm staging + compile so every worker reaches the window together
    co_b.search(list(jobs), reqs[0])
    q0 = obs.coalesced_queries.value()

    out = [None] * N
    barrier = threading.Barrier(N)

    def one(i):
        barrier.wait()
        out[i] = co_b.search(
            list(jobs), reqs[i]).response().SerializeToString()

    threads = [threading.Thread(target=one, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(N):
        assert out[i] == serial[i], f"query {i} diverged under coalescing"
    assert obs.coalesced_queries.value() > q0, "no fusion happened"


def test_coalesced_against_scan_engine_oracle():
    """Acceptance cross-check: coalesced serving results equal the same
    queries run serially through the single-block ScanEngine.scan."""
    from tempo_tpu.search.engine import ScanEngine
    from tempo_tpu.search.pipeline import compile_query

    rng = random.Random(7)
    blocks = _blocks(3, entries=150)
    jobs = _jobs(blocks)
    reqs = [_rand_req(rng) for _ in range(4)]

    def oracle(req):
        results = SearchResults.for_request(req)
        eng = ScanEngine()
        for pages in blocks:
            cq = compile_query(pages.key_dict, pages.val_dict, req)
            if cq is None:
                continue
            from tempo_tpu.search.engine import stage

            sp = stage(pages)
            _c, _i, scores, idx = eng.scan_staged(sp, cq)
            for m in eng.results(sp, cq, scores, idx):
                results.add(m)
        return results

    co_b = BlockBatcher(coalesce_window_s=0.05, coalesce_max_queries=4)
    co_b.search(list(jobs), reqs[0])  # warm
    barrier = threading.Barrier(len(reqs))
    got = [None] * len(reqs)

    def one(i):
        barrier.wait()
        got[i] = co_b.search(list(jobs), reqs[i])

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, req in enumerate(reqs):
        want = sorted(
            m.SerializeToString() for m in oracle(req).response().traces)
        have = sorted(m.SerializeToString() for m in got[i].response().traces)
        assert have == want, f"query {i} diverged from ScanEngine oracle"


# ---------------------------------------------------------------------------
# HBM cache: eviction, pinning, invalidation


def test_hbm_eviction_under_budget_pressure():
    """A cache budget below the working set LRU-evicts staged batches
    (metric: batch_cache_events{result=evict}) and queries stay correct
    across the eviction churn."""
    blocks = _blocks(6, entries=200)
    jobs = _jobs(blocks)
    b = BlockBatcher(max_batch_pages=8, coalesce_max_queries=1)
    req = _mk_req({"service.name": "svc-1"}, limit=100)
    want = b.search(list(jobs), req).response().SerializeToString()
    groups = b.plan(jobs)
    assert len(groups) > 1, "budget test needs multiple groups"

    # shrink the budget below one staged group: every group staged past
    # the first must evict a predecessor
    ev0 = obs.batch_cache_events.value(result="evict")
    b.cache_bytes = 1
    got = b.search(list(jobs), req).response().SerializeToString()
    assert got == want
    assert obs.batch_cache_events.value(result="evict") > ev0
    assert len(b._cache) <= 1  # budget enforced after pins released


def test_eviction_skips_pinned_batches():
    blocks = _blocks(2, entries=64)
    jobs = _jobs(blocks)
    b = BlockBatcher(coalesce_max_queries=1)
    b.search(list(jobs), _mk_req({"service.name": "svc-1"}, limit=20))
    assert len(b._cache) == 1
    entry = next(iter(b._cache.values()))
    entry.pins = 1
    b.cache_bytes = 1
    with b._lock:
        b._evict_hbm_locked()
    assert len(b._cache) == 1, "pinned batch must survive eviction"
    entry.pins = 0
    # pins released → next search enforces the budget again
    b.search(list(jobs), _mk_req({"service.name": "svc-2"}, limit=20))
    assert b._cache_total <= max(b.cache_bytes, entry.nbytes)


def test_invalidation_mid_flight_is_safe():
    """A blocklist change (batcher.invalidate) racing an in-flight
    search must neither crash nor corrupt results; afterwards the dead
    batches are gone from both cache tiers."""
    blocks = _blocks(4, entries=150)
    jobs = _jobs(blocks)
    b = BlockBatcher(max_batch_pages=8, coalesce_window_s=0.01,
                     coalesce_max_queries=4)
    req = _mk_req({"service.name": "svc-1"}, limit=100)
    want = b.search(list(jobs), req).response().SerializeToString()

    stop = threading.Event()
    errors = []

    def invalidator():
        while not stop.is_set():
            b.invalidate(set())          # nothing is live: drop everything
            time.sleep(0.001)

    inv = threading.Thread(target=invalidator)
    inv.start()
    try:
        for _ in range(5):
            got = b.search(list(jobs), req).response().SerializeToString()
            if got != want:
                errors.append("diverged")
    finally:
        stop.set()
        inv.join()
    assert not errors
    b.invalidate(set())
    assert not b._cache and not b._host_cache


def test_debug_stats_exposes_coalesce_ratio():
    blocks = _blocks(2, entries=64)
    eng, batch = _engine_and_batch(blocks)
    co = QueryCoalescer(eng, window_s=60.0, max_queries=2,
                        active_fn=lambda: 2)
    mqs = [compile_multi(blocks, _mk_req({"service.name": f"svc-{i}"},
                                         limit=20)) for i in (1, 2)]
    futs = [co.submit(batch, mq, 128) for mq in mqs]
    for f in futs:
        f.result(timeout=30)
    s = co.stats()
    assert s["queries"] == 2 and s["fused_dispatches"] == 1
    assert s["ratio"] == 2.0
