"""Per-query execution inspector (search/query_stats.py).

Contract under test:

  - SearchMetrics population on EVERY scan path: single-block, batched
    multi-block, coalesced (8-way concurrency), mesh-sharded — all
    report non-zero inspected counts; skipped_blocks carries time-range
    / duration / dictionary prunes with per-reason stats
  - the conservation invariant: a fused Q-way dispatch apportions its
    stage seconds (and h2d bytes) across member queries so the shares
    sum EXACTLY to the dispatch totals
  - explain opt-in: ?explain=1 / SearchRequest.explain returns the full
    breakdown on the response, populated end-to-end (frontend merge
    included)
  - search_query_stats_enabled: false is a true noop — byte-identical
    results, no record created
  - slow-query log (one rate-limited JSON line), /debug/querystats,
    per-tenant counters
"""

import json
import logging
import threading
import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.observability import metrics as obs
from tempo_tpu.observability.profile import PROFILER
from tempo_tpu.search import SearchResults
from tempo_tpu.search import query_stats
from tempo_tpu.search.batcher import BlockBatcher, QueryCoalescer
from tempo_tpu.search.multiblock import MultiBlockEngine, compile_multi
from tempo_tpu.search.engine import resolve_top_k

from tests.test_coalesce import _blocks, _jobs, _mk_req


@pytest.fixture(autouse=True)
def _fresh_registry():
    query_stats.configure(enabled=True, slow_s=10.0)
    query_stats.REGISTRY.reset()
    yield
    query_stats.configure(enabled=True, slow_s=10.0)
    query_stats.REGISTRY.reset()


def _search_with_stats(batcher, jobs, req, tenant="t1"):
    qs = query_stats.begin(tenant, req)
    with query_stats.activate(qs):
        results = batcher.search(jobs, req)
    d = qs.finish()
    return results, qs, d


# ---------------------------------------------------------------------------
# apportioning / conservation primitives


def test_apportion_conserves_totals_exactly():
    totals = {"execute": 0.123456789, "compile": 3.14159, "h2d": 1e-9}
    for weights in ([1, 1, 1, 1], [5, 1, 3], [7], [1000, 1, 1, 1, 1, 1]):
        shares = query_stats.apportion(totals, weights)
        assert len(shares) == len(weights)
        for stage, total in totals.items():
            assert sum(s[stage] for s in shares) == total  # EXACT

def test_apportion_weights_proportional():
    shares = query_stats.apportion({"execute": 1.0}, [3, 1])
    assert abs(shares[0]["execute"] - 0.75) < 1e-12
    assert abs(shares[1]["execute"] - 0.25) < 1e-12


# ---------------------------------------------------------------------------
# metrics population per path


def test_batched_path_populates_metrics_and_stats():
    blocks = _blocks(3, entries=128)
    batcher = BlockBatcher()
    req = _mk_req({"service.name": "svc-1"}, limit=500)
    results, qs, d = _search_with_stats(batcher, _jobs(blocks), req)
    m = results.metrics
    assert m.inspected_blocks > 0
    assert m.inspected_traces > 0
    assert m.inspected_bytes >= 0  # synthetic headers carry no size
    assert d["blocks_inspected"] == m.inspected_blocks
    assert d["device_seconds"] > 0
    assert d["dispatches"] >= 1
    assert d["stages_ms"]  # host stages recorded
    assert "hbm_miss_cold" in d["cache"] or "hbm_hit" in d["cache"]


def test_single_block_path_populates_metrics():
    from tempo_tpu.backend import MockBackend
    from tempo_tpu.backend.types import BlockMeta
    from tempo_tpu.search.backend_search_block import (
        BackendSearchBlock, write_search_block)
    from tests.test_coalesce import _corpus

    be = MockBackend()
    meta = BlockMeta(tenant_id="t1")
    write_search_block(be, meta, _corpus(64, seed=1), encoding="zlib")
    bsb = BackendSearchBlock(be, meta)
    req = _mk_req({"service.name": "svc-1"}, limit=100)
    qs = query_stats.begin("t1", req)
    with query_stats.activate(qs):
        results = bsb.search(req)
    d = qs.finish()
    m = results.metrics
    assert m.inspected_blocks == 1 and m.inspected_traces > 0
    assert m.inspected_bytes > 0
    assert d["bytes_inspected"]["device"] == m.inspected_bytes
    assert d["device_seconds"] > 0

    # dictionary prune: a tag value no dictionary contains
    qs2 = query_stats.begin("t1", req)
    with query_stats.activate(qs2):
        r2 = bsb.search(_mk_req({"service.name": "nope-xyz"}, limit=10))
    d2 = qs2.finish()
    assert r2.metrics.skipped_blocks == 1
    assert d2["skipped_blocks"] == {"dict": 1}


def test_skip_reasons_time_range_duration_and_dict():
    blocks = _blocks(3, entries=64)
    batcher = BlockBatcher()
    jobs = _jobs(blocks)

    # time window far in the future → header prune, reason time_range
    req = _mk_req({}, limit=10, start=2_000_000_000, end=2_000_000_100)
    results, _qs, d = _search_with_stats(batcher, jobs, req)
    assert results.metrics.skipped_blocks == len(jobs)
    assert d["skipped_blocks"] == {"time_range": len(jobs)}

    # duration beyond every entry → header prune, reason duration
    req = _mk_req({}, limit=10, min_duration_ms=10_000_000)
    results, _qs, d = _search_with_stats(batcher, jobs, req)
    assert results.metrics.skipped_blocks == len(jobs)
    assert d["skipped_blocks"] == {"duration": len(jobs)}

    # unsatisfiable tag → dictionary prune
    req = _mk_req({"service.name": "no-such-service"}, limit=10)
    results, _qs, d = _search_with_stats(batcher, jobs, req)
    assert results.metrics.skipped_blocks == len(jobs)
    assert d["skipped_blocks"] == {"dict": len(jobs)}


def test_mesh_path_populates_metrics():
    from tempo_tpu.parallel.mesh import make_mesh

    blocks = _blocks(2, entries=128)
    batcher = BlockBatcher(mesh=make_mesh())
    req = _mk_req({"service.name": "svc-2"}, limit=500)
    results, _qs, d = _search_with_stats(batcher, _jobs(blocks), req)
    assert results.metrics.inspected_blocks > 0
    assert results.metrics.inspected_traces > 0
    assert d["device_seconds"] > 0
    # mesh dispatches serialize on the collective lock → the stage
    # breakdown must carry the mesh record's stages
    assert d["device_stages_ms"]


def test_dist_engine_attributes_to_active_stats():
    from tempo_tpu.parallel import DistributedScanEngine, make_mesh
    from tempo_tpu.search.pipeline import compile_query
    from tests.test_coalesce import _corpus
    from tempo_tpu.search import ColumnarPages, PageGeometry

    pages = ColumnarPages.build(_corpus(128, seed=3), PageGeometry(32, 8))
    eng = DistributedScanEngine(make_mesh(), top_k=64)
    cq = compile_query(pages.key_dict, pages.val_dict,
                       _mk_req({"service.name": "svc-1"}, limit=20))
    qs = query_stats.begin("t1", None)
    with query_stats.activate(qs):
        count, inspected, _s, _i = eng.scan(pages, cq)
    assert inspected > 0
    assert qs.device_seconds > 0
    assert qs.dispatches >= 1


# ---------------------------------------------------------------------------
# conservation under fused dispatch


def test_conservation_8way_stacked_structural():
    """A fused plan-shape-STACKED structural dispatch (ISSUE 15)
    apportions its stage seconds and h2d bytes across the member
    queries through the same conservation invariant as the legacy
    coalescer — structural table sizes join the weights — and each
    member's ?explain structural tree carries per-node device-seconds
    that conserve to that member's own execute share."""
    import random

    from tempo_tpu.search import ir, structural as structural_mod
    from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
    from tempo_tpu.search.data import SearchData, SpanData
    from tempo_tpu.search.structural import (STRUCTURAL,
                                             compile_structural)

    rng = random.Random(7)
    entries = []
    for i in range(128):
        sd = SearchData(trace_id=i.to_bytes(16, "big"), start_s=1,
                        end_s=5, dur_ms=rng.randint(1, 2000),
                        kvs={"service.name": {f"svc-{i % 6}"}})
        for j in range(rng.randint(1, 6)):
            sd.spans.append(SpanData(
                parent=(-1 if j == 0 else rng.randrange(j)),
                dur_ms=rng.randint(1, 900), kind=rng.randint(0, 5),
                kvs={"service.name": {f"svc-{rng.randint(0, 5)}"}}))
        entries.append(sd)
    prev = STRUCTURAL.enabled
    prev_stack = STRUCTURAL.stack_enabled
    STRUCTURAL.enabled = True
    STRUCTURAL.stack_enabled = True
    try:
        blocks = [ColumnarPages.build(entries, PageGeometry(64, 8))]
        eng = MultiBlockEngine(top_k=64)
        batch = eng.stage(blocks)
        co = QueryCoalescer(eng, window_s=60.0, max_queries=8,
                            active_fn=lambda: 8)
        caught: list[dict] = []
        listener = caught.append
        PROFILER.add_listener(listener)
        try:
            mqs, stats, futs = [], [], []
            for i in range(8):
                expr = ir.parse(
                    '{"child": {"parent": {"tag": {"k": "service.name",'
                    ' "v": "svc-%d"}}, "child": {"dur": {"min_ms": %d}}}}'
                    % (i % 6, 50 * (i + 1)))
                req = tempopb.SearchRequest()
                req.limit = 64
                structural_mod.attach_query(req, expr)
                mq = compile_multi(blocks, req, cache_on=batch)
                mq.structural = compile_structural(expr, blocks,
                                                   cache_on=batch)
                mqs.append(mq)
                stats.append(query_stats.QueryStats(f"t{i % 3}"))

            def submit(i):
                with query_stats.activate(stats[i]):
                    # the serving path registers the compiled plan at
                    # prepare time; mirror it for the explain tree
                    stats[i].add_structural(mqs[i].structural)
                    futs.append(co.submit(
                        batch, mqs[i],
                        resolve_top_k(eng.top_k, mqs[i].limit),
                        peers=8))

            threads = [threading.Thread(target=submit, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for f in futs:
                f.result(timeout=60)
        finally:
            PROFILER._listeners.remove(listener)

        # ONE fused launch served all 8 structural members
        assert co.fused == 1 and co.queries == 8
        assert co.structural_stacked == 8
        fused = [rd for rd in caught if rd.get("mode") == "coalesced"]
        assert len(fused) == 1
        totals = {k: v / 1e3 for k, v in fused[0]["stages_ms"].items()}
        for stage, total in totals.items():
            attributed = sum(qs.device_stages.get(stage, 0.0)
                             for qs in stats)
            assert attributed == pytest.approx(total, rel=1e-9), stage
        assert sum(qs.h2d_bytes for qs in stats) == pytest.approx(
            fused[0].get("h2d_bytes", 0), rel=1e-9)
        # per-member explain: each member's plan tree apportions its
        # OWN execute share over its node weights, conserved
        for qs in stats:
            d = qs.to_dict()
            nodes = d["structural"]["nodes"]
            assert nodes and {n["op"] for n in nodes} >= {"child"}
            exec_s = (qs.device_stages.get("execute")
                      or sum(qs.device_stages.values()))
            assert sum(n["device_ms"] for n in nodes) == pytest.approx(
                exec_s * 1e3, abs=1e-3)
    finally:
        STRUCTURAL.enabled = prev
        STRUCTURAL.stack_enabled = prev_stack


def test_conservation_8way_coalesced():
    """8 concurrent queries fuse into ONE dispatch (max_queries=8, size
    flush); the per-query attributed stage seconds and h2d bytes must
    sum to the fused dispatch record's totals within float tolerance."""
    blocks = _blocks(2, entries=128)
    eng = MultiBlockEngine(top_k=64)
    batch = eng.stage(blocks)
    co = QueryCoalescer(eng, window_s=60.0, max_queries=8,
                        active_fn=lambda: 8)

    caught: list[dict] = []
    listener = caught.append
    PROFILER.add_listener(listener)
    try:
        reqs = [_mk_req({"service.name": f"svc-{i % 6}"},
                        limit=10 + i) for i in range(8)]
        mqs = [compile_multi(blocks, r) for r in reqs]
        stats = [query_stats.QueryStats("t%d" % (i % 3)) for i in range(8)]
        futs = []

        def submit(i):
            with query_stats.activate(stats[i]):
                futs.append(co.submit(
                    batch, mqs[i],
                    resolve_top_k(eng.top_k, mqs[i].limit), peers=8))

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in futs:
            f.result(timeout=60)
    finally:
        PROFILER._listeners.remove(listener)

    assert co.fused == 1 and co.queries == 8
    fused = [rd for rd in caught if rd.get("mode") == "coalesced"]
    assert len(fused) == 1
    rec = fused[0]
    totals = {k: v / 1e3 for k, v in rec["stages_ms"].items()}

    for qs in stats:
        assert qs.fused_dispatches == 1
        assert qs.coalesced_with == 7
        assert qs.device_seconds > 0

    for stage, total in totals.items():
        attributed = sum(qs.device_stages.get(stage, 0.0) for qs in stats)
        assert attributed == pytest.approx(total, rel=1e-9), stage
    total_h2d = rec.get("h2d_bytes", 0)
    assert sum(qs.h2d_bytes for qs in stats) == pytest.approx(
        total_h2d, rel=1e-9)
    # and the whole bill conserves: sum of device_seconds == sum stages
    assert sum(qs.device_seconds for qs in stats) == pytest.approx(
        sum(totals.values()), rel=1e-9)


def test_concurrent_batcher_searches_all_report_stats():
    """Through the real batcher under 8-way concurrency: every query's
    results carry non-zero inspected counts and its own stats record
    (fused or not)."""
    blocks = _blocks(2, entries=128)
    batcher = BlockBatcher(coalesce_window_s=0.05, coalesce_max_queries=8)
    jobs = _jobs(blocks)
    barrier = threading.Barrier(8)
    out: list = [None] * 8

    def run(i):
        req = _mk_req({"service.name": f"svc-{i % 6}"}, limit=20)
        qs = query_stats.begin(f"tenant-{i % 2}", req)
        barrier.wait()
        with query_stats.activate(qs):
            res = batcher.search(jobs, req)
        out[i] = (res, qs.finish())

    threads = [threading.Thread(target=run, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for res, d in out:
        assert res.metrics.inspected_blocks > 0
        assert res.metrics.inspected_traces > 0
        assert d["device_seconds"] > 0
        assert d["dispatches"] >= 1
    snap = query_stats.REGISTRY.snapshot()
    assert snap["tenants"]["tenant-0"]["queries"] == 4
    assert snap["tenants"]["tenant-1"]["queries"] == 4
    assert snap["tenants"]["tenant-0"]["device_seconds"] > 0


# ---------------------------------------------------------------------------
# noop contract


def test_disabled_is_true_noop_and_byte_identical():
    blocks = _blocks(2, entries=128)
    batcher = BlockBatcher()
    jobs = _jobs(blocks)
    req = _mk_req({"service.name": "svc-1"}, limit=50)

    query_stats.configure(enabled=False)
    assert query_stats.begin("t1", req) is None
    r_off = batcher.search(jobs, req).response()
    published_off = query_stats.REGISTRY._published

    query_stats.configure(enabled=True)
    qs = query_stats.begin("t1", req)
    with query_stats.activate(qs):
        r_on = batcher.search(jobs, req).response()
    qs.finish()

    t_off = b"".join(t.SerializeToString() for t in r_off.traces)
    t_on = b"".join(t.SerializeToString() for t in r_on.traces)
    assert t_off == t_on
    # legacy metrics identical; only the stats layer differs
    assert r_off.metrics.inspected_traces == r_on.metrics.inspected_traces
    assert r_off.metrics.device_seconds == 0.0
    assert not r_off.metrics.query_stats_json
    assert query_stats.REGISTRY._published == published_off + 1


# ---------------------------------------------------------------------------
# explain end-to-end (TempoDB + frontend merge + HTTP)


def _seeded_db(tmp_path, n_blocks=2, **cfg):
    from tempo_tpu.backend import LocalBackend
    from tempo_tpu.db import TempoDB, TempoDBConfig
    from tempo_tpu.model import segment_codec_for
    from tempo_tpu.search import extract_search_data
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    db = TempoDB(LocalBackend(str(tmp_path / "blocks")),
                 str(tmp_path / "wal"), TempoDBConfig(**cfg))
    sc = segment_codec_for("v2")
    for b in range(n_blocks):
        blk = db.wal.new_block("acme")
        entries = {}
        for i in range(30):
            tid = random_trace_id()
            tr = make_trace(tid, seed=b * 100 + i)
            sd = extract_search_data(tid, tr)
            blk.append(tid, sc.prepare_for_write(tr, sd.start_s, sd.end_s),
                       sd.start_s, sd.end_s)
            entries[tid] = sd
        db.complete_block(blk, [entries[t] for t in sorted(entries)])
        blk.clear()
    return db


def test_explain_rides_search_response(tmp_path):
    db = _seeded_db(tmp_path)
    req = tempopb.SearchRequest()
    req.limit = 100
    req.explain = True
    resp = db.search("acme", req).response()
    assert resp.metrics.device_seconds > 0
    assert resp.metrics.inspected_bytes_device > 0
    d = json.loads(resp.metrics.query_stats_json)
    assert d["tenant"] == "acme"
    assert d["device_seconds"] > 0
    assert d["blocks_inspected"] == resp.metrics.inspected_blocks
    # without explain the heavy JSON stays off the wire but the
    # accounting fields still ride
    req2 = tempopb.SearchRequest()
    req2.limit = 100
    resp2 = db.search("acme", req2).response()
    assert resp2.metrics.device_seconds > 0
    assert not resp2.metrics.query_stats_json


def test_search_blocks_protocol_carries_stats(tmp_path):
    db = _seeded_db(tmp_path)
    meta = db.blocklist.metas("acme")[0]
    breq = tempopb.SearchBlocksRequest()
    breq.tenant_id = "acme"
    breq.search_req.limit = 50
    breq.search_req.explain = True
    j = breq.jobs.add()
    j.block_id = meta.block_id
    j.encoding = db.cfg.search_encoding
    j.version = meta.version
    j.data_encoding = meta.data_encoding
    resp = db.search_blocks(breq).response()
    assert resp.metrics.device_seconds > 0
    d = json.loads(resp.metrics.query_stats_json)
    assert d["scope"] == "exec" and d["tenant"] == "acme"


def test_frontend_merges_subquery_stats(tmp_path):
    """The frontend's request-scope record merges sub-responses'
    breakdowns; explain returns ONE merged breakdown."""
    from tempo_tpu.modules.app import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    tid_seed = 0
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    for i in range(10):
        app.push("t1", list(make_trace(random_trace_id(),
                                       seed=tid_seed + i).batches))
    app.flush_tick(force=True)
    app.poll_tick()
    req = tempopb.SearchRequest()
    req.limit = 50
    req.explain = True
    resp = app.search("t1", req)
    assert resp.metrics.inspected_traces > 0
    d = json.loads(resp.metrics.query_stats_json)
    assert d["scope"] == "request"
    assert d.get("subqueries", 0) >= 1
    assert d["device_seconds"] >= 0
    # the merged breakdown never contradicts the metrics beside it:
    # sub-responses WITHOUT a breakdown (the live ingester leg) are
    # absorbed as a remainder
    assert d["blocks_inspected"] == resp.metrics.inspected_blocks
    assert (d["bytes_inspected"]["host"] + d["bytes_inspected"]["device"]
            ) == resp.metrics.inspected_bytes
    # ring saw both scopes (request + exec) in-process
    scopes = {e["scope"] for e in query_stats.REGISTRY.snapshot()["recent"]}
    assert {"exec", "request"} <= scopes


def test_http_explain_param_and_debug_endpoint(tmp_path):
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.modules.app import App, AppConfig
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    for i in range(5):
        app.push("t1", list(make_trace(random_trace_id(), seed=i).batches))
    app.flush_tick(force=True)
    app.poll_tick()
    api = HTTPApi(app)
    hdr = {"X-Scope-OrgID": "t1"}
    code, body = api.handle("GET", "/api/search",
                            {"limit": "10", "explain": "1"}, hdr)
    assert code == 200
    assert "queryStats" in body, body
    assert body["queryStats"]["scope"] == "request"
    assert "queryStatsJson" not in body.get("metrics", {})

    # header opt-in too
    code, body = api.handle(
        "GET", "/api/search", {"limit": "10"},
        {"X-Scope-OrgID": "t1", "X-Tempo-Explain": "1"})
    assert code == 200 and "queryStats" in body

    # "X-Tempo-Explain: 0" is an explicit NO, not a truthy string
    code, body = api.handle(
        "GET", "/api/search", {"limit": "10"},
        {"X-Scope-OrgID": "t1", "X-Tempo-Explain": "0"})
    assert code == 200 and "queryStats" not in body

    # without the opt-in: no breakdown
    code, body = api.handle("GET", "/api/search", {"limit": "10"}, hdr)
    assert code == 200 and "queryStats" not in body

    code, body = api.handle("GET", "/debug/querystats", {}, hdr)
    assert code == 200
    assert body["enabled"] is True
    assert body["recent"], "ring must carry the queries above"
    assert body["tenants"]
    assert "top_by_device_seconds" in body

    # /status gained the device block
    code, body = api.handle("GET", "/status", {}, hdr)
    assert code == 200
    assert "device" in body
    assert "backend" in body["device"]
    assert "last_dispatch_age_s" in body["device"]


def test_debug_querystats_respects_debug_gate(tmp_path):
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.modules.app import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    api = HTTPApi(app, debug_endpoints=False)
    code, _ = api.handle("GET", "/debug/querystats", {}, {})
    assert code == 404


# ---------------------------------------------------------------------------
# slow-query log + counters


def test_slow_query_log_emits_one_json_line(caplog):
    query_stats.configure(slow_s=0.0001)
    qs = query_stats.QueryStats("noisy-tenant")
    qs.add_device_stages({"execute": 0.5})
    time.sleep(0.002)
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.slowquery"):
        qs.finish()
    lines = [r.getMessage() for r in caplog.records
             if r.name == "tempo_tpu.slowquery"]
    assert len(lines) == 1
    doc = json.loads(lines[0])
    assert doc["msg"] == "slow query"
    assert doc["tenant"] == "noisy-tenant"
    assert doc["device_seconds"] == 0.5
    assert obs.slow_queries.value(tenant="noisy-tenant") >= 1


def test_slow_query_log_rate_limited(caplog):
    query_stats.configure(slow_s=0.0001)
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.slowquery"):
        for _ in range(50):
            qs = query_stats.QueryStats("flood")
            time.sleep(0.0002)
            qs.finish()
    lines = [r for r in caplog.records if r.name == "tempo_tpu.slowquery"]
    assert len(lines) <= 6  # burst 5 + at most one refill
    # every slow query still COUNTS even when its line was dropped
    assert obs.slow_queries.value(tenant="flood") >= 50


def test_per_tenant_counters_accumulate():
    before_dev = obs.query_device_seconds.value(tenant="bill-me")
    before_b = obs.query_bytes_inspected.value(tenant="bill-me",
                                               placement="device")
    qs = query_stats.QueryStats("bill-me")
    qs.add_device_stages({"execute": 0.25, "h2d": 0.05})
    qs.add_inspected(blocks=2, nbytes=1 << 20, placement="device")
    qs.add_inspected(nbytes=1 << 10, placement="host")
    qs.finish()
    assert obs.query_device_seconds.value(tenant="bill-me") \
        == pytest.approx(before_dev + 0.30)
    assert obs.query_bytes_inspected.value(
        tenant="bill-me", placement="device") == before_b + (1 << 20)
    assert obs.query_bytes_inspected.value(
        tenant="bill-me", placement="host") >= 1 << 10


def test_request_scope_does_not_book_tenant_counters():
    before = obs.query_device_seconds.value(tenant="front-only")
    qs = query_stats.QueryStats("front-only", scope="request")
    qs.add_device_stages({"execute": 1.0})
    qs.finish()
    assert obs.query_device_seconds.value(tenant="front-only") == before
    # but it IS in the ring
    assert any(e["tenant"] == "front-only"
               for e in query_stats.REGISTRY.snapshot()["recent"])


def test_nested_attribution_bills_once():
    """A body that itself runs an attributing engine must not be
    double-billed: the inner context attributes, the outer skips its
    wall fallback (DistributedScanEngine self-attributes inside
    BackendSearchBlock's attributed scan)."""
    qs = query_stats.QueryStats("t1")
    with query_stats.attributed_dispatch(qs):
        with query_stats.attributed_dispatch(qs):
            time.sleep(0.005)
    assert qs.dispatches == 1
    # sequential sibling contexts still each bill
    with query_stats.attributed_dispatch(qs):
        time.sleep(0.001)
    assert qs.dispatches == 2


def test_slow_counter_books_once_per_query_per_process(caplog):
    """Counter and log share one dedupe rule: fronted exec records
    (in-process sub-requests of a request-scope record) book nothing —
    a 4-shard slow query must count 1, not 4 (its fan-out factor)."""
    query_stats.configure(slow_s=0.0001)
    before = obs.slow_queries.value(tenant="scoped")
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.slowquery"):
        with query_stats.fronted():
            for _ in range(4):  # the request's shard fan-out
                qs = query_stats.QueryStats("scoped")
                time.sleep(0.001)
                qs.finish()
        qreq = query_stats.QueryStats("scoped", scope="request")
        time.sleep(0.001)
        qreq.finish()
    assert obs.slow_queries.value(tenant="scoped") == before + 1
    lines = [r for r in caplog.records if r.name == "tempo_tpu.slowquery"]
    assert len(lines) == 1
    # a standalone querier (exec, unfronted) books its own view
    qs2 = query_stats.QueryStats("scoped")
    time.sleep(0.001)
    qs2.finish()
    assert obs.slow_queries.value(tenant="scoped") == before + 2


def test_slow_log_limiter_is_per_tenant():
    """Tenant A's flood must not starve tenant B's line — B's slow
    query is exactly the diagnostic the log exists for."""
    query_stats.configure(slow_s=0.0001)
    lim = query_stats.REGISTRY._limiter
    for _ in range(50):
        assert lim.allow("flood-a") or True  # drain A's bucket
    assert not lim.allow("flood-a")
    assert lim.allow("quiet-b"), "B starved by A's flood"


def test_fronted_exec_suppresses_slow_log_line(caplog):
    """In-process frontend sub-requests (the fronted() mark) must not
    emit their own slow-log line — the request-scope line covers the
    query; ONE line per slow query per process. Counters still book."""
    query_stats.configure(slow_s=0.0001)
    before = obs.slow_queries.value(tenant="one-line")
    with caplog.at_level(logging.WARNING, logger="tempo_tpu.slowquery"):
        with query_stats.fronted():
            qs = query_stats.QueryStats("one-line")  # exec, fronted
            time.sleep(0.001)
            qs.finish()
        qs2 = query_stats.QueryStats("one-line", scope="request")
        time.sleep(0.001)
        qs2.finish()
    lines = [r for r in caplog.records if r.name == "tempo_tpu.slowquery"]
    assert len(lines) == 1
    assert json.loads(lines[0].getMessage())["scope"] == "request"
    # the counter still booked the (fronted) exec record
    assert obs.slow_queries.value(tenant="one-line") == before + 1


def test_explain_param_roundtrip():
    from tempo_tpu.api.params import build_search_request, \
        parse_search_request

    req = _mk_req({"a": "b"}, limit=5)
    req.explain = True
    qs = build_search_request(req)
    import urllib.parse

    parsed = parse_search_request(
        {k: v[0] for k, v in urllib.parse.parse_qs(qs).items()})
    assert parsed.explain is True
