"""Multi-host mesh path (VERDICT r2 #2): jax.distributed wiring, per-host
staging, and the multi-process localhost dryrun."""

import os

import pytest


def test_v5e64_config_expressible():
    """BASELINE config 5's topology loads through the production config
    parser with env substitution for the per-host process id."""
    os.environ["TEMPO_PROCESS_ID"] = "7"
    try:
        from tempo_tpu.cli.config import load_config

        with open(os.path.join(os.path.dirname(__file__), "..",
                               "operations", "multihost-v5e-64.yaml")) as f:
            cfg, runtime = load_config(text=f.read())
        dist = runtime["distributed"]
        assert dist["coordinator"] == "tempo-host-0.cluster.local:8476"
        assert int(dist["num_processes"]) == 16
        assert int(dist["process_id"]) == 7  # from ${TEMPO_PROCESS_ID}
        assert cfg.backend["backend"] == "s3"
    finally:
        del os.environ["TEMPO_PROCESS_ID"]


def test_init_distributed_noop_without_coordinator():
    from tempo_tpu.parallel.multihost import init_distributed

    assert init_distributed() is False  # single-host: nothing to join


def test_multiprocess_dryrun_matches_single_process():
    """2 OS processes x 2 CPU devices join one distributed runtime and
    drive the production TempoDB.search over a 4-device global mesh with
    per-host shard staging; results must be identical on every process
    and equal to the host oracle (VERDICT r2 #2 'done when')."""
    from tempo_tpu.parallel.multihost_dryrun import run

    out = run(n_processes=2, devices_per_proc=2)
    assert out["matches"] == out["expected"] > 0
    assert out["global_devices"] == 4
