"""Device-side aggregate analytics (ISSUE 19): the differential
contract. Ingest — random native summary corpora through the batched
device reduction must leave the generator registries BYTE-identical to
the per-span Python walk (exposition bytes, LRU recency order, pairing
store), packed composite keys on and off, breaker-forced host routes
included. Query — ``?agg=red`` answers byte-identically through every
engine path (batched / coalesced / mesh / both host routes) and equals
a plain-python reference aggregator; the default-off gate is a true
noop (WAL and /metrics byte-identity, 400 on ?agg=)."""

from __future__ import annotations

import bisect
import json
import random
import struct
import threading

import numpy as np
import pytest

from tempo_tpu import robustness, tempopb
from tempo_tpu.backend.local import LocalBackend
from tempo_tpu.db import TempoDB, TempoDBConfig
from tempo_tpu.modules.generator import (
    LATENCY_BUCKETS_S,
    MetricsGenerator,
    ServiceGraphProcessor,
    SpanMetricsProcessor,
)
from tempo_tpu.observability import metrics as obs
from tempo_tpu.search.analytics import (
    AGG_QUERY_TAG,
    ANALYTICS,
    MS_BUCKETS,
    _dur_thresholds,
    _dur_thresholds_full,
    agg_requested,
    agg_response,
    attach_agg,
    merge_agg,
)
from tempo_tpu.search.batcher import host_scan
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import SearchData, encode_search_data
from tempo_tpu.search.engine import fetch_coalesced_out
from tempo_tpu.search.multiblock import (
    MultiBlockEngine,
    compile_multi,
    stack_queries,
)

E_GEO = PageGeometry(entries_per_page=64, kv_per_entry=8)

_SVCS = ["api", "db", "auth", "cache", "web", "api"]  # dup: canon remap
_OPS = ["op0", "op1", "op2"]


@pytest.fixture(autouse=True)
def _analytics_gate():
    """Leave the process-wide gate and breaker as the test found them."""
    prev_enabled, prev_min = ANALYTICS.enabled, ANALYTICS.min_rows
    prev_brk = robustness.BREAKER.enabled
    prev_thr = robustness.BREAKER.threshold
    yield
    ANALYTICS.configure(enabled=prev_enabled, min_rows=prev_min)
    robustness.BREAKER.enabled = prev_brk
    robustness.BREAKER.threshold = prev_thr
    robustness.BREAKER.reset()


# ---------------------------------------------------------------------------
# native summary blob construction (the MetricsGenerator._ROW ABI)

_ROW = struct.Struct("<6IQQ8s8s")


def _blob(strs: list[str], rows: list[tuple]) -> bytes:
    out = [struct.pack("<I", len(strs))]
    for s in strs:
        b = s.encode()
        out.append(struct.pack("<H", len(b)))
        out.append(b)
    out.append(struct.pack("<I", len(rows)))
    for r in rows:
        out.append(_ROW.pack(*r))
    return b"".join(out)


def _rand_push(rng: random.Random, n_traces: int = 24,
               big_enums: bool = False):
    """One push: a string table (with deliberate duplicates), trace ids
    (with deliberate duplicate bytes), and summary rows mixing paired
    client/server edges, half pairs, and plain spans. ``big_enums``
    drives kind/status into ranges that overflow the packed int64
    composite key, forcing the 2-D unique fallback."""
    strs = _SVCS + _OPS + [rng.choice(_SVCS)]
    tids = [rng.getrandbits(64).to_bytes(8, "big").rjust(16, b"\x00")
            for _ in range(n_traces)]
    if n_traces >= 2 and rng.random() < 0.5:
        tids[1] = tids[0]          # duplicate trace-id bytes
    rows = []
    sid_n = 1
    # bucket-edge-exact durations: T and T-1 for random thresholds
    edge_durs = [t + d for t in _dur_thresholds_full(LATENCY_BUCKETS_S)
                 for d in (-1, 0)]
    for ti in range(n_traces):
        for _ in range(rng.randint(1, 5)):
            kind = rng.randint(0, 5)
            status = rng.randint(0, 2)
            if big_enums:
                kind = rng.choice([rng.randint(0, 5),
                                   rng.randint(1 << 30, (1 << 32) - 1)])
                status = rng.randint(1 << 30, (1 << 32) - 1)
            start = rng.randrange(1 << 40)
            dur = (rng.choice(edge_durs) if rng.random() < 0.3
                   else rng.randrange(20_000_000_000))
            sid = sid_n.to_bytes(8, "little")
            sid_n += 1
            if kind in (2, 3) and rng.random() < 0.7:
                # paired edge: client sid == server pid, same trace
                pid = sid_n.to_bytes(8, "little")
                sid_n += 1
                a = (ti, rng.randrange(len(_SVCS)), len(_SVCS)
                     + rng.randrange(len(_OPS)), 3, status, 0,
                     start, start + dur, sid, b"\x00" * 8)
                b = (ti, rng.randrange(len(_SVCS)), len(_SVCS)
                     + rng.randrange(len(_OPS)), 2, rng.randint(0, 2),
                     0, start, start + rng.randrange(dur + 1), pid, sid)
                pair = [a, b]
                rng.shuffle(pair)
                rows.extend(pair)
            else:
                rows.append((ti, rng.randrange(len(strs)),
                             len(_SVCS) + rng.randrange(len(_OPS)),
                             kind, status, 0, start, start + dur, sid,
                             rng.getrandbits(64).to_bytes(8, "little")))
    rng.shuffle(rows)
    return strs, rows, tids


def _feed(pushes, enabled: bool, min_rows: int = 1) -> MetricsGenerator:
    ANALYTICS.configure(enabled=enabled, min_rows=min_rows)
    gen = MetricsGenerator()
    for strs, rows, tids in pushes:
        gen.push_summary_blob("t", _blob(strs, rows), tids)
    return gen


def _snap(gen: MetricsGenerator):
    """(exposition bytes, spanmetrics LRU order, pairing-store state) —
    store timestamps dropped: wall-clock, legitimately different."""
    _reg, procs = gen._instance("t")
    spm = next(p for p in procs if isinstance(p, SpanMetricsProcessor))
    sgp = next(p for p in procs if isinstance(p, ServiceGraphProcessor))
    store = {k: v[:3] for k, v in sgp._store.items()}
    return gen.collect("t"), list(spm._series.keys()), store


# ---------------------------------------------------------------------------
# ingest parity


def test_two_limb_thresholds_are_exact():
    """T = min{n : n/1e9 > edge}: n >= T iff n/1e9 > edge, and the limb
    split round-trips."""
    full = _dur_thresholds_full(LATENCY_BUCKETS_S)
    limbs = _dur_thresholds(LATENCY_BUCKETS_S)
    for edge, T, (hi, lo) in zip(LATENCY_BUCKETS_S, full, limbs):
        assert (hi << 31) | lo == T
        assert T / 1e9 > edge
        assert (T - 1) / 1e9 <= edge
        # the device bin (count of thresholds <=) equals the walk's
        # bisect over the float edges at the exact boundary
        for dur in (T - 1, T, T + 1):
            dev_bin = sum(dur >= t for t in full)
            assert dev_bin == bisect.bisect_left(
                LATENCY_BUCKETS_S, dur / 1e9)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_ingest_differential_parity(seed):
    """The core contract: walk-fed and device-fed registries are
    byte-identical — exposition, LRU recency order, pairing store."""
    rng = random.Random(1000 + seed)
    pushes = [_rand_push(rng) for _ in range(4)]
    walk = _snap(_feed(pushes, enabled=False))
    dev = _snap(_feed(pushes, enabled=True))
    assert dev[0] == walk[0]
    assert dev[1] == walk[1]
    assert dev[2] == walk[2]


def test_ingest_parity_packed_key_overflow():
    """kind/status near 2^32 overflow the packed int64 composite key —
    the 2-D unique fallback must stay byte-identical too."""
    rng = random.Random(77)
    pushes = [_rand_push(rng, big_enums=True) for _ in range(3)]
    walk = _snap(_feed(pushes, enabled=False))
    dev = _snap(_feed(pushes, enabled=True))
    assert dev == walk


def test_ingest_parity_on_breaker_host_route():
    """Breaker open: the numpy bincount fallback answers, still
    byte-identical, and books route=host."""
    rng = random.Random(88)
    pushes = [_rand_push(rng) for _ in range(2)]
    walk = _snap(_feed(pushes, enabled=False))
    robustness.BREAKER.reset()
    robustness.BREAKER.enabled = True
    robustness.BREAKER.threshold = 1
    robustness.BREAKER.record_fault("timeout", mode="batched")
    assert robustness.BREAKER.state == "open"
    host0 = obs.search_analytics_dispatches.value(route="host")
    dev = _snap(_feed(pushes, enabled=True))
    assert dev == walk
    assert obs.search_analytics_dispatches.value(route="host") > host0
    robustness.BREAKER.reset()


def test_gate_off_and_small_blob_fall_back_to_walk():
    rng = random.Random(5)
    strs, rows, tids = _rand_push(rng, n_traces=3)
    blob = _blob(strs, rows)
    gen = MetricsGenerator()
    _reg, procs = gen._instance("t")
    # gate off: one attribute read, no consumption, no dispatch booked
    ANALYTICS.configure(enabled=False)
    d0 = (obs.search_analytics_dispatches.value(route="device")
          + obs.search_analytics_dispatches.value(route="host"))
    off = len(blob) - len(rows) * _ROW.size - 4
    assert ANALYTICS.consume_blob(procs, strs, blob, off + 4,
                                  len(rows), tids) is False
    # min_rows: tiny blobs stay on the walk
    ANALYTICS.configure(enabled=True, min_rows=len(rows) + 1)
    assert ANALYTICS.consume_blob(procs, strs, blob, off + 4,
                                  len(rows), tids) is False
    # unknown processor type: hands back to the walk
    ANALYTICS.configure(enabled=True, min_rows=1)
    assert ANALYTICS.consume_blob(procs + [object()], strs, blob,
                                  off + 4, len(rows), tids) is False
    assert (obs.search_analytics_dispatches.value(route="device")
            + obs.search_analytics_dispatches.value(route="host")) == d0
    assert gen.collect("t") == _feed([], enabled=False).collect("t")


def test_gate_off_wal_bytes_identical(tmp_path):
    """The gate is a true noop on the write path: identical pushes with
    the gate on and off leave byte-identical WAL files."""
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.utils.test_data import make_trace

    wals = {}
    for on in (False, True):
        ANALYTICS.configure(enabled=on)
        wal = tmp_path / f"wal_{on}"
        app = App(AppConfig(
            wal_dir=str(wal),
            db=TempoDBConfig(auto_mesh=False,
                             search_analytics_enabled=on)))
        for i in range(6):
            tid = bytes([i + 1]) * 16
            app.push("t1", list(make_trace(tid, seed=i).batches))
        # block dirs carry random UUIDs — normalize the name, keep the
        # (tenant, version, codec) suffix and the bytes
        ents = []
        for p in (q for q in wal.rglob("*") if q.is_file()):
            name = "+".join(p.name.split("+")[1:]) or p.name
            ents.append((str(p.parent.relative_to(wal)), name,
                         p.read_bytes()))
        wals[on] = sorted(ents)
    assert wals[True] == wals[False]


# ---------------------------------------------------------------------------
# satellite behaviors: LRU eviction, bounded expiry sweeps


def test_spanmetrics_series_cache_is_lru_not_fifo():
    from tempo_tpu.observability.metrics import Registry

    spm = SpanMetricsProcessor(Registry())
    k = [("s%d" % i, "op", 0, 0) for i in range(65_537)]
    for key in k[:-1]:            # fill exactly to the cap
        spm._series_touch(key)
    assert len(spm._series) == 65_536
    spm._series_touch(k[0])       # re-touch the oldest-CREATED series
    spm._series_touch(k[-1])      # one past the cap → one eviction
    assert len(spm._series) == 65_536
    # FIFO (insertion order) would evict k[0]; LRU evicts the coldest
    assert k[0] in spm._series
    assert k[1] not in spm._series
    assert list(spm._series)[-2:] == [k[0], k[-1]]


def test_servicegraph_expiry_is_bounded_and_counted():
    from tempo_tpu.observability.metrics import Registry

    sgp = ServiceGraphProcessor(Registry(), wait_s=0.0)
    sgp.max_expire_per_sweep = 4
    now = 100.0
    for i in range(10):
        sgp._pair((b"t", i.to_bytes(8, "little")), "client", "api",
                  (0, 0, 1), now)
    assert len(sgp._store) == 10
    sgp._expire(now + 1.0)        # bounded: at most 4 per sweep
    assert len(sgp._store) == 6
    assert sgp.expired == 4
    assert sgp.expired_total.value() == 4
    sgp._expire(now + 1.0)
    assert len(sgp._store) == 2
    assert sgp.expired_total.value() == 8
    sgp._expire(now + 1.0)
    assert len(sgp._store) == 0
    assert sgp.expired_total.value() == 10


def test_pairing_capacity_sweeps_inline_before_dropping():
    """At max_items the insert sweeps expired squatters inline instead
    of dropping the edge."""
    from tempo_tpu.observability.metrics import Registry

    sgp = ServiceGraphProcessor(Registry(), wait_s=1.0, max_items=4)
    for i in range(4):
        sgp._pair((b"t", i.to_bytes(8, "little")), "client", "api",
                  (0, 0, 1), 0.0)
    # all four are expired at t=10; the fifth insert must land
    sgp._pair((b"t", b"\xff" * 8), "client", "api", (0, 0, 1), 10.0)
    assert (b"t", b"\xff" * 8) in sgp._store
    assert sgp.expired_total.value() == 4


# ---------------------------------------------------------------------------
# query-side ?agg=


def _corpus(seed: int, n: int = 150):
    rng = random.Random(seed)
    entries = []
    for i in range(n):
        sd = SearchData(trace_id=i.to_bytes(2, "big").rjust(16, b"\x00"))
        sd.start_s = 1_600_000_000 + i
        sd.end_s = sd.start_s + rng.randint(0, 10)
        # durations hit the integer-ms edges exactly
        sd.dur_ms = rng.choice([rng.randint(1, 20_000)]
                               + [e + d for e in MS_BUCKETS
                                  for d in (0, 1)])
        sd.root_service = rng.choice(_SVCS)
        sd.kvs = {"service.name": {sd.root_service},
                  "env": {"prod" if i % 2 else "dev"}}
        if rng.random() < 0.3:
            sd.kvs["error"] = {"true"}
        entries.append(sd)
    return entries


def _ref_series(entries, pred) -> dict:
    """The plain-python reference aggregator ?agg=red must equal."""
    series = {}
    for sd in entries:
        if not pred(sd):
            continue
        s = series.setdefault(sd.root_service or "", {
            "calls": 0, "errors": 0,
            "hist": [0] * (len(MS_BUCKETS) + 1)})
        s["calls"] += 1
        s["errors"] += int("true" in sd.kvs.get("error", ()))
        s["hist"][bisect.bisect_left(MS_BUCKETS, sd.dur_ms)] += 1
    return series


def _mk_req(tags: dict, limit: int = 4096) -> tempopb.SearchRequest:
    req = tempopb.SearchRequest()
    req.limit = limit
    for k, v in tags.items():
        req.tags[k] = v
    attach_agg(req, "red")
    return req


def _pred(tags):
    def p(sd):
        return all(any(v in x for x in sd.kvs.get(k, ()))
                   for k, v in tags.items())
    return p


def test_agg_grammar_and_merge():
    req = tempopb.SearchRequest()
    attach_agg(req, " RED ")
    assert req.tags[AGG_QUERY_TAG] == "red" and agg_requested(req)
    with pytest.raises(ValueError):
        attach_agg(req, "p99")
    a = agg_response({"api": {"calls": 2, "errors": 1,
                              "hist": [1, 1] + [0] * 13}})
    b = agg_response({"api": {"calls": 3, "errors": 0,
                              "hist": [0, 3] + [0] * 13},
                      "db": {"calls": 1, "errors": 0,
                             "hist": [1] + [0] * 14}})
    m = merge_agg(a, b)
    assert m["series"]["api"] == {"calls": 5, "errors": 1,
                                  "hist": [1, 4] + [0] * 13}
    assert m["series"]["db"]["calls"] == 1
    assert merge_agg(None, a) is a and merge_agg(a, None) is a


@pytest.mark.parametrize("tags", [{"env": "prod"}, {"env": "dev"},
                                  {"service.name": "a"}])
def test_agg_engine_paths_byte_identical(tags):
    """Batched device, host route, mesh, and coalesced dispatches all
    decode to the reference aggregate — integer counts, identical by
    construction."""
    ANALYTICS.configure(enabled=True)
    entries = _corpus(31)
    half = len(entries) // 2
    blocks = [ColumnarPages.build(entries[:half], E_GEO),
              ColumnarPages.build(entries[half:], E_GEO)]
    want = _ref_series(entries, _pred(tags))
    req = _mk_req(tags)

    eng = MultiBlockEngine(top_k=512)
    host = eng.stage_host(blocks)
    batch = eng.place(host)
    mq = compile_multi(blocks, req, cache_on=batch)
    assert mq is not None
    mq.agg_stage = ANALYTICS.stage_for_batch(batch)
    count, _ins, _s, _i, *ext = eng.scan(batch, mq)
    assert ext, "batched dispatch dropped the agg output"
    got_dev = mq.agg_stage.decode(ext[0])
    assert got_dev == want
    assert sum(s["calls"] for s in got_dev.values()) == count

    # breaker-style host route
    mq_h = compile_multi(blocks, req, cache_on=batch, host_only=True)
    mq_h.agg_stage = ANALYTICS.stage_for_batch(host)
    _c, _i2, _s2, _x2, *ext_h = host_scan(host, mq_h, 512)
    assert ext_h and mq_h.agg_stage.decode(ext_h[0]) == want

    # coalesced: three members, same batch-global stage
    mqs = []
    for other in ({"env": "prod"}, tags, {"env": "dev"}):
        m = compile_multi(blocks, _mk_req(other), cache_on=batch)
        m.agg_stage = mq.agg_stage
        mqs.append(m)
    cq = stack_queries(mqs)
    assert cq.agg_stage is mq.agg_stage
    _cs, _i3, _s3, _x3, *ext_c = fetch_coalesced_out(
        eng.coalesced_scan_async(batch, cq, 512))
    assert ext_c
    for qi, other in enumerate(({"env": "prod"}, tags, {"env": "dev"})):
        assert mq.agg_stage.decode(ext_c[0][qi]) == \
            _ref_series(entries, _pred(other)), other

    # mesh (8 virtual CPU devices, conftest)
    from tempo_tpu.parallel import make_mesh

    eng_m = MultiBlockEngine(top_k=512, mesh=make_mesh())
    host_m = eng_m.stage_host(blocks)
    batch_m = eng_m.place(host_m)
    mq_m = compile_multi(blocks, req, cache_on=batch_m)
    mq_m.agg_stage = ANALYTICS.stage_for_batch(batch_m)
    _cm, _im, _sm, _xm, *ext_m = eng_m.scan(batch_m, mq_m)
    assert ext_m and mq_m.agg_stage.decode(ext_m[0]) == want


def _mkdb(tmp_path, entries, **cfg_kw) -> TempoDB:
    cfg_kw.setdefault("auto_mesh", False)
    cfg_kw.setdefault("search_analytics_enabled", True)
    be = LocalBackend(str(tmp_path / "blocks"))
    db = TempoDB(be, str(tmp_path / "wal"), TempoDBConfig(**cfg_kw))
    half = len(entries) // 2
    for chunk in (entries[:half], entries[half:]):
        db.write_block_direct(
            "t", [(sd.trace_id, encode_search_data(sd), sd.start_s,
                   sd.end_s) for sd in chunk],
            search_entries=chunk)
    return db


def test_agg_serving_path_and_host_route(tmp_path):
    entries = _corpus(41, n=120)
    db = _mkdb(tmp_path, entries)
    req = _mk_req({"env": "prod"}, limit=1000)
    want = agg_response(_ref_series(entries, _pred({"env": "prod"})))
    resp = db.search("t", req).response()
    got = json.loads(resp.metrics.agg_json)
    assert got == want
    # limit=1 truncates the result LIST but never the aggregate:
    # ?agg= disables the early-quit
    resp_lim = db.search("t", _mk_req({"env": "prod"},
                                      limit=1)).response()
    assert len(resp_lim.traces) == 1
    assert resp_lim.metrics.agg_json == resp.metrics.agg_json
    # breaker open: the host route serves the byte-identical aggregate
    robustness.BREAKER.reset()
    robustness.BREAKER.threshold = 1
    robustness.BREAKER.record_fault("timeout", mode="batched")
    assert robustness.BREAKER.state == "open"
    resp_h = db.search("t", _mk_req({"env": "prod"},
                                    limit=1000)).response()
    assert resp_h.metrics.agg_json == resp.metrics.agg_json
    robustness.BREAKER.reset()
    # a non-agg request through the same db carries no aggregate
    plain = tempopb.SearchRequest()
    plain.limit = 1000
    plain.tags["env"] = "prod"
    assert db.search("t", plain).response().metrics.agg_json == ""


def test_agg_concurrent_queries_match_serial(tmp_path):
    """Concurrent agg + non-agg queries through the coalescer: agg
    members group apart, every answer byte-identical to serial."""
    entries = _corpus(43, n=100)
    db = _mkdb(tmp_path, entries, search_coalesce_window_s=0.05)
    reqs = [_mk_req({"env": "prod"}, limit=1000),
            _mk_req({"env": "dev"}, limit=1000),
            _mk_req({"service.name": "a"}, limit=1000),
            _mk_req({"env": "prod"}, limit=1000)]
    plain = tempopb.SearchRequest()
    plain.limit = 1000
    plain.tags["env"] = "prod"
    reqs.append(plain)

    def canon(resp):
        resp.metrics.device_seconds = 0
        return resp.SerializeToString()

    serial = [canon(db.search("t", tempopb.SearchRequest.FromString(
        r.SerializeToString())).response()) for r in reqs]
    out = [None] * len(reqs)
    barrier = threading.Barrier(len(reqs))

    def one(i):
        r = tempopb.SearchRequest.FromString(reqs[i].SerializeToString())
        barrier.wait()
        out[i] = canon(db.search("t", r).response())

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert out == serial


def test_http_agg_param_and_gate_400(tmp_path):
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.utils.test_data import make_trace

    app = App(AppConfig(
        wal_dir=str(tmp_path / "wal"),
        db=TempoDBConfig(auto_mesh=False,
                         search_analytics_enabled=True)))
    api = HTTPApi(app)
    hdr = {"X-Scope-OrgID": "t1"}
    for i in range(4):
        tid = bytes([i + 1]) * 16
        app.push("t1", list(make_trace(tid, seed=i).batches))
    api.handle("GET", "/flush", {}, hdr)
    app.reader_db.poll()
    code, body = api.handle("GET", "/api/search",
                            {"agg": "red", "limit": "10"}, hdr)
    assert code == 200, body
    agg = body.get("aggregates")
    assert agg and agg["type"] == "red"
    assert agg["buckets_ms"] == list(MS_BUCKETS)
    assert sum(s["calls"] for s in agg["series"].values()) == \
        len(body.get("traces", []))
    # the raw tag never leaks into the response metrics block
    assert "aggJson" not in body.get("metrics", {})
    # bad grammar: 400, not 500
    code, body = api.handle("GET", "/api/search",
                            {"agg": "p99", "limit": "10"}, hdr)
    assert code == 400 and "agg" in body["error"]
    # gate off: ?agg= is a 400, plain search still serves
    ANALYTICS.configure(enabled=False)
    code, body = api.handle("GET", "/api/search",
                            {"agg": "red", "limit": "10"}, hdr)
    assert code == 400 and "disabled" in body["error"]
    code, _body = api.handle("GET", "/api/search", {"limit": "10"}, hdr)
    assert code == 200
