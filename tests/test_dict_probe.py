"""Device-resident dictionary probe (PR4 tentpole).

The substring prefilter moves from the host (numpy char.find / native
memmem) onto the device as a rolling-window kernel over the packed
dictionary bytes (tempo_tpu/search/dict_probe.py). These tests pin the
contract from ISSUE 4's acceptance criteria:

  - differential parity: device probe ≡ host substring_value_ids ≡
    native substr_scan over random unicode dictionaries and needles
    (empty needle, multi-byte chars, needles spanning value boundaries);
  - match results byte-identical to the host path through every
    dispatch shape: single-block, multi-block (mixed device/host
    blocks), coalesced multi-query, and mesh-sharded;
  - HBM accounting covers the staged dictionary arrays, and an
    HBM-evicted batch re-uploads its dictionaries on re-stage without
    re-packing the host side.
"""

import random
import threading

import numpy as np
import pytest

from tempo_tpu import tempopb
from tempo_tpu.search import dict_probe, pipeline
from tempo_tpu.search.columnar import ColumnarPages, PageGeometry
from tempo_tpu.search.data import SearchData
from tempo_tpu.search.engine import ScanEngine, stage
from tempo_tpu.search.pipeline import compile_query, substring_value_ids
from tempo_tpu.search.multiblock import (
    MultiBlockEngine,
    compile_multi,
    stack_blocks,
    stack_queries,
)


@pytest.fixture(autouse=True)
def _fresh_compile_cache():
    """The global compile cache deliberately serves a cached host-path
    probe product to device-capable callers (both are exact); parity
    tests that compare the two paths must start cold."""
    pipeline._COMPILE_CACHE.clear()
    yield
    pipeline._COMPILE_CACHE.clear()


def _mk_req(tags=None, **kw):
    req = tempopb.SearchRequest()
    for k, v in (tags or {}).items():
        req.tags[k] = v
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def _probe_ids(val_dict, needles, n_shards=1, mesh=None):
    dd = dict_probe.stage_val_dict(val_dict, n_shards=n_shards, mesh=mesh)
    hits, any_hits = dict_probe.probe_value_hits(
        dd, [n.encode("utf-8") for n in needles])
    hits = np.asarray(hits)
    any_hits = np.asarray(any_hits)
    out = []
    for t in range(len(needles)):
        ids = dict_probe.hits_to_ids(hits[t])
        assert bool(any_hits[t]) == (ids.size > 0)
        assert not hits[t, len(val_dict):].any(), "padding values lit up"
        out.append(ids)
    return out


# ---------------------------------------------------------------------------
# kernel-level differential parity


def test_probe_matches_host_on_fixed_edges():
    """The edge cases named in ISSUE 4: empty needle, multi-byte chars,
    a needle that only exists ACROSS a value boundary (must not match),
    zero-length values, needle == whole value."""
    vd = sorted(["", "ab", "cd", "alpha", "alphabet", "βeta", "日本語",
                 "日本", "a" * 40, "xx-日本-yy"])
    needles = ["", "ab", "bc",       # "bc" spans ab|cd in the packed buf
               "alpha", "日本", "語", "βeta", "a" * 40, "a" * 41, "zzz"]
    got = _probe_ids(vd, needles)
    for needle, ids in zip(needles, got):
        want = substring_value_ids(vd, needle)
        assert ids.tolist() == want.tolist(), needle


def test_probe_matches_host_property():
    """Random unicode dictionaries × random needles, several size/needle
    buckets; the device kernel must agree exactly with the host scan."""
    charset = "abcdefgh0123-_αβγ日本語🎉"
    rng = random.Random(99)
    for round_ in range(6):
        n_vals = rng.choice([7, 33, 70])
        vd = sorted({
            "".join(rng.choice(charset)
                    for _ in range(rng.randint(0, 12)))
            for _ in range(n_vals)
        })
        needles = []
        for _ in range(rng.randint(1, 4)):
            if rng.random() < 0.3 and vd:
                src = rng.choice(vd)  # sampled substring: real hits
                if src:
                    i = rng.randrange(len(src))
                    needles.append(src[i:i + rng.randint(1, 6)])
                    continue
            needles.append("".join(rng.choice(charset)
                                   for _ in range(rng.randint(0, 5))))
        got = _probe_ids(vd, needles)
        for needle, ids in zip(needles, got):
            want = substring_value_ids(vd, needle)
            assert ids.tolist() == want.tolist(), (round_, needle, vd)


def test_probe_matches_native_scan():
    from tempo_tpu.ops import native
    from tempo_tpu.search.pipeline import pack_val_dict

    if not native.available():
        pytest.skip("native lib unavailable")
    vd = sorted({f"val-{i:05d}-{'x' if i % 3 else 'special'}"
                 for i in range(2_000)})
    buf, offsets = pack_val_dict(vd)
    needles = ["special", "val-0001", "", "zzz", "-x"]
    got = _probe_ids(vd, needles)
    for needle, ids in zip(needles, got):
        want = native.substr_scan(buf, offsets, needle.encode()).tolist()
        assert ids.tolist() == want, needle


def test_probe_sharded_matches_unsharded():
    """The value axis splits into shards and the per-shard masks
    all_gather back — global ids must be identical to the S=1 probe.
    Uses the mesh over the test process's CPU devices."""
    from tempo_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    vd = sorted({f"session-{i:05d}" for i in range(1_000)}
                | {"", "x", "sess"})
    needles = ["session-0001", "sess", "", "zzz", "05"]
    flat = _probe_ids(vd, needles)
    sharded = _probe_ids(vd, needles,
                         n_shards=int(mesh.devices.size), mesh=mesh)
    for needle, a, b in zip(needles, flat, sharded):
        assert a.tolist() == b.tolist(), needle


def test_probe_sharded_pack_placed_unsharded_probes_every_shard():
    """A dictionary packed for an S-way mesh but placed WITHOUT the mesh
    (place_batch's shard-mismatch fallback) must still probe every
    shard's value range — the single-device kernel vmaps over the shard
    axis, it does not silently drop shards 1..S-1."""
    vd = sorted({f"session-{i:05d}" for i in range(500)} | {"", "tail-zz"})
    needles = ["session-0049", "tail", "", "zzz"]
    flat = _probe_ids(vd, needles)
    packed4 = _probe_ids(vd, needles, n_shards=4)  # no mesh passed
    for needle, a, b in zip(needles, flat, packed4):
        assert a.tolist() == b.tolist(), needle
        assert a.tolist() == substring_value_ids(vd, needle).tolist()


def test_backend_search_block_honors_probe_threshold():
    """The single-block path must honor cfg's threshold like the
    batcher: <= 0 keeps the probe on the host, a small threshold stages
    the dictionary and yields identical results."""
    from tempo_tpu.backend import BlockMeta, MockBackend
    from tempo_tpu.search.backend_search_block import (
        BackendSearchBlock,
        write_search_block,
    )

    be = MockBackend()
    meta = BlockMeta(tenant_id="t1")
    write_search_block(be, meta, _corpus(200, seed=7), PageGeometry(32, 8))
    req = _mk_req({"session.id": "session-00"}, limit=500)

    off = BackendSearchBlock(be, meta, probe_min_vals=-1)
    assert off.staged().staged_dict is None
    r_off = off.search(req).response().SerializeToString()

    pipeline._COMPILE_CACHE.clear()
    on = BackendSearchBlock(be, meta, probe_min_vals=1)
    assert on.staged().staged_dict is not None
    assert on.search(req).response().SerializeToString() == r_off


def test_probe_rejects_oversized_needle():
    dd = dict_probe.stage_val_dict(["aa", "bb"])
    with pytest.raises(ValueError):
        dict_probe.probe_value_hits(
            dd, [b"x" * (dict_probe.MAX_NEEDLE_BYTES + 1)])


# ---------------------------------------------------------------------------
# corpora for the dispatch-path tests


def _corpus(n, seed, card=300):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        tid = (seed.to_bytes(2, "big") + i.to_bytes(4, "big")).rjust(16, b"\x00")
        sd = SearchData(trace_id=tid)
        # unique start seconds: top-k tie-breaks are documented as
        # unordered, byte-identity must not depend on them
        sd.start_s = 1_600_000_000 + seed * 1_000_000 + i
        sd.end_s = sd.start_s + 5
        sd.dur_ms = rng.randint(1, 30_000)
        sd.kvs = {"session.id": {f"session-{rng.randint(0, card - 1):04d}"},
                  "svc": {rng.choice(["frontend", "cart"])}}
        out.append(sd)
    return out


def _blocks(n=3, entries=150, small_tail=True):
    blocks = [ColumnarPages.build(_corpus(entries, seed=s),
                                  PageGeometry(32, 8)) for s in range(n)]
    if small_tail:  # one low-cardinality block that stays on the host path
        blocks.append(ColumnarPages.build(_corpus(80, seed=9, card=3),
                                          PageGeometry(32, 8)))
    return blocks


# ---------------------------------------------------------------------------
# single-block engine path


def test_single_block_device_probe_byte_identical():
    pages = ColumnarPages.build(_corpus(300, seed=1), PageGeometry(64, 8))
    req = _mk_req({"session.id": "session-00"}, limit=1000)
    eng = ScanEngine(top_k=1024)

    sp_host = stage(pages, probe_min_vals=0)
    assert sp_host.staged_dict is None
    cq_host = compile_query(pages.key_dict, pages.val_dict, req)
    out_host = eng.scan_staged(sp_host, cq_host)

    pipeline._COMPILE_CACHE.clear()
    sp_dev = stage(pages, probe_min_vals=1)
    assert sp_dev.staged_dict is not None
    cq_dev = compile_query(pages.key_dict, pages.val_dict, req,
                           staged_dict=sp_dev.staged_dict)
    assert cq_dev.val_hits is not None
    out_dev = eng.scan_staged(sp_dev, cq_dev)

    assert out_host[0] == out_dev[0] and out_host[1] == out_dev[1]
    r_h = [(m.trace_id, m.start_time_unix_nano) for m in
           eng.results(sp_host, cq_host, out_host[2], out_host[3])]
    r_d = [(m.trace_id, m.start_time_unix_nano) for m in
           eng.results(sp_dev, cq_dev, out_dev[2], out_dev[3])]
    assert r_h == r_d

    # prune parity: a needle no dictionary value contains prunes on both
    miss = _mk_req({"session.id": "zzz-absent"})
    assert compile_query(pages.key_dict, pages.val_dict, miss,
                         staged_dict=sp_dev.staged_dict) is None


def test_oversized_needle_falls_back_to_exact_host_path():
    pages = ColumnarPages.build(_corpus(120, seed=2), PageGeometry(32, 8))
    sp = stage(pages, probe_min_vals=1)
    long_needle = "x" * (dict_probe.MAX_NEEDLE_BYTES + 1)
    req = _mk_req({"session.id": long_needle, "svc": "frontend"},
                  limit=100)
    # must not raise — the whole query drops to the host scan
    cq = compile_query(pages.key_dict, pages.val_dict, req,
                       staged_dict=sp.staged_dict)
    assert cq is None  # nothing contains a 65-byte needle → pruned
    req2 = _mk_req({"svc": "front" + "t" * dict_probe.MAX_NEEDLE_BYTES})
    assert compile_query(pages.key_dict, pages.val_dict, req2,
                         staged_dict=sp.staged_dict) is None


def test_exhaustive_flag_with_device_probe():
    """Under the exhaustive debug tag a missing key / empty-match term
    must scan (and match nothing), not prune — same semantics as host."""
    pages = ColumnarPages.build(_corpus(100, seed=3), PageGeometry(32, 8))
    sp = stage(pages, probe_min_vals=1)
    req = _mk_req({"absent.key": "x",
                   pipeline.EXHAUSTIVE_SEARCH_TAG: "1"}, limit=50)
    cq = compile_query(pages.key_dict, pages.val_dict, req,
                       staged_dict=sp.staged_dict)
    assert cq is not None
    count, inspected, _, _ = ScanEngine(top_k=64).scan_staged(sp, cq)
    assert count == 0 and inspected == 100


def test_compile_cache_skips_device_probe_work():
    """Repeated tag-sets must hit the compile cache without re-running
    the probe kernel (same contract as the host path's cache)."""
    from unittest import mock

    pages = ColumnarPages.build(_corpus(150, seed=4), PageGeometry(32, 8))
    sp = stage(pages, probe_min_vals=1)
    req = _mk_req({"session.id": "session-01"}, limit=20)
    with mock.patch.object(dict_probe, "probe_value_hits",
                           wraps=dict_probe.probe_value_hits) as probe:
        cq1 = compile_query(pages.key_dict, pages.val_dict, req,
                            cache_on=pages, staged_dict=sp.staged_dict)
        assert cq1 is not None and probe.call_count == 1
        cq2 = compile_query(pages.key_dict, pages.val_dict, req,
                            cache_on=pages, staged_dict=sp.staged_dict)
        assert probe.call_count == 1  # cache hit: no second dispatch
        assert cq2.val_hits is cq1.val_hits


# ---------------------------------------------------------------------------
# multi-block / coalesced / mesh dispatch paths


def test_multiblock_mixed_device_and_host_blocks():
    """High-cardinality blocks probe on device while the small block
    keeps host ranges, in ONE batch — results byte-identical to the
    all-host compile."""
    blocks = _blocks()
    req = _mk_req({"session.id": "session-00"}, limit=1000)
    eng = MultiBlockEngine(top_k=1024)

    batch_host = stack_blocks(blocks, pad_to=32)
    mq_host = compile_multi(blocks, req)
    out_h = eng.scan(batch_host, mq_host)

    pipeline._COMPILE_CACHE.clear()
    batch_dev = stack_blocks(blocks, pad_to=32, probe_min_vals=50)
    assert len(batch_dev.staged_dicts) == 3  # the small block stays host
    mq_dev = compile_multi(blocks, req, cache_on=batch_dev)
    assert mq_dev.val_hits is not None
    assert (mq_dev.block_group >= 0).sum() == 3
    assert mq_dev.block_group[3] == -1
    out_d = eng.scan(batch_dev, mq_dev)

    assert out_h[0] == out_d[0] and out_h[1] == out_d[1]
    r_h = [(m.trace_id, m.start_time_unix_nano) for m in
           eng.results(batch_host, mq_host, out_h[2], out_h[3])]
    r_d = [(m.trace_id, m.start_time_unix_nano) for m in
           eng.results(batch_dev, mq_dev, out_d[2], out_d[3])]
    assert r_h == r_d


def test_multiblock_header_skip_masks_device_probed_block():
    from tempo_tpu.search.data import search_data_matches

    blocks = _blocks(n=2, small_tail=False)
    req = _mk_req({"session.id": "session-0"}, limit=1000)
    batch = stack_blocks(blocks, probe_min_vals=10)
    mq = compile_multi(blocks, req, skip=[True, False], cache_on=batch)
    assert mq is not None
    assert mq.block_group[0] == -1          # skipped row: range path,
    assert (mq.term_keys[0] == -1).all()    # unmatchable sentinel
    eng = MultiBlockEngine(top_k=1024)
    count, _, scores, idx = eng.scan(batch, mq)
    # only block 1's matches survive — block 0 was header-skipped
    expected = {sd.trace_id for sd in _corpus(150, seed=1)
                if search_data_matches(sd, req)}
    assert count == len(expected)
    got = {bytes.fromhex(m.trace_id)
           for m in eng.results(batch, mq, scores, idx)}
    assert got == expected


def test_coalesced_dispatch_with_device_probe_queries():
    """Fused multi-query dispatch where some members carry device hit
    masks and others compiled through the host path — every member's
    fused result equals its solo dispatch."""
    blocks = _blocks()
    batch = stack_blocks(blocks, pad_to=32, probe_min_vals=50)
    eng = MultiBlockEngine(top_k=1024)
    mqs = []
    for v in ("session-001", "session-01"):
        mqs.append(compile_multi(blocks, _mk_req({"session.id": v},
                                                 limit=1000),
                                 cache_on=batch))
    mqs.append(compile_multi(blocks, _mk_req({}, min_duration_ms=10_000,
                                             limit=1000),
                             cache_on=batch))
    mqs = [m for m in mqs if m is not None]
    assert any(m.val_hits is not None for m in mqs)
    assert any(m.val_hits is None for m in mqs)

    cq = stack_queries(mqs)
    assert cq.val_hits is not None
    counts, inspected, scores, idx = eng.coalesced_scan_async(
        batch, cq, 1024)
    counts, scores, idx = (np.asarray(counts), np.asarray(scores),
                           np.asarray(idx))
    for qi, mq in enumerate(mqs):
        s_count, _, s_scores, s_idx = eng.scan(batch, mq)
        assert counts[qi] == s_count
        assert np.array_equal(scores[qi][:s_scores.shape[0]], s_scores)


def test_mesh_sharded_dispatch_with_device_probe():
    """The dictionary shards along the value axis over the mesh, the
    hit masks all_gather, and the sharded scan consumes them — results
    identical to the unsharded host-path scan."""
    from tempo_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    blocks = _blocks(n=2, entries=256, small_tail=False)
    req = _mk_req({"session.id": "session-00"}, limit=1000)

    eng = MultiBlockEngine(top_k=1024, mesh=mesh, device_probe_min_vals=50)
    batch = eng.stage(blocks)
    assert len(batch.staged_dicts) == 2
    assert all(dd.mesh is not None for dd in batch.staged_dicts.values())
    mq = compile_multi(blocks, req, cache_on=batch)
    assert mq.val_hits is not None
    out_mesh = eng.scan(batch, mq)

    pipeline._COMPILE_CACHE.clear()
    eng_h = MultiBlockEngine(top_k=1024)
    batch_h = eng_h.stage(blocks)
    mq_h = compile_multi(blocks, req, cache_on=batch_h)
    assert mq_h.val_hits is None
    out_h = eng_h.scan(batch_h, mq_h)

    assert out_mesh[0] == out_h[0] and out_mesh[1] == out_h[1]
    r_m = {m.trace_id for m in eng.results(batch, mq,
                                           out_mesh[2], out_mesh[3])}
    r_h = {m.trace_id for m in eng_h.results(batch_h, mq_h,
                                             out_h[2], out_h[3])}
    assert r_m == r_h

    # mesh + coalesced + device probe in one dispatch
    mqs = [compile_multi(blocks, _mk_req({"session.id": v}, limit=1000),
                         cache_on=batch)
           for v in ("session-001", "session-01")]
    mqs = [m for m in mqs if m is not None]
    cq = stack_queries(mqs)
    counts = np.asarray(eng.coalesced_scan_async(batch, cq, 1024)[0])
    for qi, m in enumerate(mqs):
        assert counts[qi] == eng.scan(batch, m)[0]


# ---------------------------------------------------------------------------
# batcher: HBM accounting, eviction/re-stage, concurrent coalescing


def _jobs(blocks):
    from tempo_tpu.search.batcher import ScanJob

    jobs = []
    for i, p in enumerate(blocks):
        jobs.append(ScanJob(
            key=(f"blk-{i:03d}", 0, p.n_pages), pages_fn=(lambda p=p: p),
            header=dict(p.header), n_pages=p.n_pages,
            n_entries=p.n_entries,
            geometry=(p.header["entries_per_page"],
                      p.header["kv_per_entry"])))
    return jobs


def test_batcher_accounts_staged_dict_bytes():
    from tempo_tpu.search.batcher import BlockBatcher

    blocks = _blocks(n=2, small_tail=False)
    b = BlockBatcher(coalesce_max_queries=1, device_probe_min_vals=10)
    req = _mk_req({"session.id": "session-01"}, limit=100)
    b.search(_jobs(blocks), req)
    assert b._cache, "nothing staged"
    entry = next(iter(b._cache.values()))
    page_bytes = sum(int(a.nbytes) for a in entry.batch.device.values())
    dict_bytes = sum(d.nbytes for d in entry.batch.staged_dicts.values())
    assert dict_bytes > 0
    assert entry.batch.nbytes == page_bytes + dict_bytes
    # the budget counter tracks the full entry sizes
    assert b._cache_total == sum(e.nbytes for e in b._cache.values())


def test_evicted_batch_restages_dictionaries():
    """HBM eviction must leave the host PACKED dictionaries in the host
    tier; the re-stage re-uploads fresh device arrays (one H2D) with the
    byte accounting intact — and never re-packs the strings."""
    from tempo_tpu.search.batcher import BlockBatcher

    blocks = _blocks(n=2, entries=200, small_tail=False)
    # max_batch_pages below two blocks' pages → one group per block
    b = BlockBatcher(max_batch_pages=8, coalesce_max_queries=1,
                     device_probe_min_vals=10)
    req = _mk_req({"session.id": "session-01"}, limit=100)
    r1 = b.search(_jobs(blocks), req).response().SerializeToString()
    assert len(b._cache) == 2 and len(b._host_cache) == 2
    old_dicts = {k: dict(v.batch.staged_dicts)
                 for k, v in b._cache.items()}
    assert all(d for d in old_dicts.values())
    packed_before = [getattr(blk, "_device_dict_packed", None)
                     for blk in blocks]
    assert all(p is not None for p in packed_before)

    # evict the LRU group from HBM (the bench's churn scenario) — the
    # host tier keeps the stacked arrays AND the packed dictionaries
    with b._lock:
        victim, old_entry = b._cache.popitem(last=False)
        b._cache_total -= old_entry.nbytes
    assert b._cache_total == sum(e.nbytes for e in b._cache.values())

    pipeline._COMPILE_CACHE.clear()
    r2 = b.search(_jobs(blocks), req).response().SerializeToString()
    assert r2 == r1
    # the evicted group re-staged through the host tier with NEW device
    # dictionary arrays (one fresh H2D upload), the host packing reused
    assert victim in b._cache
    entry = b._cache[victim]
    assert entry.batch.staged_dicts
    for fp, dd in entry.batch.staged_dicts.items():
        assert old_dicts[victim][fp] is not dd          # re-uploaded
        assert old_dicts[victim][fp].packed is dd.packed  # not re-packed
    packed_after = [getattr(blk, "_device_dict_packed", None)
                    for blk in blocks]
    assert all(a is p for a, p in zip(packed_after, packed_before))
    # HBM accounting intact after evict + re-stage
    assert b._cache_total == sum(e.nbytes for e in b._cache.values())


def test_batcher_concurrent_device_probe_coalesces_identically():
    """Concurrent searches over device-probed batches (the coalescer's
    fused dispatch) must serialize to the same bytes as solo runs."""
    from tempo_tpu.search.batcher import BlockBatcher

    blocks = _blocks(n=2, small_tail=False)
    jobs = _jobs(blocks)
    serial_b = BlockBatcher(coalesce_max_queries=1,
                            device_probe_min_vals=10)
    co_b = BlockBatcher(coalesce_window_s=0.05, coalesce_max_queries=4,
                        device_probe_min_vals=10)
    reqs = [_mk_req({"session.id": f"session-0{i:02d}"[:11]}, limit=200)
            for i in range(4)]
    serial = [serial_b.search(jobs, r).response().SerializeToString()
              for r in reqs]
    co_b.search(jobs, reqs[0])  # warm staging + compile
    barrier = threading.Barrier(len(reqs))
    got = [None] * len(reqs)

    def worker(i):
        barrier.wait()
        got[i] = co_b.search(jobs, reqs[i]).response().SerializeToString()

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(len(reqs))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert got == serial


# ---------------------------------------------------------------------------
# satellites: fingerprint from the encoded dictionary section, bisected
# tag-values, bench smoke


def test_dict_fingerprint_from_encoded_section():
    sd = SearchData(trace_id=b"\x01" * 16, start_s=1, end_s=2, dur_ms=5)
    sd.kvs = {"k1": {"v1", "v2"}, "k2": {"v3"}}
    pages = ColumnarPages.build([sd], PageGeometry(4, 8))
    blob = pages.to_bytes()
    p2 = ColumnarPages.from_bytes(blob)
    # the decoded container carries the build-time digest: the first
    # cache touch must not walk the dictionaries
    assert p2._dict_section_sha == pages._dict_section_sha
    import hashlib
    from unittest import mock

    with mock.patch.object(hashlib, "sha256",
                           side_effect=AssertionError("python walk ran")):
        fp = pipeline._dict_fingerprint(p2, p2.key_dict, p2.val_dict)
    assert fp == p2._dict_section_sha
    # all decodes of the same container share the fingerprint (compile
    # cache sharing across blocks with identical dictionaries)
    p3 = ColumnarPages.from_bytes(blob)
    assert pipeline._dict_fingerprint(p3, p3.key_dict, p3.val_dict) == fp
    # a page-range slice inherits it (no per-job rehash)
    assert p2.slice_pages(0, 1)._dict_section_sha == fp
    # synthetic/in-memory containers still walk (and still work)
    p4 = ColumnarPages.build([sd], PageGeometry(4, 8))
    assert pipeline._dict_fingerprint(p4, p4.key_dict, p4.val_dict)


def test_legacy_container_without_dict_sha_header():
    import json as _json
    import struct

    sd = SearchData(trace_id=b"\x02" * 16, start_s=1, end_s=2, dur_ms=5)
    sd.kvs = {"k": {"v"}}
    pages = ColumnarPages.build([sd], PageGeometry(4, 8))
    blob = pages.to_bytes()
    hdr_s = struct.Struct("<IIQ")
    magic, version, hdr_len = hdr_s.unpack_from(blob)
    hdr = _json.loads(blob[hdr_s.size:hdr_s.size + hdr_len])
    del hdr["dict_sha"]
    hdr_b = _json.dumps(hdr).encode()
    legacy = hdr_s.pack(magic, version, len(hdr_b)) + hdr_b \
        + blob[hdr_s.size + hdr_len:]
    p = ColumnarPages.from_bytes(legacy)
    # falls back to hashing the encoded section bytes — same digest
    assert p._dict_section_sha == pages._dict_section_sha


def test_values_for_key_bisect():
    sd = SearchData(trace_id=b"\x03" * 16, start_s=1, end_s=2, dur_ms=5)
    sd.kvs = {"bb": {"v1", "v2"}, "dd": {"v3"}}
    pages = ColumnarPages.build([sd], PageGeometry(4, 8))
    assert sorted(pages.values_for_key("bb")) == ["v1", "v2"]
    assert list(pages.values_for_key("dd")) == ["v3"]
    assert list(pages.values_for_key("aa")) == []  # before first key
    assert list(pages.values_for_key("cc")) == []  # between keys
    assert list(pages.values_for_key("zz")) == []  # past the end


def test_bench_high_cardinality_device_probe_smoke():
    """Tier-1-safe smoke of the bench's device-probe measurement at
    small cardinality: both timings present, matches byte-identical
    (asserted inside bench_high_cardinality)."""
    import sys

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import bench

    rate, matches, host_ms, probe = bench.bench_high_cardinality(
        8_192, 2_000, 2, probe_min_vals=500)
    assert rate > 0 and matches >= 0 and host_ms >= 0
    assert probe["device_probe_ms"] is not None
    assert probe["device_probe_rate"] is not None
    assert probe["device_probe_stage_ms"] is not None
