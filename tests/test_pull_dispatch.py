"""Pull-model query dispatch (tempopb.Frontend/Process): dispatcher
fairness + redelivery semantics, the real gRPC duplex stream, and the
redistribution-on-querier-kill behavior the pull model exists for
(reference modules/frontend/v1/frontend.go Process +
modules/querier/worker/frontend_processor.go)."""

import socket
import threading
import time

import grpc
import pytest

from tempo_tpu import tempopb
from tempo_tpu.api.grpc_service import make_module_grpc_server
from tempo_tpu.modules.worker import (
    JobFailed, PullDispatcher, PullQuerierPool, PullQuerierStub, PullWorker,
)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_for(pred, timeout_s=10.0, interval_s=0.02, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval_s)
    raise TimeoutError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# dispatcher unit semantics


def test_dispatcher_roundtrip():
    d = PullDispatcher()
    job = tempopb.ProcessJob(kind="search_tags")
    fut = d.submit("acme", job)
    entry = d.next_job(timeout=1.0)
    assert entry.job.job_id == job.job_id and entry.job.tenant_id == "acme"
    res = tempopb.ProcessResult(job_id=entry.job.job_id)
    res.tags.tag_names.append("svc")
    d.deliver(res)
    assert fut.result(timeout=1).tags.tag_names == ["svc"]
    assert d.delivered == 1
    d.stop()


def test_dispatcher_error_result_raises():
    d = PullDispatcher()
    fut = d.submit("t", tempopb.ProcessJob(kind="search_recent"))
    entry = d.next_job(timeout=1.0)
    d.deliver(tempopb.ProcessResult(job_id=entry.job.job_id, error="boom"))
    with pytest.raises(JobFailed, match="boom"):
        fut.result(timeout=1)
    d.stop()


def test_dispatcher_requeue_then_fail_after_budget():
    d = PullDispatcher(max_redeliveries=2)
    fut = d.submit("t", tempopb.ProcessJob(kind="search_recent"))
    # three deliveries (initial + 2 redeliveries) may fail; the fourth
    # requeue attempt exhausts the budget
    for _ in range(3):
        entry = d.next_job(timeout=1.0)
        assert entry is not None
        d.requeue(entry)
    with pytest.raises(JobFailed, match="failed after"):
        fut.result(timeout=1)
    assert d.next_job(timeout=0.05) is None  # nothing left queued
    d.stop()


def test_dispatcher_abandoned_job_skipped():
    d = PullDispatcher()
    job = tempopb.ProcessJob(kind="search_tags")
    d.submit("t", job)
    d.abandon(job.job_id)
    assert d.next_job(timeout=0.05) is None  # cancelled entry skipped
    d.stop()


def test_dispatcher_tenant_fairness():
    d = PullDispatcher()
    for _ in range(3):
        d.submit("a", tempopb.ProcessJob(kind="search_tags"))
    d.submit("b", tempopb.ProcessJob(kind="search_tags"))
    order = [d.next_job(timeout=1.0).job.tenant_id for _ in range(4)]
    # round-robin: b is served before a's backlog drains
    assert order.index("b") < 3
    d.stop()


# ---------------------------------------------------------------------------
# gRPC stream end-to-end


class FakeQuerier:
    """Duck-typed Querier that records which instance served each job."""

    def __init__(self, name, block_event=None):
        self.name = name
        self.block_event = block_event
        self.served = []

    def search_blocks(self, req):
        if self.block_event is not None:
            self.block_event.wait(30)
        self.served.append("search_blocks")
        resp = tempopb.SearchResponse()
        t = resp.traces.add()
        t.root_service_name = self.name
        resp.metrics.inspected_blocks = len(req.jobs)
        return resp

    def search_recent(self, tenant, req):
        self.served.append("search_recent")
        return tempopb.SearchResponse()

    def find_trace_by_id(self, tenant, trace_id, block_start="", block_end="",
                         mode="all"):
        self.served.append("trace_by_id")
        resp = tempopb.TraceByIDResponse()
        resp.metrics.failed_blocks = 0
        return resp

    def search_tags(self, tenant):
        self.served.append("search_tags")
        resp = tempopb.SearchTagsResponse()
        resp.tag_names.append(f"tag-from-{self.name}")
        return resp

    def search_tag_values(self, tenant, tag):
        self.served.append("search_tag_values")
        resp = tempopb.SearchTagValuesResponse()
        resp.tag_values.append(f"{tag}={self.name}")
        return resp


@pytest.fixture
def frontend_server():
    d = PullDispatcher()
    port = free_port()
    server = make_module_grpc_server(f"127.0.0.1:{port}",
                                     frontend_dispatcher=d)
    server.start()
    yield d, f"127.0.0.1:{port}"
    d.stop()
    server.stop(0)


def test_pull_stream_all_job_kinds(frontend_server):
    d, addr = frontend_server
    q = FakeQuerier("q1")
    w = PullWorker(q, addr, parallelism=1)
    try:
        wait_for(lambda: d.workers() >= 1, what="worker stream connects")
        stub = PullQuerierStub(d, job_timeout_s=10)

        breq = tempopb.SearchBlocksRequest(tenant_id="t")
        breq.jobs.add()
        assert stub.search_blocks(breq).metrics.inspected_blocks == 1
        assert stub.search_recent("t", tempopb.SearchRequest()) is not None
        assert stub.find_trace_by_id("t", b"\x01" * 16) is not None
        assert stub.search_tags("t").tag_names == ["tag-from-q1"]
        assert stub.search_tag_values("t", "svc").tag_values == ["svc=q1"]
        assert set(q.served) == {"search_blocks", "search_recent",
                                 "trace_by_id", "search_tags",
                                 "search_tag_values"}
    finally:
        w.stop()


def test_pull_worker_error_travels_as_job_failure(frontend_server):
    d, addr = frontend_server

    class Exploding(FakeQuerier):
        def search_tags(self, tenant):
            raise ValueError("no tags today")

    w = PullWorker(Exploding("q1"), addr, parallelism=1)
    try:
        wait_for(lambda: d.workers() >= 1, what="worker connects")
        stub = PullQuerierStub(d, job_timeout_s=10)
        with pytest.raises(JobFailed, match="no tags today"):
            stub.search_tags("t")
    finally:
        w.stop()


def test_kill_querier_redistributes_inflight_job(frontend_server):
    """THE pull-model property: a worker dies holding a job; the frontend
    requeues it and the surviving worker answers."""
    d, addr = frontend_server
    stall = threading.Event()
    victim_q = FakeQuerier("victim", block_event=stall)
    victim = PullWorker(victim_q, addr, parallelism=1)
    try:
        wait_for(lambda: d.workers() >= 1, what="victim connects")

        stub = PullQuerierStub(d, job_timeout_s=30)
        breq = tempopb.SearchBlocksRequest(tenant_id="t")
        breq.jobs.add()
        result = {}

        def query():
            result["resp"] = stub.search_blocks(breq)

        t = threading.Thread(target=query, daemon=True)
        t.start()
        # the victim pulls the job and stalls inside its querier
        wait_for(lambda: d.queued() == 0 and d.workers() == 1,
                 what="victim holds the job")
        time.sleep(0.2)

        # survivor joins, then the victim is killed mid-job
        survivor_q = FakeQuerier("survivor")
        survivor = PullWorker(survivor_q, addr, parallelism=1)
        try:
            wait_for(lambda: d.workers() >= 2, what="survivor connects")
            victim.stop()   # cancels the stream with the job in flight
            stall.set()     # unblock the victim thread (its reply is moot)

            t.join(timeout=20)
            assert not t.is_alive(), "query never completed after kill"
            assert result["resp"].traces[0].root_service_name == "survivor"
            assert d.requeued >= 1
        finally:
            survivor.stop()
    finally:
        victim.stop()


def test_pull_pool_falls_back_to_push_clients():
    d = PullDispatcher()
    fallback = ["push-client-0", "push-client-1"]
    pool = PullQuerierPool(d, fallback=fallback)
    # no workers connected: indexes resolve to the push clients
    assert pool[0] == "push-client-0" and len(pool) == 2
    wid = d.register_worker()
    assert isinstance(pool[0], PullQuerierStub) and len(pool) == 1
    d.unregister_worker(wid)
    d.stop()


# ---------------------------------------------------------------------------
# microservice topology over pull dispatch


def test_microservice_pull_topology(tmp_path):
    from tempo_tpu.db import TempoDBConfig
    from tempo_tpu.modules import AppConfig
    from tempo_tpu.modules.microservices import ModuleProcess
    from tempo_tpu.utils.ids import random_trace_id
    from tempo_tpu.utils.test_data import make_trace

    cfg = AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "blk")}},
        wal_dir=str(tmp_path / "wal"),
        replication_factor=1,
        db=TempoDBConfig(blocklist_poll_s=1),
    )
    procs = []

    def mk(target, iid, join=(), grpc=False):
        p = ModuleProcess(
            cfg, target, instance_id=iid,
            grpc_port=free_port() if grpc else 0,
            memberlist_cfg={"join": list(join), "gossip_interval_s": 0.1,
                            "suspect_timeout_s": 5.0},
        )
        procs.append(p)
        return p

    try:
        ing = mk("ingester", "ing-1", grpc=True)
        seed = [ing.ml.gossip_addr]
        dist = mk("distributor", "dist-1", join=seed, grpc=True)
        quer = mk("querier", "quer-1", join=seed, grpc=True)
        front = mk("query-frontend", "front-1", join=seed, grpc=True)

        assert front.dispatcher is not None, "frontend must run pull mode"
        wait_for(lambda: dist.ready() and front.ready(), what="convergence")
        # querier workers discover the frontend via gossip and dial in
        wait_for(lambda: front.dispatcher.workers()
                 >= cfg.frontend_worker_parallelism,
                 timeout_s=15, what="pull workers connect")

        tid = random_trace_id()
        dist.push("acme", list(make_trace(tid, seed=5).batches))
        ing.flush_tick(force=True)
        quer.db.poll()
        front.db.poll()

        req = tempopb.SearchRequest()
        req.tags["service.name"] = "frontend"
        req.limit = 10
        resp = front.search("acme", req)
        assert resp.metrics.inspected_blocks >= 1
        # the answer came over the pull stream, not the push fallback
        assert front.dispatcher.delivered >= 1

        byid = front.find_trace(tenant="acme", trace_id=tid)
        assert byid.trace.batches
    finally:
        for p in procs:
            try:
                p.shutdown()
            except Exception:
                pass


def test_status_exposes_pull_dispatch_stats(tmp_path):
    """Operators see worker/queue/delivery counts on the frontend's
    /status (the reference's frontend queue metrics role)."""
    from tempo_tpu.api.http import HTTPApi
    from tempo_tpu.db import TempoDBConfig
    from tempo_tpu.modules import AppConfig
    from tempo_tpu.modules.microservices import ModuleProcess

    cfg = AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "b")}},
        wal_dir=str(tmp_path / "wal"), db=TempoDBConfig(blocklist_poll_s=1))
    front = ModuleProcess(cfg, "query-frontend", instance_id="f1",
                          grpc_port=free_port(),
                          memberlist_cfg={"gossip_interval_s": 0.2})
    try:
        api = HTTPApi(front)
        code, doc = api.handle("GET", "/status", {}, {})
        assert code == 200
        pd = doc["pull_dispatch"]
        assert set(pd) == {"workers", "queued", "delivered", "requeued"}
    finally:
        front.shutdown()


# ---------------------------------------------------------------------------
# querier shuffle-sharding (reference queue.go querier awareness)


def test_shuffle_shard_limits_tenant_to_subset_of_workers():
    """With max_queriers_per_tenant=2 and 4 worker streams, a tenant's
    jobs only ever pop on its 2 rendezvous-elected workers; another
    tenant gets its own (generally different) pair."""
    d = PullDispatcher(max_queriers_per_tenant=2)
    wids = [d.register_worker() for _ in range(4)]
    try:
        for t in ("tenant-a", "tenant-b", "tenant-c"):
            elig = [w for w in wids if d.eligible(t, w)]
            assert len(elig) == 2, (t, elig)
            # deterministic given the same live set
            assert elig == [w for w in wids if d.eligible(t, w)]
            # jobs for t pop ONLY on eligible workers
            d.submit(t, tempopb.ProcessJob(kind="search_tags"))
            for w in wids:
                if w not in elig:
                    assert d.next_job(timeout=0.02, worker_id=w) is None
            entry = d.next_job(timeout=1.0, worker_id=elig[0])
            assert entry is not None and entry.job.tenant_id == t
        # shards differ across tenants (4 choose 2: collision possible
        # for ONE pair, not all three identical)
        shards = {t: tuple(w for w in wids if d.eligible(t, w))
                  for t in ("tenant-a", "tenant-b", "tenant-c")}
        assert len(set(shards.values())) >= 2, shards
    finally:
        d.stop()


def test_shuffle_shard_heals_on_worker_death():
    d = PullDispatcher(max_queriers_per_tenant=1)
    w1 = d.register_worker()
    w2 = d.register_worker()
    try:
        owner = w1 if d.eligible("t", w1) else w2
        other = w2 if owner == w1 else w1
        assert not d.eligible("t", other)
        d.unregister_worker(owner)  # the tenant's only worker dies
        # survivors inherit: with one live stream, it is always eligible
        assert d.eligible("t", other)
        d.submit("t", tempopb.ProcessJob(kind="search_tags"))
        assert d.next_job(timeout=1.0, worker_id=other) is not None
    finally:
        d.stop()


def test_shuffle_shard_counts_queriers_not_streams():
    """ADVICE r4: with parallelism=2 (two streams per querier process),
    a tenant capped at S queriers must still spread over S DISTINCT
    querier processes — and every stream of an eligible querier is
    eligible."""
    d = PullDispatcher(max_queriers_per_tenant=2)
    streams = {}  # querier id → its two stream ids
    for q in ("qA", "qB", "qC", "qD"):
        streams[q] = [d.register_worker(q), d.register_worker(q)]
    try:
        for t in ("tenant-a", "tenant-b", "tenant-c"):
            elig_q = {q for q, wids in streams.items()
                      if any(d.eligible(t, w) for w in wids)}
            assert len(elig_q) == 2, (t, elig_q)
            for q, wids in streams.items():
                # both streams of a querier agree — all-or-nothing
                assert d.eligible(t, wids[0]) == d.eligible(t, wids[1])
        # querier death (both streams) heals the shard
        victim = sorted(streams)[0]
        for w in streams.pop(victim):
            d.unregister_worker(w)
        for t in ("tenant-a", "tenant-b", "tenant-c"):
            elig_q = {q for q, wids in streams.items()
                      if any(d.eligible(t, w) for w in wids)}
            assert len(elig_q) == 2, (t, elig_q)
    finally:
        d.stop()


def test_pull_worker_streams_share_querier_identity(frontend_server):
    """E2E: one PullWorker with parallelism=2 opens two streams that
    register under ONE querier id (sent as stream metadata)."""
    d, addr = frontend_server
    w = PullWorker(FakeQuerier("q1"), addr, parallelism=2)
    try:
        wait_for(lambda: d.workers() >= 2, what="both streams connect")
        qids = set(d._worker_qids.values())
        assert qids == {w.querier_id}, qids
    finally:
        w.stop()


def test_shuffle_shard_off_by_default():
    d = PullDispatcher()
    w = d.register_worker()
    assert d.eligible("anyone", w) and d.eligible("anyone", 999)
    d.stop()


def test_dispatcher_queue_bound_raises_429_and_cleans_pending():
    """The per-tenant sub-request memory bound propagates as
    TooManyRequests (HTTP 429 at the API layer) and leaves no orphaned
    pending entry."""
    from tempo_tpu.modules.queue import TooManyRequests

    d = PullDispatcher(max_queued_per_tenant=2)
    d.submit("t", tempopb.ProcessJob(kind="search_tags"))
    d.submit("t", tempopb.ProcessJob(kind="search_tags"))
    with pytest.raises(TooManyRequests):
        d.submit("t", tempopb.ProcessJob(kind="search_tags"))
    assert len(d._pending) == 2  # the rejected job didn't leak
    d.stop()
