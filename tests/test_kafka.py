"""Kafka receiver: wire codec, client↔fake-broker, consume→push e2e.

Covers the reference's kafka receiver role (distributor/receiver
shim.go factories) the way §4's e2e backend fakes cover object storage:
a real TCP broker speaking the protocol, real CRC-checked record
batches, offset-commit resume semantics.
"""

from __future__ import annotations

import os

import pytest

from tempo_tpu import tempopb
from tempo_tpu.api.kafka import (
    KafkaClient,
    KafkaReceiver,
    KafkaReceiverConfig,
    crc32c,
    decode_record_batches,
    encode_record_batch,
)
from tempo_tpu.utils.test_data import make_trace

from tests.fake_kafka import FakeKafkaBroker


@pytest.fixture()
def broker():
    b = FakeKafkaBroker(n_partitions=2).start()
    yield b
    b.stop()


def test_crc32c_known_answer():
    # RFC 3720 test vector
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0


def test_record_batch_roundtrip():
    recs = [(b"k1", b"v1"), (None, b"v2"), (b"", os.urandom(100))]
    batch = encode_record_batch(recs, base_offset=7)
    got = decode_record_batches(batch)
    assert [(o, k, v) for o, k, v in got] == [
        (7, b"k1", b"v1"),
        (8, None, b"v2"),
        (9, b"", recs[2][1]),
    ]


def test_record_batch_truncated_tail_dropped():
    b1 = encode_record_batch([(None, b"a")], base_offset=0)
    b2 = encode_record_batch([(None, b"b")], base_offset=1)
    data = b1 + b2[: len(b2) - 3]  # torn fetch response
    got = decode_record_batches(data)
    assert [v for _, _, v in got] == [b"a"]


def test_corrupt_batch_preserves_good_prefix():
    """A CRC-corrupt batch mid-response must not discard the valid
    batches before it (at-least-once: good records are delivered, the
    corrupt batch is hit at the start of the next fetch)."""
    good = encode_record_batch([(None, b"a"), (None, b"b")], base_offset=0)
    bad = bytearray(encode_record_batch([(None, b"z")], base_offset=2))
    bad[-1] ^= 0xFF
    got = decode_record_batches(good + bytes(bad))
    assert [v for _, _, v in got] == [b"a", b"b"]


def test_sasl_username_without_password_fails_fast():
    with pytest.raises(ValueError, match="sasl_password"):
        KafkaReceiverConfig(["h:1"], sasl_username="user")


def test_record_batch_crc_mismatch_raises():
    batch = bytearray(encode_record_batch([(None, b"payload")]))
    batch[-1] ^= 0xFF
    with pytest.raises(ValueError, match="crc32c"):
        decode_record_batches(bytes(batch))


def test_produce_fetch_roundtrip(broker):
    client = KafkaClient([broker.addr])
    meta = client.metadata(["otlp_spans"])
    assert set(meta["otlp_spans"]) == {0, 1}
    base = client.produce("otlp_spans", 0, [(None, b"one"), (None, b"two")])
    assert base == 0
    assert client.produce("otlp_spans", 0, [(None, b"three")]) == 2
    records, hw = client.fetch("otlp_spans", 0, 0, leader=0)
    assert [v for _, _, v in records] == [b"one", b"two", b"three"]
    assert hw == 3
    # mid-batch fetch: client drops records below the requested offset
    records, _ = client.fetch("otlp_spans", 0, 1, leader=0)
    assert [v for _, _, v in records] == [b"two", b"three"]
    client.close()


def test_list_offsets_and_group_offsets(broker):
    client = KafkaClient([broker.addr])
    client.produce("t", 1, [(None, b"x")])
    assert client.list_offset("t", 1, -2, leader=0) == 0  # earliest
    assert client.list_offset("t", 1, -1, leader=0) == 1  # latest
    assert client.fetch_offset("g1", "t", 1) == -1
    client.commit_offset("g1", "t", 1, 1)
    assert client.fetch_offset("g1", "t", 1) == 1
    assert client.fetch_offset("g2", "t", 1) == -1  # group isolation
    client.close()


def _otlp_bytes(tid: bytes, seed: int) -> bytes:
    return make_trace(tid, seed=seed).SerializeToString()


def test_receiver_consume_push_commit(broker):
    client = KafkaClient([broker.addr])
    tid1, tid2 = os.urandom(16), os.urandom(16)
    client.produce("otlp_spans", 0, [(tid1, _otlp_bytes(tid1, 1))])
    client.produce("otlp_spans", 1, [(tid2, _otlp_bytes(tid2, 2))])

    pushed = []
    cfg = KafkaReceiverConfig([broker.addr], start_at="earliest")
    rx = KafkaReceiver(cfg, lambda tenant, batches: pushed.append((tenant, batches)))
    assert rx.poll_once() == 2
    assert len(pushed) == 2
    tids = {rs.scope_spans[0].spans[0].trace_id for _, bs in pushed for rs in bs[:1]}
    assert tids == {tid1, tid2}
    # nothing new → no duplicate delivery
    assert rx.poll_once() == 0
    rx.stop()

    # a fresh receiver (same group) resumes from the committed offsets
    rx2 = KafkaReceiver(cfg, lambda tenant, batches: pushed.append((tenant, batches)))
    assert rx2.poll_once() == 0
    client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), 3))])
    assert rx2.poll_once() == 1
    rx2.stop()
    client.close()


def test_receiver_static_membership_partition_split(broker):
    client = KafkaClient([broker.addr])
    client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), 1))])
    client.produce("otlp_spans", 1, [(None, _otlp_bytes(os.urandom(16), 2))])
    got = {0: 0, 1: 0}
    for idx in (0, 1):
        cfg = KafkaReceiverConfig(
            [broker.addr], start_at="earliest", member_index=idx, members=2
        )
        rx = KafkaReceiver(cfg, lambda t, b: None)
        got[idx] = rx.poll_once()
        rx.stop()
    assert got == {0: 1, 1: 1}  # one partition each, no overlap
    client.close()


def test_receiver_decode_error_skips_and_advances(broker):
    client = KafkaClient([broker.addr])
    good = _otlp_bytes(os.urandom(16), 5)
    client.produce("otlp_spans", 0, [(None, b"\xff\xffnot-a-proto-batch\x00"), (None, good)])
    pushed = []
    cfg = KafkaReceiverConfig([broker.addr], start_at="earliest")
    rx = KafkaReceiver(cfg, lambda t, b: pushed.append(b))
    rx.poll_once()
    # poison message skipped but offset advanced past it
    assert rx.decode_errors == 1
    assert len(pushed) == 1
    assert rx.poll_once() == 0
    rx.stop()
    client.close()


def test_receiver_zipkin_encoding(broker):
    client = KafkaClient([broker.addr])
    body = (
        b'[{"traceId":"%s","id":"1112131415161718","name":"op",'
        b'"localEndpoint":{"serviceName":"svc"},"timestamp":1000,"duration":5}]'
        % (b"0a" * 16)
    )
    client.produce("zipkin_spans", 0, [(None, body)])
    pushed = []
    cfg = KafkaReceiverConfig(
        [broker.addr], topic="zipkin_spans", encoding="zipkin_json", start_at="earliest"
    )
    rx = KafkaReceiver(cfg, lambda t, b: pushed.extend(b))
    assert rx.poll_once() == 1
    assert pushed[0].resource.attributes[0].value.string_value == "svc"
    rx.stop()
    client.close()


def test_receiver_background_thread(broker):
    import time

    pushed = []
    cfg = KafkaReceiverConfig([broker.addr], start_at="earliest", poll_interval_s=0.05)
    rx = KafkaReceiver(cfg, lambda t, b: pushed.append(b))
    rx.start()
    client = KafkaClient([broker.addr])
    client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), 9))])
    deadline = time.time() + 5
    while not pushed and time.time() < deadline:
        time.sleep(0.02)
    rx.stop()
    client.close()
    assert pushed


def test_sasl_plain_auth(broker):
    sb = FakeKafkaBroker(n_partitions=1, sasl=("user", "secret")).start()
    try:
        # correct credentials: full produce/fetch path works
        client = KafkaClient([sb.addr], sasl=("user", "secret"))
        client.produce("t", 0, [(None, b"v")])
        records, _ = client.fetch("t", 0, 0, leader=0)
        assert [v for _, _, v in records] == [b"v"]
        client.close()
        # wrong password: authenticate is rejected
        bad = KafkaClient([sb.addr], sasl=("user", "wrong"))
        with pytest.raises(Exception):
            bad.metadata(["t"], force=True)
        bad.close()
        # no SASL at all: broker drops the connection on first real API
        anon = KafkaClient([sb.addr])
        with pytest.raises(Exception):
            anon.metadata(["t"], force=True)
        anon.close()
    finally:
        sb.stop()


def test_dead_connection_evicted_and_reconnects(broker):
    client = KafkaClient([broker.addr])
    client.metadata(["t"], force=True)
    # simulate a dropped socket (broker restart / idle timeout)
    for conn in client._conns.values():
        conn.sock.close()
    with pytest.raises((OSError, ConnectionError, ValueError)):
        client.metadata(["t"], force=True)
    # eviction means the next call opens a fresh connection and succeeds
    assert client.metadata(["t"], force=True)["t"]
    client.close()


def test_offset_out_of_range_resets_to_earliest(broker):
    client = KafkaClient([broker.addr])
    for i in range(5):
        client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), i))])
    pushed = []
    cfg = KafkaReceiverConfig([broker.addr], start_at="earliest", members=2)
    rx = KafkaReceiver(cfg, lambda t, b: pushed.append(b))
    assert rx.poll_once() == 5
    rx.stop()

    # retention deletes segments under the committed offset (commit=5,
    # log now starts at 6) — a fresh consumer must reset, not wedge
    client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), 9))])
    client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), 10))])
    broker.truncate("otlp_spans", 0, 6)
    rx2 = KafkaReceiver(cfg, lambda t, b: pushed.append(b))
    assert rx2.poll_once() == 0  # detects out-of-range, schedules reset
    assert rx2.offset_resets == 1
    assert rx2.poll_once() == 1  # resumes from the new log start
    rx2.stop()
    client.close()


def test_metadata_cached_between_polls(broker):
    client = KafkaClient([broker.addr])
    m1 = client.metadata(["otlp_spans"])
    assert client.metadata(["otlp_spans"]) is m1  # TTL cache hit
    assert client.metadata(["otlp_spans"], force=True) is not m1
    client.close()


def test_app_kafka_receiver_e2e(broker, tmp_path):
    """config → App → kafka consume → distributor → find_trace."""
    import time

    from tempo_tpu.cli.config import load_config
    from tempo_tpu.modules.app import App

    cfg, _runtime = load_config(text=f"""
storage:
  backend: memory
  wal_dir: {tmp_path}/wal
distributor:
  receivers:
    kafka:
      brokers: ["{broker.addr}"]
      topic: otlp_spans
      start_at: earliest
      poll_interval_s: 0.05
      tenant: t-kafka
""")
    assert cfg.receivers["kafka"]["topic"] == "otlp_spans"
    tid = os.urandom(16)
    client = KafkaClient([broker.addr])
    client.produce("otlp_spans", 0, [(tid, _otlp_bytes(tid, 11))])
    app = App(cfg)
    try:
        app.start_receivers()
        deadline = time.time() + 5
        found = None
        while time.time() < deadline:
            found = app.find_trace("t-kafka", tid)
            if found is not None and len(found.trace.batches):
                break
            time.sleep(0.05)
        assert found is not None and len(found.trace.batches)
    finally:
        app.shutdown()
    client.close()


def test_pubsub_lite_requires_token():
    from tempo_tpu.api.kafka import pubsub_lite_receiver

    with pytest.raises(ValueError, match="token"):
        pubsub_lite_receiver({"topic": "t", "subscription": "s"}, lambda t, b: None)


def test_crc32c_native_matches_python():
    from tempo_tpu.api.kafka import _crc32c_py
    from tempo_tpu.ops import native

    if not native.available():
        pytest.skip("native runtime not built")
    for n in (0, 1, 7, 8, 13, 4096):
        d = os.urandom(n)
        assert native.crc32c(d) == _crc32c_py(d)


def test_otlp_batch_proto_parse():
    tid = os.urandom(16)
    t = tempopb.Trace()
    t.ParseFromString(_otlp_bytes(tid, 1))
    assert t.batches[0].scope_spans[0].spans[0].trace_id == tid


def test_corrupt_batch_skips_whole_batch(broker):
    """A CRC-corrupt N-record batch advances the offset past the WHOLE
    batch in one poll round via the header's lastOffsetDelta, instead of
    grinding one offset per fetch cycle (ADVICE r1 #3)."""
    from tempo_tpu.api.kafka import (
        CorruptBatchError, decode_record_batches, encode_record_batch,
    )

    batch = encode_record_batch(
        [(None, b"v%d" % i) for i in range(7)], base_offset=40)
    corrupt = bytearray(batch)
    corrupt[-1] ^= 0xFF  # flip a byte inside the CRC'd body
    with pytest.raises(CorruptBatchError) as ei:
        decode_record_batches(bytes(corrupt))
    assert ei.value.next_offset == 47  # base 40 + lastOffsetDelta 6 + 1

    # consumer-level: the partition offset jumps the whole batch
    pushed = []
    cfg = KafkaReceiverConfig([broker.addr], start_at="earliest")
    rx = KafkaReceiver(cfg, lambda t, b: pushed.append(b))
    real_fetch = rx.client.fetch
    calls = []

    def corrupt_once(topic, partition, offset, leader):
        calls.append(offset)
        if len(calls) == 1:
            raise CorruptBatchError("crc", next_offset=offset + 7)
        return real_fetch(topic, partition, offset, leader)

    rx.client.fetch = corrupt_once
    rx.poll_once()
    assert rx.decode_errors == 1
    assert rx._offsets[0] == 7  # skipped the whole 7-record batch
    rx.stop()


def test_corrupt_delta_field_falls_back_to_single_step():
    """When the corruption hits lastOffsetDelta itself, the delta fails
    the self-consistency check (delta == count-1) and the skip falls back
    to one offset — over-skipping would drop valid batches."""
    from tempo_tpu.api.kafka import CorruptBatchError, decode_record_batches, encode_record_batch

    batch = bytearray(encode_record_batch(
        [(None, b"v%d" % i) for i in range(7)], base_offset=40))
    # batch layout: baseOffset(8) len(4) epoch(4) magic(1) crc(4)
    # attributes(2) lastOffsetDelta(4) — corrupt the delta itself
    batch[23] ^= 0x7F
    with pytest.raises(CorruptBatchError) as ei:
        decode_record_batches(bytes(batch))
    assert ei.value.next_offset == 41  # base+1, NOT a wild jump


def test_corrupt_batch_unanchored_base_not_trusted():
    """baseOffset lives outside the CRC'd region too: when it doesn't
    anchor to the offset the caller fetched, no skip math is trusted
    (the receiver falls back to offset+1)."""
    from tempo_tpu.api.kafka import CorruptBatchError, decode_record_batches, encode_record_batch

    batch = bytearray(encode_record_batch(
        [(None, b"v%d" % i) for i in range(7)], base_offset=40))
    batch[-1] ^= 0xFF  # body corrupt; header intact
    # caller fetched offset 40: anchored, delta trusted
    with pytest.raises(CorruptBatchError) as ei:
        decode_record_batches(bytes(batch), expect_base=40)
    assert ei.value.next_offset == 47
    # caller fetched offset 5000: base 40 is garbage w.r.t. the request
    with pytest.raises(CorruptBatchError) as ei:
        decode_record_batches(bytes(batch), expect_base=5000)
    assert ei.value.next_offset is None


def test_dead_member_partitions_adopted_by_survivor(broker):
    """Liveness rebalance: member 1 heartbeats, consumes its partition,
    then dies (stops heartbeating). After liveness_timeout_s the
    survivor's split covers ALL partitions, resuming partition 1 from
    the dead member's committed offset."""
    import time as _t

    client = KafkaClient([broker.addr])
    client.produce("otlp_spans", 0, [(None, _otlp_bytes(os.urandom(16), 1))])
    client.produce("otlp_spans", 1, [(None, _otlp_bytes(os.urandom(16), 2))])

    def cfg(idx):
        return KafkaReceiverConfig(
            [broker.addr], start_at="earliest", member_index=idx, members=2,
            heartbeat_interval_s=0.05, liveness_timeout_s=0.4)

    rx0 = KafkaReceiver(cfg(0), lambda t, b: None)
    rx1 = KafkaReceiver(cfg(1), lambda t, b: None)
    # both alive: static split, one record each
    assert rx1.poll_once() == 1   # member 1 consumes + commits partition 1
    assert rx0.poll_once() == 1
    assert rx0._live_members() == [0, 1]

    # member 1 dies silently (no more heartbeats)
    rx1.stop()
    client.produce("otlp_spans", 1, [(None, _otlp_bytes(os.urandom(16), 3))])

    deadline = _t.monotonic() + 5.0
    adopted = 0
    while _t.monotonic() < deadline:
        _t.sleep(0.1)
        adopted += rx0.poll_once()
        if adopted:
            break
    assert adopted == 1, "survivor never adopted the dead member's partition"
    assert rx0._live_members() == [0]
    # resumed from member 1's commit: exactly the ONE new record, not a
    # replay of what member 1 already consumed
    rx0.stop()
    client.close()


def test_revived_member_reclaims_partitions(broker):
    import time as _t

    def cfg(idx):
        return KafkaReceiverConfig(
            [broker.addr], start_at="earliest", member_index=idx, members=2,
            heartbeat_interval_s=0.05, liveness_timeout_s=0.3)

    client = KafkaClient([broker.addr])
    rx0 = KafkaReceiver(cfg(0), lambda t, b: None)
    rx0.poll_once()
    _t.sleep(0.4)  # member 1 has never heartbeated → not live
    assert rx0._live_members() == [0]
    assert set(rx0._my_partitions({0: 1, 1: 1})) == {0, 1}

    rx1 = KafkaReceiver(cfg(1), lambda t, b: None)
    rx1.poll_once()  # heartbeats
    _t.sleep(0.1)
    rx0._live_checked = 0.0  # force a fresh liveness sweep
    assert rx0._live_members() == [0, 1]
    assert set(rx0._my_partitions({0: 1, 1: 1})) == {0}
    rx0.stop(); rx1.stop()
    client.close()


def test_blind_heartbeats_hold_static_split(broker):
    """ADVICE r4: if our own heartbeat never reads back (a broker that
    accepts but does not serve the synthetic partition), every member
    would see only itself live and adopt the whole topic. The receiver
    must detect the blind readback and hold the static split instead."""
    import time as _t

    cfg = KafkaReceiverConfig(
        [broker.addr], start_at="earliest", member_index=0, members=2,
        heartbeat_interval_s=0.05, liveness_timeout_s=0.2)
    rx = KafkaReceiver(cfg, lambda t, b: None)
    rx.poll_once()  # commits a heartbeat (so the blind check is armed)
    # broker "loses" every heartbeat readback from here on
    rx.client.fetch_offset = lambda *a, **k: -1
    _t.sleep(0.3)  # past the startup grace
    rx._live_checked = 0.0
    assert rx._live_members() == [0, 1]  # static roster, not self-only
    assert set(rx._my_partitions({0: 1, 1: 1})) == {0}
    rx.stop()


def test_sticky_reassignment_moves_only_dead_members_share(broker):
    """members=3, member 1 dead: members 0 and 2 keep their static
    partitions; only member 1's fold onto survivors."""
    def rx_with_live(idx, live):
        cfg = KafkaReceiverConfig([broker.addr], member_index=idx, members=3,
                                  heartbeat_interval_s=0)  # static base
        r = KafkaReceiver(cfg, lambda t, b: None)
        r._live_members = lambda: live  # fabricate the liveness view
        return r

    parts = {p: 1 for p in range(6)}
    own0 = set(rx_with_live(0, [0, 2])._my_partitions(parts))
    own2 = set(rx_with_live(2, [0, 2])._my_partitions(parts))
    # static shares survive: 0 keeps {0,3}, 2 keeps {2,5}
    assert {0, 3} <= own0 and {2, 5} <= own2
    # the dead member's {1,4} are covered exactly once between survivors
    assert own0 | own2 == set(parts)
    assert own0 & own2 == set()
