"""Cache, queues, metrics-generator, CLI tooling, vulture."""

import io
import json
import sys
import threading
import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.backend import MockBackend, LocalBackend
from tempo_tpu.backend.cache import CachedBackend, LRUCache
from tempo_tpu.modules import App, AppConfig
from tempo_tpu.modules.generator import (
    MetricsGenerator,
    ServiceGraphProcessor,
    SpanMetricsProcessor,
)
from tempo_tpu.modules.queue import ExclusiveQueue, RequestQueue, TooManyRequests
from tempo_tpu.observability.metrics import Registry
from tempo_tpu.cli.vulture import Vulture
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


# ---- cache ----

def test_cached_backend_read_through():
    inner = MockBackend()
    cb = CachedBackend(inner, LRUCache(max_bytes=1 << 20))
    cb.write("t", "b", "index", b"idx")
    cb.write("t", "b", "data", b"data")
    inner.read_count = 0
    for _ in range(5):
        assert cb.read("t", "b", "index") == b"idx"
    assert inner.read_count == 0  # warmed by write-through
    for _ in range(5):
        cb.read("t", "b", "data")
    assert inner.read_count == 5  # data is never cached


def test_lru_eviction():
    c = LRUCache(max_bytes=100)
    c.store("a", b"x" * 60)
    c.store("b", b"y" * 60)  # evicts a
    assert c.fetch("a") is None
    assert c.fetch("b") is not None


# ---- queues ----

def test_request_queue_tenant_fairness():
    q = RequestQueue()
    for i in range(3):
        q.enqueue("noisy", f"n{i}")
    q.enqueue("quiet", "q0")
    served = [q.get(timeout=0.1)[0] for _ in range(3)]
    # quiet tenant is served within the first rounds, not starved
    assert "quiet" in served


def test_request_queue_max_outstanding():
    q = RequestQueue(max_outstanding_per_tenant=2)
    q.enqueue("t", 1)
    q.enqueue("t", 2)
    with pytest.raises(TooManyRequests):
        q.enqueue("t", 3)


def test_exclusive_queue_dedupes_inflight():
    q = ExclusiveQueue()
    assert q.enqueue("block-1", 1.0, "op")
    assert not q.enqueue("block-1", 0.5, "dup")  # queued → refused
    key, item = q.dequeue()
    assert not q.enqueue("block-1", 0.5, "dup")  # in-flight → refused
    q.done(key)
    assert q.enqueue("block-1", 0.5, "retry")    # released → accepted


# ---- metrics generator ----

def _client_server_pair(tid, client_svc="web", server_svc="db", error=False):
    client = tempopb.ResourceSpans()
    kv = client.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = client_svc
    cs = client.scope_spans.add().spans.add()
    cs.trace_id = tid
    cs.span_id = b"\x01" * 8
    cs.kind = tempopb.Span.SPAN_KIND_CLIENT
    cs.start_time_unix_nano = 10**9
    cs.end_time_unix_nano = int(1.5e9)

    server = tempopb.ResourceSpans()
    kv = server.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = server_svc
    ss = server.scope_spans.add().spans.add()
    ss.trace_id = tid
    ss.span_id = b"\x02" * 8
    ss.parent_span_id = cs.span_id
    ss.kind = tempopb.Span.SPAN_KIND_SERVER
    if error:
        ss.status.code = tempopb.Status.STATUS_CODE_ERROR
    return client, server


def test_spanmetrics_processor():
    reg = Registry()
    p = SpanMetricsProcessor(reg)
    tid = random_trace_id()
    p.consume(make_trace(tid, seed=1).batches[0])
    out = reg.expose()
    assert "traces_spanmetrics_calls_total" in out
    assert "traces_spanmetrics_latency_bucket" in out


def test_service_graph_pairs_edges():
    reg = Registry()
    p = ServiceGraphProcessor(reg)
    client, server = _client_server_pair(random_trace_id())
    p.consume(client)
    p.consume(server)
    assert p.requests.value(client="web", server="db") == 1
    assert p.failed.value(client="web", server="db") == 0

    c2, s2 = _client_server_pair(random_trace_id(), error=True)
    p.consume(s2)  # server first — order must not matter
    p.consume(c2)
    assert p.requests.value(client="web", server="db") == 2
    assert p.failed.value(client="web", server="db") == 1


def test_generator_end_to_end_via_app(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    tid = random_trace_id()
    app.push("t1", list(make_trace(tid, seed=5).batches))
    app.distributor.forward_flush()  # forwarder is async off the hot path
    out = app.generator.collect("t1")
    assert "traces_spanmetrics_calls_total" in out


def test_generator_series_limit():
    gen = MetricsGenerator(max_active_series=1)
    tid = random_trace_id()
    gen.push_spans("t", list(make_trace(tid, seed=1).batches))
    before = gen.dropped_over_limit
    gen.push_spans("t", list(make_trace(random_trace_id(), seed=2).batches))
    assert gen.dropped_over_limit > before


# ---- CLI ----

def test_cli_block_tooling(tmp_path, capsys):
    from tempo_tpu.cli import blocks as cli

    # build a block via the app
    app = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "be")}},
        wal_dir=str(tmp_path / "wal"),
    ))
    tid = random_trace_id()
    app.push("t1", list(make_trace(tid, seed=9).batches))
    app.flush_tick(force=True)

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "list-blocks", "t1"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert len(rows) == 1 and rows[0]["objects"] == 1
    bid = rows[0]["id"]

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "view-block", "t1", bid]) == 0
    view = json.loads(capsys.readouterr().out)
    assert view["total_objects"] == 1 and view["pages"]

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "find", "t1", bid, tid.hex()]) == 0
    assert "batches" in capsys.readouterr().out

    # destroy + regenerate bloom, then find still works
    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "gen-bloom", "t1", bid]) == 0
    capsys.readouterr()
    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "find", "t1", bid, tid.hex()]) == 0
    capsys.readouterr()

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "search", "t1", "--tags", "component=db"]) == 0


# ---- vulture ----

def test_vulture_consistency_cycle(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    v = Vulture(app)
    stats = v.run_cycle(n=5)
    assert stats.written == 5
    assert stats.found == 5 and stats.missing == 0 and stats.mismatched == 0
    assert stats.search_found == 5 and stats.search_missing == 0

    # and again after a flush (block path)
    app.flush_tick(force=True)
    app.poll_tick()
    v.read_pass()
    assert v.stats.missing == 0
