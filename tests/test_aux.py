"""Cache, queues, metrics-generator, CLI tooling, vulture."""

import io
import json
import sys
import threading
import time

import pytest

from tempo_tpu import tempopb
from tempo_tpu.backend import MockBackend, LocalBackend
from tempo_tpu.backend.cache import CachedBackend, LRUCache
from tempo_tpu.modules import App, AppConfig
from tempo_tpu.modules.generator import (
    MetricsGenerator,
    ServiceGraphProcessor,
    SpanMetricsProcessor,
)
from tempo_tpu.modules.queue import ExclusiveQueue, RequestQueue, TooManyRequests
from tempo_tpu.observability.metrics import Registry
from tempo_tpu.cli.vulture import Vulture
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


# ---- cache ----

def test_cached_backend_read_through():
    inner = MockBackend()
    cb = CachedBackend(inner, LRUCache(max_bytes=1 << 20))
    cb.write("t", "b", "index", b"idx")
    cb.write("t", "b", "data", b"data")
    inner.read_count = 0
    for _ in range(5):
        assert cb.read("t", "b", "index") == b"idx"
    assert inner.read_count == 0  # warmed by write-through
    for _ in range(5):
        cb.read("t", "b", "data")
    assert inner.read_count == 5  # data is never cached


def test_lru_eviction():
    c = LRUCache(max_bytes=100)
    c.store("a", b"x" * 60)
    c.store("b", b"y" * 60)  # evicts a
    assert c.fetch("a") is None
    assert c.fetch("b") is not None


# ---- queues ----

def test_request_queue_tenant_fairness():
    q = RequestQueue()
    for i in range(3):
        q.enqueue("noisy", f"n{i}")
    q.enqueue("quiet", "q0")
    served = [q.get(timeout=0.1)[0] for _ in range(3)]
    # quiet tenant is served within the first rounds, not starved
    assert "quiet" in served


def test_request_queue_max_outstanding():
    # the cap counts top-level request brackets, not queued sub-requests
    # (reference v1/frontend.go:46-48)
    q = RequestQueue(max_outstanding_per_tenant=2)
    q.begin_request("t")
    q.begin_request("t")
    with pytest.raises(TooManyRequests):
        q.begin_request("t")
    q.end_request("t")
    q.begin_request("t")  # slot released -> admitted again
    assert q.outstanding("t") == 2


def test_exclusive_queue_dedupes_inflight():
    q = ExclusiveQueue()
    assert q.enqueue("block-1", 1.0, "op")
    assert not q.enqueue("block-1", 0.5, "dup")  # queued → refused
    key, item = q.dequeue()
    assert not q.enqueue("block-1", 0.5, "dup")  # in-flight → refused
    q.done(key)
    assert q.enqueue("block-1", 0.5, "retry")    # released → accepted


# ---- metrics generator ----

def _client_server_pair(tid, client_svc="web", server_svc="db", error=False):
    client = tempopb.ResourceSpans()
    kv = client.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = client_svc
    cs = client.scope_spans.add().spans.add()
    cs.trace_id = tid
    cs.span_id = b"\x01" * 8
    cs.kind = tempopb.Span.SPAN_KIND_CLIENT
    cs.start_time_unix_nano = 10**9
    cs.end_time_unix_nano = int(1.5e9)

    server = tempopb.ResourceSpans()
    kv = server.resource.attributes.add()
    kv.key = "service.name"
    kv.value.string_value = server_svc
    ss = server.scope_spans.add().spans.add()
    ss.trace_id = tid
    ss.span_id = b"\x02" * 8
    ss.parent_span_id = cs.span_id
    ss.kind = tempopb.Span.SPAN_KIND_SERVER
    if error:
        ss.status.code = tempopb.Status.STATUS_CODE_ERROR
    return client, server


def test_spanmetrics_processor():
    reg = Registry()
    p = SpanMetricsProcessor(reg)
    tid = random_trace_id()
    p.consume(make_trace(tid, seed=1).batches[0])
    out = reg.expose()
    assert "traces_spanmetrics_calls_total" in out
    assert "traces_spanmetrics_latency_bucket" in out


def test_spanmetrics_non_string_service_label():
    """ADVICE r5: a non-string service.name (int/bool/double) must label
    the series with the stringified AnyValue — matching search-data
    extraction and the native summary feed — not the empty string
    .string_value yields."""
    reg = Registry()
    p = SpanMetricsProcessor(reg)
    for field, val, want in (("int_value", 123, "123"),
                             ("bool_value", True, "true"),
                             ("double_value", 2.5, "2.5")):
        b = tempopb.ResourceSpans()
        kv = b.resource.attributes.add()
        kv.key = "service.name"
        setattr(kv.value, field, val)
        sp = b.scope_spans.add().spans.add()
        sp.trace_id = random_trace_id()
        sp.name = "op"
        sp.start_time_unix_nano = 1
        sp.end_time_unix_nano = 2
        p.consume(b)
        assert f'service="{want}"' in reg.expose()


def test_service_graph_non_string_service_label():
    reg = Registry()
    p = ServiceGraphProcessor(reg)
    client, server = _client_server_pair(random_trace_id())
    for half in (client, server):
        for kv in half.resource.attributes:
            if kv.key == "service.name":
                kv.value.int_value = 7  # clears string_value (oneof)
    p.consume(client)
    p.consume(server)
    assert p.requests.value(client="7", server="7") == 1


def test_service_graph_pairs_edges():
    reg = Registry()
    p = ServiceGraphProcessor(reg)
    client, server = _client_server_pair(random_trace_id())
    p.consume(client)
    p.consume(server)
    assert p.requests.value(client="web", server="db") == 1
    assert p.failed.value(client="web", server="db") == 0

    c2, s2 = _client_server_pair(random_trace_id(), error=True)
    p.consume(s2)  # server first — order must not matter
    p.consume(c2)
    assert p.requests.value(client="web", server="db") == 2
    assert p.failed.value(client="web", server="db") == 1


def test_generator_end_to_end_via_app(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    tid = random_trace_id()
    app.push("t1", list(make_trace(tid, seed=5).batches))
    app.distributor.forward_flush()  # forwarder is async off the hot path
    out = app.generator.collect("t1")
    assert "traces_spanmetrics_calls_total" in out


def test_generator_series_limit():
    gen = MetricsGenerator(max_active_series=1)
    tid = random_trace_id()
    gen.push_spans("t", list(make_trace(tid, seed=1).batches))
    before = gen.dropped_over_limit
    gen.push_spans("t", list(make_trace(random_trace_id(), seed=2).batches))
    assert gen.dropped_over_limit > before


# ---- CLI ----

def test_cli_block_tooling(tmp_path, capsys):
    from tempo_tpu.cli import blocks as cli

    # build a block via the app
    app = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "be")}},
        wal_dir=str(tmp_path / "wal"),
    ))
    tid = random_trace_id()
    app.push("t1", list(make_trace(tid, seed=9).batches))
    app.flush_tick(force=True)

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "list-blocks", "t1"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert len(rows) == 1 and rows[0]["objects"] == 1
    bid = rows[0]["id"]

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "view-block", "t1", bid]) == 0
    view = json.loads(capsys.readouterr().out)
    assert view["total_objects"] == 1 and view["pages"]

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "find", "t1", bid, tid.hex()]) == 0
    assert "batches" in capsys.readouterr().out

    # destroy + regenerate bloom, then find still works
    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "gen-bloom", "t1", bid]) == 0
    capsys.readouterr()
    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "find", "t1", bid, tid.hex()]) == 0
    capsys.readouterr()

    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "search", "t1", "--tags", "component=db"]) == 0
    capsys.readouterr()

    # duration/window filters parse and apply (a 1h floor excludes all)
    assert cli.main(["--backend-path", str(tmp_path / "be"),
                     "search", "t1", "--min-duration", "3600s"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert not out.get("traces")


# ---- vulture ----

def test_vulture_consistency_cycle(tmp_path):
    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    v = Vulture(app)
    stats = v.run_cycle(n=5)
    assert stats.written == 5
    assert stats.found == 5 and stats.missing == 0 and stats.mismatched == 0
    assert stats.search_found == 5 and stats.search_missing == 0

    # and again after a flush (block path)
    app.flush_tick(force=True)
    app.poll_tick()
    v.read_pass()
    assert v.stats.missing == 0


# ---- shuffle shard / quorum / hedging / serverless / receivers ----

def test_shuffle_shard_deterministic_and_isolated():
    from tempo_tpu.modules import Ring

    ring = Ring(replication_factor=2)
    for i in range(10):
        ring.register(f"i{i}")
    a1 = ring.shuffle_shard("tenant-a", 3)
    a2 = ring.shuffle_shard("tenant-a", 3)
    b = ring.shuffle_shard("tenant-b", 3)
    assert a1.instance_ids() == a2.instance_ids()
    assert len(a1.instance_ids()) == 3
    assert a1.instance_ids() != b.instance_ids()  # overwhelmingly likely
    # placement inside the sub-ring only uses its instances
    got = a1.get(12345)
    assert set(got) <= set(a1.instance_ids())


def test_write_quorum_one_mode(tmp_path):
    """RF=2 eventual-consistency: one replica down, quorum 'one' accepts
    the write while 'majority' (2 of 2) rejects it."""
    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.modules.distributor import Distributor, IngestError

    app = App(AppConfig(wal_dir=str(tmp_path / "wal"), n_ingesters=2,
                        replication_factor=2))

    class Broken:
        def push_bytes(self, *a):
            raise OSError("down")

    pushers = dict(app.ingesters)
    pushers[next(iter(pushers))] = Broken()

    tid = random_trace_id()
    tr = make_trace(tid, seed=1)
    strict = Distributor(app.ring, pushers, app.overrides)
    with pytest.raises(IngestError):
        strict.push_batches("t1", list(tr.batches))
    eventual = Distributor(app.ring, pushers, app.overrides,
                           write_quorum="one")
    eventual.push_batches("t1", list(tr.batches))  # succeeds


def test_hedged_call_returns_fast_result():
    from tempo_tpu.db.hedge import hedged_call

    calls = []

    def slow_then_fast():
        calls.append(1)
        if len(calls) == 1:
            time.sleep(2.0)
            return "slow"
        return "fast"

    out = hedged_call(slow_then_fast, hedge_after_s=0.05, max_hedges=2)
    assert out == "fast"


def test_hedged_backend_passthrough():
    from tempo_tpu.db.hedge import HedgedBackend

    inner = MockBackend()
    hb = HedgedBackend(inner, hedge_after_s=5)
    hb.write("t", "b", "data", b"abc")  # __getattr__ passthrough
    assert hb.read("t", "b", "data") == b"abc"
    assert hb.read_range("t", "b", "data", 1, 1) == b"b"


def test_serverless_worker_and_external_querier(tmp_path):
    import threading

    from tempo_tpu.modules import App, AppConfig
    from tempo_tpu.modules.querier import Querier
    from tempo_tpu.serverless import SearchWorker, serve_worker

    app = App(AppConfig(
        backend={"backend": "local", "local": {"path": str(tmp_path / "be")}},
        wal_dir=str(tmp_path / "wal"),
    ))
    traces = {}
    for i in range(10):
        tid = random_trace_id()
        app.push("t1", list(make_trace(tid, seed=i).batches))
        traces[tid] = 1
    app.flush_tick(force=True)
    app.poll_tick()
    meta = app.reader_db.blocklist.metas("t1")[0]

    worker = SearchWorker(app.backend, wal_dir=str(tmp_path / "worker-wal"))
    server = serve_worker(worker, host="127.0.0.1", port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        req = tempopb.SearchBlockRequest()
        req.tenant_id = "t1"
        req.block_id = meta.block_id
        req.search_req.limit = 100

        # querier with prefer_self=0 → every job goes external
        q = Querier(app.reader_db, app.ring, app.ingesters,
                    external_endpoints=[f"http://127.0.0.1:{port}"],
                    prefer_self=0, external_hedge_after_s=5.0)
        resp = q.search_block(req)
        assert len(resp.traces) == 10

        # malformed body → 400 (a hedging caller must not retry it)
        import urllib.error
        import urllib.request

        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/search-block",
            data=b"\xff\xfenot-a-proto-message-at-all" * 3,
            headers={"Content-Type": "application/protobuf"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=5)
        assert ei.value.code == 400
    finally:
        server.shutdown()


def test_zipkin_receiver(tmp_path):
    from tempo_tpu.api import HTTPApi
    from tempo_tpu.modules import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    api = HTTPApi(app)
    tid = "0102030405060708090a0b0c0d0e0f10"
    spans = [
        {"traceId": tid, "id": "1112131415161718", "name": "get /",
         "kind": "SERVER", "timestamp": 1_600_000_000_000_000,
         "duration": 250_000,
         "localEndpoint": {"serviceName": "shop"},
         "tags": {"http.method": "GET"}},
        {"traceId": tid, "id": "2122232425262728",
         "parentId": "1112131415161718", "name": "q",
         "kind": "CLIENT", "timestamp": 1_600_000_000_050_000,
         "duration": 100_000,
         "localEndpoint": {"serviceName": "db"}},
    ]
    code, body = api.handle("POST", "/api/v2/spans", {},
                            {"X-Scope-OrgID": "t1"},
                            json.dumps(spans).encode())
    assert code == 200 and body["accepted_batches"] == 2

    resp = app.find_trace("t1", bytes.fromhex(tid))
    assert len(resp.trace.batches) == 2
    names = {s.name for b in resp.trace.batches
             for ss in b.scope_spans for s in ss.spans}
    assert names == {"get /", "q"}


def test_otlp_http_receiver(tmp_path):
    from tempo_tpu.api import HTTPApi
    from tempo_tpu.modules import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    api = HTTPApi(app)
    tid = random_trace_id()
    tr = make_trace(tid, seed=3)
    code, body = api.handle("POST", "/v1/traces", {},
                            {"X-Scope-OrgID": "t1"}, tr.SerializeToString())
    assert code == 200
    resp = app.find_trace("t1", tid)
    assert len(resp.trace.batches) == len(tr.batches)


def test_request_queue_sub_request_memory_bound():
    """Complementary to the request cap: queued sub-requests are bounded
    per tenant so frontend memory cannot grow without limit."""
    q = RequestQueue(max_outstanding_per_tenant=10, max_queued_per_tenant=3)
    for i in range(3):
        q.enqueue("t", i)
    with pytest.raises(TooManyRequests):
        q.enqueue("t", 3)


def test_honor_jax_platforms_applies_config(monkeypatch):
    """The env→config bridge every entry point uses: with JAX_PLATFORMS
    set, jax.config must reflect it (the env var alone does not gate a
    registered TPU plugin's backend init)."""
    import jax

    from tempo_tpu.utils.jaxenv import honor_jax_platforms

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    honor_jax_platforms(required=True)
    assert jax.config.jax_platforms == "cpu"
    # unset env: helper must be a no-op, not clear the config
    monkeypatch.delenv("JAX_PLATFORMS")
    honor_jax_platforms()
    assert jax.config.jax_platforms == "cpu"
