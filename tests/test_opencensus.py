"""OpenCensus receiver: OC→OTLP translation + streaming gRPC ingest.

Covers the reference's opencensus receiver role (distributor/receiver
shim factories): a real OC agent `Export` stream over gRPC, node/resource
stickiness across stream messages, attribute/kind/status/annotation
translation, and query-back through the normal read path.
"""

from __future__ import annotations

import os

import grpc
import pytest

from tempo_tpu import tempopb
from tempo_tpu.api.opencensus import OC_TRACE_SERVICE, oc_request_to_batches
from tempo_tpu.tempopb import opencensus_pb2 as ocpb


def _oc_span(tid: bytes, sid: bytes, name="op", service=None, **attrs):
    s = ocpb.OCSpan()
    s.trace_id = tid
    s.span_id = sid
    s.name.value = name
    s.kind = ocpb.OCSpan.SERVER
    s.start_time.seconds = 1_600_000_000
    s.end_time.seconds = 1_600_000_001
    s.end_time.nanos = 500_000_000
    for k, v in attrs.items():
        av = s.attributes.attribute_map[k]
        if isinstance(v, bool):
            av.bool_value = v
        elif isinstance(v, int):
            av.int_value = v
        elif isinstance(v, float):
            av.double_value = v
        else:
            av.string_value.value = str(v)
    return s


def test_translation_basics():
    tid, sid = os.urandom(16), os.urandom(8)
    req = ocpb.OCExportTraceServiceRequest()
    req.node.service_info.name = "checkout"
    req.resource.labels["region"] = "us-east1"
    span = _oc_span(tid, sid, name="charge", http_status=500, retried=True,
                    amount=1.5, route="/pay")
    span.status.code = 2  # gRPC UNKNOWN → error
    span.status.message = "boom"
    span.parent_span_id = b"\x01" * 8
    ann = span.time_events.time_event.add()
    ann.time.seconds = 1_600_000_000
    ann.annotation.description.value = "retrying"
    ann.annotation.attributes.attribute_map["attempt"].int_value = 2
    req.spans.append(span)

    batches = oc_request_to_batches(req)
    assert len(batches) == 1
    rs = batches[0]
    res_attrs = {kv.key: kv.value.string_value for kv in rs.resource.attributes}
    assert res_attrs["service.name"] == "checkout"
    assert res_attrs["region"] == "us-east1"
    s = rs.scope_spans[0].spans[0]
    assert s.trace_id == tid and s.span_id == sid
    assert s.parent_span_id == b"\x01" * 8
    assert s.name == "charge"
    assert s.kind == tempopb.Span.SPAN_KIND_SERVER
    assert s.start_time_unix_nano == 1_600_000_000 * 10**9
    assert s.end_time_unix_nano == 1_600_000_001 * 10**9 + 500_000_000
    attrs = {kv.key: kv.value for kv in s.attributes}
    assert attrs["http_status"].int_value == 500
    assert attrs["retried"].bool_value is True
    assert attrs["amount"].double_value == 1.5
    assert attrs["route"].string_value == "/pay"
    assert s.status.code == tempopb.Status.STATUS_CODE_ERROR
    assert s.status.message == "boom"
    assert s.events[0].name == "retrying"
    assert s.events[0].attributes[0].value.int_value == 2


def test_per_span_resource_override_groups():
    req = ocpb.OCExportTraceServiceRequest()
    req.node.service_info.name = "svc-a"
    sp1 = _oc_span(os.urandom(16), os.urandom(8))
    sp2 = _oc_span(os.urandom(16), os.urandom(8))
    sp2.resource.labels["service.name"] = "svc-b"
    req.spans.extend([sp1, sp2])
    batches = oc_request_to_batches(req)
    names = sorted(
        next(kv.value.string_value for kv in b.resource.attributes
             if kv.key == "service.name")
        for b in batches
    )
    assert names == ["svc-a", "svc-b"]


def test_node_vs_label_service_name_no_duplicate():
    req = ocpb.OCExportTraceServiceRequest()
    req.node.service_info.name = "from-node"
    req.resource.labels["service.name"] = "from-label"
    req.spans.append(_oc_span(os.urandom(16), os.urandom(8)))
    (rs,) = oc_request_to_batches(req)
    svc_attrs = [kv.value.string_value for kv in rs.resource.attributes
                 if kv.key == "service.name"]
    assert svc_attrs == ["from-label"]  # exactly one; explicit label wins


def test_short_trace_id_padded():
    req = ocpb.OCExportTraceServiceRequest()
    req.spans.append(_oc_span(b"\x05" * 8, os.urandom(8)))
    (rs,) = oc_request_to_batches(req)
    assert len(rs.scope_spans[0].spans[0].trace_id) == 16


def test_streaming_export_node_stickiness_e2e(tmp_path):
    """Real gRPC bidi stream: node only on the first message; spans on
    later messages inherit it. Query back via the app."""
    from tempo_tpu.api.grpc_service import make_grpc_server
    from tempo_tpu.modules import App, AppConfig

    app = App(AppConfig(wal_dir=str(tmp_path / "wal")))
    server = make_grpc_server(app, "127.0.0.1:0")
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        rpc = channel.stream_stream(
            f"/{OC_TRACE_SERVICE}/Export",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=ocpb.OCExportTraceServiceResponse.FromString,
        )
        tid1, tid2 = os.urandom(16), os.urandom(16)

        def gen():
            first = ocpb.OCExportTraceServiceRequest()
            first.node.service_info.name = "stream-svc"
            first.spans.append(_oc_span(tid1, os.urandom(8), name="one"))
            yield first
            second = ocpb.OCExportTraceServiceRequest()  # no node
            second.spans.append(_oc_span(tid2, os.urandom(8), name="two"))
            yield second

        responses = list(rpc(gen(), metadata=(("x-scope-orgid", "oc-t"),)))
        assert len(responses) == 2

        for tid, name in ((tid1, "one"), (tid2, "two")):
            found = app.find_trace("oc-t", tid)
            assert found.trace.batches, name
            rs = found.trace.batches[0]
            svc = next(kv.value.string_value for kv in rs.resource.attributes
                       if kv.key == "service.name")
            assert svc == "stream-svc"
        channel.close()
    finally:
        server.stop(0)
        app.shutdown()


def test_config_stream_echoes():
    from tempo_tpu.api.grpc_service import make_grpc_server
    from tempo_tpu.modules import App, AppConfig
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        app = App(AppConfig(wal_dir=td + "/wal"))
        server = make_grpc_server(app, "127.0.0.1:0")
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        try:
            channel = grpc.insecure_channel(f"127.0.0.1:{port}")
            rpc = channel.stream_stream(
                f"/{OC_TRACE_SERVICE}/Config",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=ocpb.OCUpdatedLibraryConfig.FromString,
            )
            out = list(rpc(iter([ocpb.OCCurrentLibraryConfig()])))
            assert len(out) == 1
            channel.close()
        finally:
            server.stop(0)
            app.shutdown()


def test_real_wire_byte_literal_roundtrip():
    """Decode a request byte string hand-assembled from the REAL
    opencensus-proto field spec (trace.pb.go: parent=3 name=4 start=5
    end=6 attributes=7 time_events=9 status=11 kind=14 tracestate=15
    resource=16) — NOT via our own pb2 — so a same-wrong-numbering bug
    in the schema cannot self-consistently pass."""

    def tag(field, wire):
        out, key = b"", (field << 3) | wire
        while True:
            b, key = key & 0x7F, key >> 7
            out += bytes([b | (0x80 if key else 0)])
            if not key:
                return out

    def varint(v):
        out = b""
        while True:
            b, v = v & 0x7F, v >> 7
            out += bytes([b | (0x80 if v else 0)])
            if not v:
                return out

    def ld(field, payload):  # length-delimited
        return tag(field, 2) + varint(len(payload)) + payload

    tid, sid, psid = bytes(range(16)), b"\x01" * 8, b"\x02" * 8
    ts_start = tag(1, 0) + varint(1_700_000_000)           # Timestamp.seconds=1
    ts_end = tag(1, 0) + varint(1_700_000_001) + tag(2, 0) + varint(250)
    trunc_name = ld(1, b"real-oc-op")                      # TruncatableString.value
    # Attributes.attribute_map entry: key="env", value=AttributeValue{string}
    attr_val = ld(1, ld(1, b"prod"))                       # string_value.value
    attr_entry = ld(1, b"env") + ld(2, attr_val)
    attributes = ld(1, attr_entry)                         # map entry is field 1
    status = tag(1, 0) + varint(2) + ld(2, b"boom")        # code=2, message
    tracestate = ld(1, ld(1, b"k") + ld(2, b"v"))          # Tracestate.entries
    span = (
        ld(1, tid) + ld(2, sid) + ld(3, psid)              # ids, parent=3
        + ld(4, trunc_name)                                # name=4
        + ld(5, ts_start) + ld(6, ts_end)                  # start=5 end=6
        + ld(7, attributes)                                # attributes=7
        + ld(8, b"\x00")                                   # stack_trace=8 (ignored)
        + ld(11, status)                                   # status=11
        + tag(12, 2) + varint(2) + tag(1, 0) + varint(1)   # same_process (unknown)
        + tag(14, 0) + varint(1)                           # kind=14 SERVER
        + ld(15, tracestate)                               # tracestate=15
    )
    node = ld(3, ld(1, b"real-svc"))                       # Node.service_info.name
    req_bytes = ld(1, node) + ld(2, span)                  # request: node=1 spans=2

    req = ocpb.OCExportTraceServiceRequest.FromString(req_bytes)
    batches = oc_request_to_batches(req)
    assert len(batches) == 1
    s = batches[0].scope_spans[0].spans[0]
    assert s.trace_id == tid and s.span_id == sid and s.parent_span_id == psid
    assert s.name == "real-oc-op"
    assert s.kind == tempopb.Span.SPAN_KIND_SERVER
    assert s.start_time_unix_nano == 1_700_000_000 * 10**9
    assert s.end_time_unix_nano == 1_700_000_001 * 10**9 + 250
    assert s.attributes[0].key == "env"
    assert s.attributes[0].value.string_value == "prod"
    assert s.status.code == tempopb.Status.STATUS_CODE_ERROR
    assert s.status.message == "boom"
    assert s.trace_state == "k=v"
    svc = next(kv.value.string_value for kv in batches[0].resource.attributes
               if kv.key == "service.name")
    assert svc == "real-svc"
