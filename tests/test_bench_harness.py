"""Wedge-proofing of the driver bench (VERDICT r4 #1).

BENCH_r04 recorded value=0 because one wedged device op lost every
completed phase. The harness now runs each phase in its own subprocess
with its own deadline and checkpoints results as they land; these tests
prove a hung phase loses only itself, and that the preflight probe
degrades to an explicit CPU run instead of silence.

All children run with JAX_PLATFORMS=cpu and tiny corpora so the suite
stays fast; the hang is simulated with the documented BENCH_TEST_HANG_PHASE
hook (a hang is a hang — the orchestrator cannot tell a sleeping child
from one wedged inside the accelerator tunnel's C handshake).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

TINY = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_ENTRIES": "8192",
    "BENCH_ITERS": "2",
    "BENCH_BLOCKS": "2",
    "BENCH_CARDINALITY_FULL": "0",
    "BENCH_SCALE_BLOCKS": "0",
    "BENCH_LARGE_BLOCKS": "0",
}


def run_bench(tmp_path, extra_env, timeout=240):
    env = dict(os.environ)
    env.update(TINY)
    env["BENCH_CKPT_DIR"] = str(tmp_path / "ckpt")
    env.update(extra_env)
    p = subprocess.run(
        [sys.executable, BENCH], env=env, cwd=REPO, timeout=timeout,
        capture_output=True, text=True)
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line emitted\nstderr: {p.stderr[-2000:]}"
    return p.returncode, json.loads(lines[-1])


@pytest.mark.slow
def test_hung_phase_loses_only_itself(tmp_path):
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single,multiblock,serving",
        "BENCH_TEST_HANG_PHASE": "multiblock",
        "BENCH_TIMEOUT_MULTIBLOCK": "4",
    })
    cfg = doc["detail"]["configs"]
    # the phases before and after the wedge kept their numbers
    assert doc["value"] > 0
    assert doc["vs_baseline"] > 0
    assert cfg["serving_path"]["p50_ms"] > 0
    # the wedged phase is an explicit error, not silence
    assert "timed out" in cfg["multiblock"]["error"]
    assert rc == 0  # headline survived → success exit


@pytest.mark.slow
def test_hung_headline_still_reports_other_phases(tmp_path):
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single,multiblock",
        "BENCH_TEST_HANG_PHASE": "single",
        "BENCH_TIMEOUT_SINGLE": "4",
    })
    assert doc["value"] == 0
    assert "timed out" in doc["error"]
    assert doc["detail"]["configs"]["multiblock"]["traces_per_sec"] > 0
    assert rc == 3  # headline lost → failure exit, but numbers present


@pytest.mark.slow
def test_preflight_probe_failure_is_explicit(tmp_path):
    # hang the probe itself and forbid the CPU fallback: the emitted line
    # must say the device never answered, within the probe deadlines
    rc, doc = run_bench(tmp_path, {
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_CPU_FALLBACK": "0",
        "BENCH_WATCHDOG_S": "30",
    })
    assert rc == 3
    assert doc["value"] == 0
    assert "preflight" in doc["error"] or "probe" in doc["error"]


@pytest.mark.slow
def test_cpu_fallback_is_marked_degraded(tmp_path):
    # probes 1-3 wedge (counted hang hook); the 4th — the CPU fallback —
    # answers. The run must complete with CPU numbers in detail only,
    # headline value=0 (the TPU metric contract), and rc=4.
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single",
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_TEST_HANG_TIMES": "3",
        "BENCH_TIMEOUT_PROBE": "4",
    }, timeout=300)
    assert rc == 4
    assert doc["value"] == 0 and doc["vs_baseline"] == 0
    assert doc["degraded"].startswith("cpu-fallback")
    assert "CPU-fallback" in doc["error"]
    # the degraded run still recorded real (CPU) numbers in detail
    cfg = doc["detail"]["configs"]
    assert cfg["duration_only_traces_per_sec"] > 0


@pytest.mark.slow
def test_checkpoints_land_per_phase(tmp_path):
    rc, doc = run_bench(tmp_path, {"BENCH_PHASES": "single"})
    assert rc == 0
    ckpt = tmp_path / "ckpt"
    single = json.loads((ckpt / "single.json").read_text())
    assert single["data"]["tpu_traces_per_sec"] > 0
    assert single["_fp"]["jax_platforms"] == "cpu"  # resume fingerprint
    assert json.loads((ckpt / "final.json").read_text())["value"] > 0
