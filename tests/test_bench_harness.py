"""Wedge-proofing of the driver bench (VERDICT r4 #1).

BENCH_r04 recorded value=0 because one wedged device op lost every
completed phase. The harness now runs each phase in its own subprocess
with its own deadline and checkpoints results as they land; these tests
prove a hung phase loses only itself, and that the preflight probe
degrades to an explicit CPU run instead of silence.

All children run with JAX_PLATFORMS=cpu and tiny corpora so the suite
stays fast; the hang is simulated with the documented BENCH_TEST_HANG_PHASE
hook (a hang is a hang — the orchestrator cannot tell a sleeping child
from one wedged inside the accelerator tunnel's C handshake).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

TINY = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_ENTRIES": "8192",
    "BENCH_ITERS": "2",
    "BENCH_BLOCKS": "2",
    "BENCH_CARDINALITY_FULL": "0",
    "BENCH_SCALE_BLOCKS": "0",
    "BENCH_LARGE_BLOCKS": "0",
}


def run_bench(tmp_path, extra_env, timeout=240):
    env = dict(os.environ)
    env.update(TINY)
    env["BENCH_CKPT_DIR"] = str(tmp_path / "ckpt")
    env.update(extra_env)
    p = subprocess.run(
        [sys.executable, BENCH], env=env, cwd=REPO, timeout=timeout,
        capture_output=True, text=True)
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.startswith("{")]
    assert lines, f"no JSON line emitted\nstderr: {p.stderr[-2000:]}"
    return p.returncode, json.loads(lines[-1])


@pytest.mark.slow
def test_hung_phase_loses_only_itself(tmp_path):
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single,multiblock,serving",
        "BENCH_TEST_HANG_PHASE": "multiblock",
        "BENCH_TIMEOUT_MULTIBLOCK": "4",
    })
    cfg = doc["detail"]["configs"]
    # the phases before and after the wedge kept their numbers
    assert doc["value"] > 0
    assert doc["vs_baseline"] > 0
    assert cfg["serving_path"]["p50_ms"] > 0
    # the wedged phase is an explicit error, not silence
    assert "timed out" in cfg["multiblock"]["error"]
    assert rc == 0  # headline survived → success exit


@pytest.mark.slow
def test_hung_headline_still_reports_other_phases(tmp_path):
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single,multiblock",
        "BENCH_TEST_HANG_PHASE": "single",
        "BENCH_TIMEOUT_SINGLE": "4",
    })
    assert doc["value"] == 0
    assert "timed out" in doc["error"]
    assert doc["detail"]["configs"]["multiblock"]["traces_per_sec"] > 0
    assert rc == 3  # headline lost → failure exit, but numbers present


@pytest.mark.slow
def test_preflight_probe_failure_is_explicit(tmp_path):
    # hang the probe itself and forbid the CPU fallback: the emitted line
    # must say the device never answered, within the probe deadlines
    rc, doc = run_bench(tmp_path, {
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_CPU_FALLBACK": "0",
        "BENCH_WATCHDOG_S": "30",
    })
    assert rc == 3
    assert doc["value"] == 0
    assert "preflight" in doc["error"] or "probe" in doc["error"]


@pytest.mark.slow
def test_cpu_fallback_after_first_wedge_by_default(tmp_path):
    # default BENCH_PREFLIGHT_ATTEMPTS=1: ONE wedged probe (counted hang
    # hook) and the very next attempt is the CPU fallback — r05 burned
    # 3x60s before falling back. The run must complete with CPU numbers
    # in detail only, headline value=0 (the TPU metric contract), rc=4.
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single",
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_TEST_HANG_TIMES": "1",
        "BENCH_TIMEOUT_PROBE": "4",
    }, timeout=300)
    assert rc == 4
    assert doc["value"] == 0 and doc["vs_baseline"] == 0
    assert doc["degraded"].startswith("cpu-fallback")
    assert "CPU-fallback" in doc["error"]
    # the degraded run still recorded real (CPU) numbers in detail
    cfg = doc["detail"]["configs"]
    assert cfg["duration_only_traces_per_sec"] > 0


@pytest.mark.slow
def test_preflight_attempts_env_configurable(tmp_path):
    # BENCH_PREFLIGHT_ATTEMPTS=3 restores the retry-happy behavior:
    # probes 1-3 wedge, the 4th (CPU fallback) answers
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single",
        "BENCH_PREFLIGHT_ATTEMPTS": "3",
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_TEST_HANG_TIMES": "3",
        "BENCH_TIMEOUT_PROBE": "4",
    }, timeout=300)
    assert rc == 4
    assert doc["degraded"].startswith("cpu-fallback")
    assert "3x" in doc["degraded"]


@pytest.mark.slow
def test_degraded_run_records_reduced_scale_point(tmp_path):
    # a degraded (CPU-fallback) round must still record scale-phase
    # numbers — at reduced size, flagged as such — instead of skipping
    # them (r05 lost both scale series to one wedged tunnel)
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single,scale_10k",
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_TEST_HANG_TIMES": "1",
        "BENCH_TIMEOUT_PROBE": "4",
        "BENCH_DEGRADED_SCALE_BLOCKS": "4",
    }, timeout=420)
    assert rc == 4
    scale = doc["detail"]["configs"]["scale_10k"]
    assert "error" not in scale, scale
    assert scale["degraded_reduced_size"] is True
    assert scale["blocks"] == 4  # the reduced corpus, not the 10K config
    assert scale["p50_ms"] > 0


@pytest.mark.slow
def test_degraded_scale_opt_out_still_skips(tmp_path):
    rc, doc = run_bench(tmp_path, {
        "BENCH_PHASES": "single,scale_10k",
        "BENCH_TEST_HANG_PHASE": "probe",
        "BENCH_TEST_HANG_TIMES": "1",
        "BENCH_TIMEOUT_PROBE": "4",
        "BENCH_DEGRADED_SCALE": "0",
    }, timeout=300)
    assert rc == 4
    scale = doc["detail"]["configs"]["scale_10k"]
    assert "skipped: degraded" in scale["error"]


def test_assemble_surfaces_dict_probe_trajectory():
    """The host-prefilter vs device-probe timings of BOTH high-
    cardinality phases must land at detail.dict_probe in the final doc
    (the round-over-round trajectory for the PR4 optimization) — and a
    wedged phase must drop out instead of contributing nulls."""
    sys.path.insert(0, REPO)
    import bench

    hc = {"distinct_values": 1_000_000, "traces_per_sec": 100,
          "dict_prefilter_ms": 38.0, "matches": 5,
          "device_probe_ms": 2.5, "device_probe_stage_ms": 40.0,
          "device_probe_rate": 120}
    full = dict(hc, distinct_values=10_000_000, dict_prefilter_ms=312.0)
    doc = bench._assemble({"high_cardinality": hc,
                           "high_cardinality_full": full})
    traj = doc["detail"]["dict_probe"]
    assert traj["high_cardinality"]["dict_prefilter_ms"] == 38.0
    assert traj["high_cardinality"]["device_probe_ms"] == 2.5
    assert traj["high_cardinality_full"]["distinct_values"] == 10_000_000
    assert traj["high_cardinality_full"]["device_probe_stage_ms"] == 40.0

    doc = bench._assemble({"high_cardinality": hc,
                           "high_cardinality_full": {"error": "wedged"}})
    assert list(doc["detail"]["dict_probe"]) == ["high_cardinality"]
    assert bench._assemble({}).get("detail", {}).get("dict_probe") is None


@pytest.mark.slow
def test_checkpoints_land_per_phase(tmp_path):
    rc, doc = run_bench(tmp_path, {"BENCH_PHASES": "single"})
    assert rc == 0
    ckpt = tmp_path / "ckpt"
    single = json.loads((ckpt / "single.json").read_text())
    assert single["data"]["tpu_traces_per_sec"] > 0
    assert single["_fp"]["jax_platforms"] == "cpu"  # resume fingerprint
    assert json.loads((ckpt / "final.json").read_text())["value"] > 0
