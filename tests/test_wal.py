import os

from tempo_tpu.backend import BlockMeta, LocalBackend
from tempo_tpu.encoding.v2 import StreamingBlock, BackendBlock
from tempo_tpu.model import segment_codec_for, codec_for
from tempo_tpu.utils.test_data import make_trace
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.wal import WAL, parse_wal_filename


def _seg(tid, seed, start, end):
    sc = segment_codec_for("v2")
    return sc.prepare_for_write(make_trace(tid, seed=seed, batches=1), start, end)


def test_wal_append_find_iterate(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = sorted(random_trace_id() for _ in range(10))
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100 + i, 200 + i), 100 + i, 200 + i)
    # duplicate segment for tids[0] combines on read
    blk.append(tids[0], _seg(tids[0], 99, 50, 60), 50, 60)

    assert blk.meta.total_objects == 11
    assert blk.meta.start_time == 50 and blk.meta.end_time == 209

    obj = blk.find(tids[0])
    c = codec_for("v2")
    assert c.fast_range(obj) == (50, 200)
    assert blk.find(b"\x00" * 16) is None

    ids = [i for i, _ in blk.iterator()]
    assert ids == tids  # sorted, deduped
    blk.close()


def test_wal_replay(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = [random_trace_id() for _ in range(5)]
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 10, 20), 10, 20)
    blk.close()

    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert removed == []
    assert len(blocks) == 1
    rb = blocks[0]
    assert rb.meta.tenant_id == "t1"
    assert rb.meta.total_objects == 5
    assert rb.meta.block_id == blk.meta.block_id
    for i, tid in enumerate(tids):
        assert rb.find(tid) is not None
    rb.close()


def test_wal_replay_truncated_tail(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = [random_trace_id() for _ in range(3)]
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 10, 20), 10, 20)
    blk.close()

    # simulate crash mid-append: chop 3 bytes off the tail
    with open(blk.path, "r+b") as f:
        f.truncate(os.path.getsize(blk.path) - 3)

    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert len(blocks) == 1
    rb = blocks[0]
    assert rb.meta.total_objects == 2  # torn last record discarded
    # appends continue cleanly after replay truncation
    extra = random_trace_id()
    rb.append(extra, _seg(extra, 9, 10, 20), 10, 20)
    assert rb.find(extra) is not None
    rb.close()


def test_wal_replay_removes_garbage(tmp_wal_dir):
    with open(os.path.join(tmp_wal_dir, "not-a-wal-file"), "wb") as f:
        f.write(b"junk")
    with open(os.path.join(tmp_wal_dir, "a+b+vT1+none+v2"), "wb") as f:
        pass  # zero length
    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert blocks == []
    assert sorted(removed) == ["a+b+vT1+none+v2", "not-a-wal-file"]
    assert os.listdir(tmp_wal_dir) == []


def test_parse_wal_filename():
    m = parse_wal_filename("abc123+tenant-1+vT1+none+v2")
    assert m.block_id == "abc123"
    assert m.tenant_id == "tenant-1"
    assert m.data_encoding == "v2"


def test_wal_to_complete_block(tmp_wal_dir, tmp_backend_dir):
    """The flush path: WAL iterator → StreamingBlock → BackendBlock find."""
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = [random_trace_id() for _ in range(20)]
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100, 200), 100, 200)

    be = LocalBackend(tmp_backend_dir)
    meta = BlockMeta(tenant_id="t1", block_id=blk.meta.block_id, encoding="zstd")
    sb = StreamingBlock(meta, page_size=1024)
    c = codec_for("v2")
    for oid, obj in blk.iterator():
        s, e = c.fast_range(obj)
        sb.add_object(oid, obj, s, e)
    out = sb.complete(be)
    assert out.total_objects == 20

    bb = BackendBlock(be, out)
    for tid in tids:
        obj = bb.find_by_id(tid)
        assert obj is not None
        tr = c.prepare_for_read(obj)
        assert len(tr.batches) == 1
    blk.clear()
    assert not os.path.exists(blk.path)
