import os

from tempo_tpu.backend import BlockMeta, LocalBackend
from tempo_tpu.encoding.v2 import StreamingBlock, BackendBlock
from tempo_tpu.model import segment_codec_for, codec_for
from tempo_tpu.utils.test_data import make_trace
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.wal import WAL, parse_wal_filename


def _seg(tid, seed, start, end):
    sc = segment_codec_for("v2")
    return sc.prepare_for_write(make_trace(tid, seed=seed, batches=1), start, end)


def test_wal_append_find_iterate(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = sorted(random_trace_id() for _ in range(10))
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100 + i, 200 + i), 100 + i, 200 + i)
    # duplicate segment for tids[0] combines on read
    blk.append(tids[0], _seg(tids[0], 99, 50, 60), 50, 60)

    assert blk.meta.total_objects == 11
    assert blk.meta.start_time == 50 and blk.meta.end_time == 209

    obj = blk.find(tids[0])
    c = codec_for("v2")
    assert c.fast_range(obj) == (50, 200)
    assert blk.find(b"\x00" * 16) is None

    ids = [i for i, _ in blk.iterator()]
    assert ids == tids  # sorted, deduped
    blk.close()


def test_wal_replay(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = [random_trace_id() for _ in range(5)]
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 10, 20), 10, 20)
    blk.close()

    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert removed == []
    assert len(blocks) == 1
    rb = blocks[0]
    assert rb.meta.tenant_id == "t1"
    assert rb.meta.total_objects == 5
    assert rb.meta.block_id == blk.meta.block_id
    for i, tid in enumerate(tids):
        assert rb.find(tid) is not None
    rb.close()


def test_wal_replay_truncated_tail(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = [random_trace_id() for _ in range(3)]
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 10, 20), 10, 20)
    blk.close()

    # simulate crash mid-append: chop 3 bytes off the tail
    with open(blk.path, "r+b") as f:
        f.truncate(os.path.getsize(blk.path) - 3)

    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert len(blocks) == 1
    rb = blocks[0]
    assert rb.meta.total_objects == 2  # torn last record discarded
    # appends continue cleanly after replay truncation
    extra = random_trace_id()
    rb.append(extra, _seg(extra, 9, 10, 20), 10, 20)
    assert rb.find(extra) is not None
    rb.close()


def test_wal_replay_removes_garbage(tmp_wal_dir):
    with open(os.path.join(tmp_wal_dir, "not-a-wal-file"), "wb") as f:
        f.write(b"junk")
    with open(os.path.join(tmp_wal_dir, "a+b+vT1+none+v2"), "wb") as f:
        pass  # zero length
    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert blocks == []
    assert sorted(removed) == ["a+b+vT1+none+v2", "not-a-wal-file"]
    assert os.listdir(tmp_wal_dir) == []


def test_parse_wal_filename():
    m = parse_wal_filename("abc123+tenant-1+vT1+none+v2")
    assert m.block_id == "abc123"
    assert m.tenant_id == "tenant-1"
    assert m.data_encoding == "v2"


def test_wal_to_complete_block(tmp_wal_dir, tmp_backend_dir):
    """The flush path: WAL iterator → StreamingBlock → BackendBlock find."""
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = [random_trace_id() for _ in range(20)]
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100, 200), 100, 200)

    be = LocalBackend(tmp_backend_dir)
    # zstd when the codec exists here; the test exercises the flush
    # machinery, not the codec, so degrade rather than fail on hosts
    # without the native lib / zstandard wheel
    from tempo_tpu.encoding.v2.compression import best_available

    meta = BlockMeta(tenant_id="t1", block_id=blk.meta.block_id,
                     encoding=best_available("zstd"))
    sb = StreamingBlock(meta, page_size=1024)
    c = codec_for("v2")
    for oid, obj in blk.iterator():
        s, e = c.fast_range(obj)
        sb.add_object(oid, obj, s, e)
    out = sb.complete(be)
    assert out.total_objects == 20

    bb = BackendBlock(be, out)
    for tid in tids:
        obj = bb.find_by_id(tid)
        assert obj is not None
        tr = c.prepare_for_read(obj)
        assert len(tr.batches) == 1
    blk.clear()
    assert not os.path.exists(blk.path)


# ---------------------------------------------------------------------------
# WAL record compression (reference wal.go:54-97 snappy v2 pages)


def test_wal_default_encoding_compresses_and_replays(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    assert wal.encoding in ("snappy", "zlib")  # auto-resolved, never none
    blk = wal.new_block("t1")
    tids = sorted(random_trace_id() for _ in range(8))
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100 + i, 200 + i), 100 + i, 200 + i)
    # encoding travels in the filename -> replay is self-describing
    assert parse_wal_filename(os.path.basename(blk.path)).encoding == wal.encoding
    assert blk.find(tids[3]) is not None
    blk.close()

    blocks, removed = WAL(tmp_wal_dir).replay_all()
    assert not removed and len(blocks) == 1
    rb = blocks[0]
    assert rb.meta.total_objects == 8
    assert rb.meta.start_time == 100 and rb.meta.end_time == 207
    assert [i for i, _ in rb.iterator()] == tids
    c = codec_for("v2")
    assert c.fast_range(rb.find(tids[0])) == (100, 200)
    rb.close()


def test_wal_compression_shrinks_redundant_segments(tmp_wal_dir):
    """The point of the codec: repetitive span payloads must land on disk
    smaller than raw (reference's rationale for snappy WAL pages)."""
    raw = WAL(tmp_wal_dir + "-raw", encoding="none")
    comp = WAL(tmp_wal_dir + "-comp")
    braw, bcomp = raw.new_block("t"), comp.new_block("t")
    # fixed id: a random one occasionally lands a payload whose single
    # small record compresses right at the 0.9 assertion line (flake)
    tid = bytes(range(16))
    seg = _seg(tid, 1, 100, 200) * 1  # one real segment
    for b in (braw, bcomp):
        for _ in range(50):
            b.append(tid, seg, 100, 200)
    assert bcomp.data_length < braw.data_length * 0.9
    braw.close(); bcomp.close()


def test_wal_uncompressed_legacy_files_still_replay(tmp_wal_dir):
    """An upgrade must replay pre-compression WAL files: encoding "none"
    parsed from the filename wins over the WAL's new default."""
    legacy = WAL(tmp_wal_dir, encoding="none")
    blk = legacy.new_block("t1")
    tid = random_trace_id()
    blk.append(tid, _seg(tid, 5, 10, 20), 10, 20)
    blk.close()

    blocks, removed = WAL(tmp_wal_dir).replay_all()  # default: compressed
    assert not removed and len(blocks) == 1
    assert blocks[0].find(tid) is not None
    blocks[0].close()


def test_wal_compressed_truncated_tail(tmp_wal_dir):
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = sorted(random_trace_id() for _ in range(5))
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100, 200), 100, 200)
    blk.close()
    # tear mid-record
    with open(blk.path, "r+b") as f:
        f.truncate(os.path.getsize(blk.path) - 7)

    blocks, _ = WAL(tmp_wal_dir).replay_all()
    rb = blocks[0]
    assert rb.meta.total_objects == 4  # torn record dropped
    assert all(rb.find(t) is not None for t in tids[:4])
    # appends after replay continue cleanly on the truncated file
    rb.append(tids[4], _seg(tids[4], 9, 100, 200), 100, 200)
    assert rb.find(tids[4]) is not None
    rb.close()


def test_s2_encoding_accepted():
    from tempo_tpu.encoding.v2.compression import compress, decompress
    from tempo_tpu.ops import native

    if not native.available():
        import pytest
        pytest.skip("s2/snappy requires the native runtime")
    data = b"tempo" * 1000
    assert decompress(compress(data, "s2"), "s2") == data
    assert len(compress(data, "s2")) < len(data)


def test_wal_corrupt_compressed_record_dropped_at_replay(tmp_wal_dir):
    """A bit-flipped compressed payload must be DROPPED at replay (like
    the reference's corrupt-WAL cleanup) — indexing it would wedge block
    completion in an infinite retry and 500 every find()."""
    wal = WAL(tmp_wal_dir)
    blk = wal.new_block("t1")
    tids = sorted(random_trace_id() for _ in range(3))
    for i, tid in enumerate(tids):
        blk.append(tid, _seg(tid, i, 100, 200), 100, 200)
    blk.close()
    # flip bytes INSIDE the middle record's compressed payload (frame
    # intact: length prefix + 16-byte id untouched)
    e1 = blk._entries[1]
    with open(blk.path, "r+b") as f:
        f.seek(e1.offset + 8 + 16 + 4)
        f.write(b"\xff\xff\xff\xff")

    blocks, _ = WAL(tmp_wal_dir).replay_all()
    rb = blocks[0]
    assert rb.corrupt_records == 1
    assert rb.meta.total_objects == 2
    # intact records before AND after the corrupt one survive
    assert rb.find(tids[0]) is not None
    assert rb.find(tids[2]) is not None
    assert rb.find(tids[1]) is None  # dropped, not raising
    # completion-path iterator works (no infinite flush retry)
    assert [i for i, _ in rb.iterator()] == [tids[0], tids[2]]
    rb.close()


def test_config_empty_sections_use_defaults():
    from tempo_tpu.cli.config import load_config

    cfg, _ = load_config(text="frontend:\nquerier:\nstorage:\ningester:\n")
    assert cfg.frontend.retries == 2
    assert cfg.frontend_worker_parallelism == 2
