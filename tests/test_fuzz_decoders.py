"""Hostile-bytes fuzz over every wire decoder: random garbage and
mutations of valid payloads must raise the decoder's DOCUMENTED error
types (or return gracefully) — never hang, never corrupt state, never
escape with an undeclared exception class that would 500 an ingest
endpoint that promises 400s for malformed bodies."""

from __future__ import annotations

import json
import random

import pytest

from tempo_tpu import tempopb
from tempo_tpu.utils.ids import random_trace_id
from tempo_tpu.utils.test_data import make_trace


def _mutations(valid: bytes, rng, n=40):
    """Truncations, bit flips, and splices of a valid payload."""
    out = []
    for _ in range(n):
        b = bytearray(valid)
        op = rng.randrange(3)
        if op == 0 and len(b) > 1:
            b = b[: rng.randrange(1, len(b))]
        elif op == 1 and b:
            for _ in range(rng.randint(1, 8)):
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        else:
            i = rng.randrange(len(b) + 1)
            b[i:i] = rng.randbytes(rng.randint(1, 64))
        out.append(bytes(b))
    out += [b"", rng.randbytes(3), rng.randbytes(200)]
    return out


def test_fuzz_object_file_unmarshal():
    from tempo_tpu.encoding.v2.objects import marshal_object, unmarshal_objects

    rng = random.Random(9)
    valid = b"".join(marshal_object(random_trace_id(), rng.randbytes(50))
                     for _ in range(5))
    for payload in _mutations(valid, rng):
        # tolerant mode: always terminates, yields a (possibly empty)
        # prefix, never raises
        list(unmarshal_objects(payload, tolerate_truncation=True))
        # strict mode may raise, but only ValueError
        try:
            list(unmarshal_objects(payload))
        except ValueError:
            pass


def test_fuzz_kafka_record_batches():
    from tempo_tpu.api.kafka import (
        CorruptBatchError, decode_record_batches, encode_record_batch,
    )

    rng = random.Random(10)
    valid = encode_record_batch(
        [(None, b"value-%d" % i) for i in range(4)], base_offset=7)
    for payload in _mutations(valid, rng):
        try:
            decode_record_batches(payload)
        except CorruptBatchError:
            pass  # the one documented failure class


def test_fuzz_jaeger_thrift():
    from tempo_tpu.api.jaeger import jaeger_thrift_http_to_batches
    from tempo_tpu.api.thriftproto import ThriftError

    rng = random.Random(11)
    for payload in _mutations(rng.randbytes(120), rng, n=25):
        try:
            jaeger_thrift_http_to_batches(payload)
        except (ThriftError, KeyError, TypeError, AttributeError,
                ValueError, EOFError):
            pass  # api/http treats these as 400s


def test_fuzz_zipkin_json():
    from tempo_tpu.api.receivers import zipkin_json_to_batches

    rng = random.Random(12)
    valid = json.dumps([{
        "traceId": random_trace_id().hex(), "id": "1" * 16, "name": "op",
        "timestamp": 1, "duration": 2,
        "localEndpoint": {"serviceName": "svc"},
    }]).encode()
    for payload in _mutations(valid, rng, n=25):
        try:
            zipkin_json_to_batches(payload)
        except (json.JSONDecodeError, KeyError, TypeError, AttributeError,
                ValueError):
            pass  # 400 classes per api/http._ingest


def test_fuzz_otlp_protobuf():
    from google.protobuf.message import DecodeError

    from tempo_tpu.api.receivers import otlp_http_to_batches

    rng = random.Random(13)
    valid = make_trace(random_trace_id(), seed=1).SerializeToString()
    for payload in _mutations(valid, rng, n=25):
        try:
            otlp_http_to_batches(payload)
        except (DecodeError, ValueError):
            pass


def test_fuzz_search_data_decode():
    from tempo_tpu.search.data import decode_search_data, encode_search_data
    from tempo_tpu.search import extract_search_data

    rng = random.Random(14)
    tid = random_trace_id()
    valid = encode_search_data(extract_search_data(tid, make_trace(tid, seed=2)))
    for payload in _mutations(valid, rng, n=25):
        try:
            decode_search_data(payload, tid)
        except Exception as e:  # noqa: BLE001 — classify below
            # the live-trace fold catches Exception; what matters is the
            # class is a sane decode error, not e.g. MemoryError from a
            # hostile length prefix
            assert not isinstance(e, MemoryError), type(e)


def test_fuzz_tenant_index():
    from tempo_tpu.backend.types import BlockMeta, TenantIndex

    rng = random.Random(15)
    valid = TenantIndex(created_at=1,
                        metas=[BlockMeta(tenant_id="t")]).to_bytes()
    for payload in _mutations(valid, rng, n=25):
        try:
            TenantIndex.from_bytes(payload)
        except (ValueError, OSError, EOFError, KeyError, TypeError,
                AttributeError):
            pass  # poller treats any of these as index-missing


def test_kafka_negative_batch_length_cannot_hang():
    """Fuzz-found: a negative batchLen rewound the parse cursor and spun
    forever. Decode must terminate (bounded) with the documented error."""
    import struct
    import threading

    from tempo_tpu.api.kafka import CorruptBatchError, decode_record_batches

    payload = b"\x00" * 8 + struct.pack(">i", -12) + b"\x00" * 49
    result = {}

    def run():
        try:
            result["out"] = decode_record_batches(payload)
        except CorruptBatchError as e:
            result["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=5)
    assert not t.is_alive(), "decode_record_batches hung on negative length"
    assert "err" in result  # documented error, not garbage output


def test_kafka_torn_batch_never_delivers_partial_records():
    """A batch whose record section is corrupt must not leak half-decoded
    records (they carry mis-parsed offsets and values)."""
    from tempo_tpu.api.kafka import (
        CorruptBatchError, decode_record_batches, encode_record_batch,
    )

    good = encode_record_batch([(None, b"a"), (None, b"b")], base_offset=10)
    bad = bytearray(encode_record_batch(
        [(None, b"v0"), (None, b"v1"), (None, b"v2")], base_offset=100))
    # corrupt the records section but FIX the CRC so only structure fails
    # (simulates producer-side corruption under a recomputed checksum):
    # easiest equivalent — truncate mid-records at the wire level
    torn = bytes(good) + bytes(bad[: len(bad) - 5])
    out = decode_record_batches(torn)
    offsets = [o for o, _, _ in out]
    assert offsets == [10, 11], offsets  # the good batch only, intact


def test_fuzz_gossip_survives_hostile_peer():
    """Garbage bytes on the gossip port and type-poisoned snapshots must
    not kill the node: the tick thread stays alive, healthy state stays
    intact, and a real peer still converges afterwards."""
    import socket as _socket
    import time as _time

    from tempo_tpu.modules.membership import Memberlist

    a = Memberlist("a", "ingester", gossip_interval_s=0.1,
                   suspect_timeout_s=5.0)
    try:
        host, port = a.gossip_addr.rsplit(":", 1)
        rng = random.Random(31)
        payloads = [
            b"\xff\xfe garbage\n",
            b"[]\n",
            b'"just-a-string"\n',
            b'{"members": []}\n',
            b'{"members": {"x": 42}}\n',
            b'{"members": {"x": {"id": "x", "role": null, '
            b'"gossip_addr": 9, "heartbeat": "NaN"}}}\n',
            json.dumps({"members": {"evil": {
                "id": "evil", "role": "ingester",
                "gossip_addr": "127.0.0.1:1", "heartbeat": [1, 2],
                "state": {"deep": "wrong"}}}}).encode() + b"\n",
            rng.randbytes(500) + b"\n",
        ]
        for p in payloads:
            with _socket.create_connection((host, int(port)), timeout=2) as s:
                s.sendall(p)
                try:
                    s.recv(4096)
                except OSError:
                    pass
        # hostile snapshots through merge() directly too (gossip-loop path)
        a.merge("nope")
        a.merge({"members": {"y": {"id": "y", "role": "ingester",
                                   "gossip_addr": "z", "heartbeat": None}}})
        _time.sleep(0.3)
        assert a._thread.is_alive(), "gossip tick thread died"
        assert a.ring("ingester").healthy_count() == 1  # just ourselves

        # a REAL peer still joins and converges after the abuse
        b = Memberlist("b", "ingester", join=[a.gossip_addr],
                       gossip_interval_s=0.1, suspect_timeout_s=5.0)
        try:
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline:
                if a.ring("ingester").healthy_count() == 2:
                    break
                _time.sleep(0.05)
            assert a.ring("ingester").healthy_count() == 2
        finally:
            b.shutdown()
    finally:
        a.shutdown()


def test_gossip_rejects_identity_forgery_and_poison_types():
    """The review's thread-killers: json Infinity heartbeats, unhashable
    ids, forged self-records, unknown states, and poisoned addresses are
    all skipped — and the node keeps advertising ITSELF as ACTIVE."""
    from tempo_tpu.modules.membership import Memberlist

    a = Memberlist("me", "ingester", gossip_interval_s=5, suspect_timeout_s=5)
    try:
        a.merge({"members": {
            "x1": {"id": "x1", "role": "r", "gossip_addr": "h:1",
                   "heartbeat": float("inf")},
            "x2": {"id": [1, 2], "role": "r", "gossip_addr": "h:1",
                   "heartbeat": 1},
            "x3": {"id": "me", "role": "ingester", "gossip_addr": "h:1",
                   "heartbeat": 999, "state": "LEFT"},   # forged self
            "x4": {"id": "x4", "role": "r", "gossip_addr": "h:1",
                   "heartbeat": 1, "state": "ZOMBIE"},
            "x5": {"id": "x5", "role": "r", "gossip_addr": "h:1",
                   "grpc_addr": {"deep": "wrong"}, "heartbeat": 1},
            "ok": {"id": "ok", "role": "r", "gossip_addr": "h:2",
                   "heartbeat": 1},
        }})
        ids = {m.id for m in a.members(alive_only=False)}
        assert ids == {"me", "ok"}, ids
        me = [m for m in a.members(alive_only=False) if m.id == "me"][0]
        assert me.state == "ACTIVE"   # forgery did not mark us LEFT
        # snapshot must be buildable (no unhashable ids slipped in)
        a._snapshot()
    finally:
        a.shutdown()


def test_fuzz_dns_response_parse():
    """Hostile DNS responses (the seed-resolution path feeding the gossip
    thread) must fail as ValueError only — the class its callers catch."""
    from tempo_tpu.utils.dns import encode_query, parse_response

    rng = random.Random(41)
    q = encode_query("seed.example.com", 1, txid=0x1234)
    for payload in _mutations(q + rng.randbytes(64), rng, n=40):
        try:
            parse_response(payload, txid=0x1234)
        except ValueError:
            pass
    # compression-pointer loop specifically (classic DNS parser bomb)
    bomb = bytearray(q)
    bomb[2] |= 0x80  # response flag
    bomb += b"\xc0\x0c\x00\x01\x00\x01\x00\x00\x00\x3c\x00\x04\x7f\x00\x00\x01"
    loop = bytes(bomb[:12]) + b"\xc0\x0c" + bytes(bomb[14:])
    try:
        parse_response(bytes(loop), txid=0x1234)
    except ValueError:
        pass
