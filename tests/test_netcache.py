"""Memcached/Redis cache clients against in-process fake servers speaking
the real wire protocols (reference pkg/cache memcached/redis + background)."""

import socketserver
import threading

import pytest

from tempo_tpu.backend import MockBackend
from tempo_tpu.backend.cache import CachedBackend
from tempo_tpu.backend.netcache import (
    BackgroundCache, MemcachedCache, RedisCache, jump_hash, open_cache,
)


class _FakeMemcached(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _MemcachedHandler(socketserver.StreamRequestHandler):
    def handle(self):
        while True:
            line = self.rfile.readline()
            if not line:
                return
            parts = line.strip().split()
            if not parts:
                continue
            if parts[0] == b"set":
                n = int(parts[4])
                data = self.rfile.read(n)
                self.rfile.read(2)
                self.server.data[parts[1].decode()] = data
                self.wfile.write(b"STORED\r\n")
            elif parts[0] == b"get":
                key = parts[1].decode()
                val = self.server.data.get(key)
                if val is not None:
                    self.wfile.write(
                        b"VALUE %s 0 %d\r\n%s\r\n" % (key.encode(), len(val), val))
                self.wfile.write(b"END\r\n")
            else:
                self.wfile.write(b"ERROR\r\n")


class _RedisHandler(socketserver.StreamRequestHandler):
    def _read_cmd(self):
        line = self.rfile.readline()
        if not line or not line.startswith(b"*"):
            return None
        n = int(line[1:].strip())
        args = []
        for _ in range(n):
            ln = int(self.rfile.readline()[1:].strip())
            args.append(self.rfile.read(ln))
            self.rfile.read(2)
        return args

    def handle(self):
        while True:
            args = self._read_cmd()
            if args is None:
                return
            cmd = args[0].upper()
            if cmd == b"SET":
                self.server.data[args[1].decode()] = args[2]
                self.wfile.write(b"+OK\r\n")
            elif cmd == b"GET":
                val = self.server.data.get(args[1].decode())
                if val is None:
                    self.wfile.write(b"$-1\r\n")
                else:
                    self.wfile.write(b"$%d\r\n%s\r\n" % (len(val), val))
            else:
                self.wfile.write(b"-ERR unknown\r\n")


def _start(handler):
    srv = _FakeMemcached(("127.0.0.1", 0), handler)
    srv.data = {}
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


@pytest.fixture
def memcached():
    srv, port = _start(_MemcachedHandler)
    yield srv, port
    srv.shutdown()


@pytest.fixture
def redis():
    srv, port = _start(_RedisHandler)
    yield srv, port
    srv.shutdown()


def test_memcached_roundtrip(memcached):
    srv, port = memcached
    c = MemcachedCache(f"127.0.0.1:{port}")
    c.store("k1", b"v1")
    assert c.fetch("k1") == b"v1"
    assert c.fetch("missing") is None
    c.store("k1", b"v2" * 1000)
    assert c.fetch("k1") == b"v2" * 1000
    c.stop()


def test_redis_roundtrip(redis):
    srv, port = redis
    c = RedisCache(f"127.0.0.1:{port}", ttl_s=60)
    c.store("k1", b"\x00binary\xff")
    assert c.fetch("k1") == b"\x00binary\xff"
    assert c.fetch("missing") is None
    c.stop()


def test_jump_hash_distribution_and_stability():
    # keys spread over buckets, and adding a bucket moves only ~1/n of them
    before = {k: jump_hash(k * 2654435761, 4) for k in range(2000)}
    assert len(set(before.values())) == 4
    after = {k: jump_hash(k * 2654435761, 5) for k in range(2000)}
    moved = sum(1 for k in before if before[k] != after[k])
    assert 0 < moved < 2000 * 0.35  # ≈1/5 expected
    assert all(after[k] == 4 for k in before if before[k] != after[k])


def test_sharding_across_two_servers(memcached):
    srv1, port1 = memcached
    srv2, port2 = _start(_MemcachedHandler)
    try:
        c = MemcachedCache([f"127.0.0.1:{port1}", f"127.0.0.1:{port2}"])
        for i in range(50):
            c.store(f"key-{i}", b"x")
        assert srv1.data and srv2.data  # both shards took writes
        assert len(srv1.data) + len(srv2.data) == 50
        for i in range(50):
            assert c.fetch(f"key-{i}") == b"x"
        c.stop()
    finally:
        srv2.shutdown()


def test_down_server_degrades_to_miss():
    c = MemcachedCache("127.0.0.1:1")  # nothing listens
    c.store("k", b"v")                 # no raise
    assert c.fetch("k") is None
    c.stop()


def test_background_write_behind(memcached):
    srv, port = memcached
    bg = BackgroundCache(MemcachedCache(f"127.0.0.1:{port}"), workers=1)
    for i in range(20):
        bg.store(f"k{i}", b"v")
    bg.flush()
    assert bg.fetch("k0") == b"v"
    assert len(srv.data) == 20
    bg.stop()


def test_cached_backend_over_memcached(memcached):
    srv, port = memcached
    be = MockBackend()
    cached = CachedBackend(be, cache=MemcachedCache(f"127.0.0.1:{port}"))
    cached.write("t1", "b1", "index", b"index-bytes")
    assert srv.data  # write-through populated the network cache
    # delete from the inner store: a cached read still serves
    be.delete("t1", "b1", "index")
    assert cached.read("t1", "b1", "index") == b"index-bytes"


def test_open_cache_factory(memcached):
    _, port = memcached
    c = open_cache({"cache": "memcached",
                    "memcached": {"servers": f"127.0.0.1:{port}",
                                  "background": {"enabled": True}}})
    c.store("k", b"v")
    c.flush()
    assert c.fetch("k") == b"v"
    c.stop()
    assert open_cache({"cache": "none"}) is None
    lru = open_cache({"cache": "lru"})
    lru.store("a", b"b")
    assert lru.fetch("a") == b"b"


def test_unsafe_keys_are_hashed(memcached):
    from tempo_tpu.backend.netcache import safe_cache_key

    srv, port = memcached
    c = MemcachedCache(f"127.0.0.1:{port}")
    # tenant IDs come verbatim from headers: injection/whitespace/overlong
    evil = "t 0 0 5\r\nset victim/blk/index 0 0 4\r\nevil/blk/index"
    c.store(evil, b"payload")
    assert c.fetch(evil) == b"payload"
    assert "victim/blk/index" not in srv.data  # no injected command ran
    long_key = "t/" + "x" * 300
    c.store(long_key, b"v")
    assert c.fetch(long_key) == b"v"
    assert safe_cache_key("plain/key") == "plain/key"  # safe keys untouched
    c.stop()


def test_hostile_value_lengths_degrade_to_miss():
    """A cache server declaring an absurd value length must count as a
    wire error (miss), not drive a giant allocation."""
    import socketserver
    import threading

    from tempo_tpu.backend.netcache import MemcachedCache, RedisCache

    class EvilMemcached(socketserver.StreamRequestHandler):
        def handle(self):
            self.rfile.readline()
            self.wfile.write(b"VALUE k 0 99999999999999\r\n")

    class EvilRedis(socketserver.StreamRequestHandler):
        def handle(self):
            self.rfile.read(1)
            self.wfile.write(b"$99999999999999\r\n")

    for cls, handler in ((MemcachedCache, EvilMemcached),
                         (RedisCache, EvilRedis)):
        srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), handler)
        srv.daemon_threads = True
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            c = cls([f"127.0.0.1:{srv.server_address[1]}"])
            assert c.fetch("k") is None  # degraded, no MemoryError
        finally:
            srv.shutdown()
            srv.server_close()
